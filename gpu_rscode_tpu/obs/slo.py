"""Per-tenant SLO objectives — ``RS_SLO`` parsing, rolling attainment,
burn rates.

The ROADMAP's scheduler rungs (SLO classes, priorities, preemption,
quotas) all need the same substrate first: a definition of "meeting the
objective" that is measured, per tenant, from real request outcomes.
This module is that substrate (docs/SERVE.md "Request lifecycle"):

* **Spec** — ``RS_SLO`` holds ``;``-separated objectives, each
  ``TENANT:OP:KEY=VAL[,KEY=VAL...]``::

      RS_SLO='default:encode:p99=250ms,avail=99.9;*:decode:p99=1s'

  ``TENANT``/``OP`` may be ``*`` (any).  Keys: ``p50``/``p90``/``p99``
  with a duration value (``250ms``, ``0.25s``, bare number = ms) and
  ``avail`` with a percentage.  The most specific objective wins per
  (tenant, op): exact tenant+op, then exact tenant, then exact op, then
  ``*:*``.
* **SLIs** — per matched request: *latency* (request wall, admission to
  response, against each percentile target: a ``p99=250ms`` objective
  means >= 99 % of requests complete within 250 ms) and *availability*
  (HTTP 200; rejections and errors both burn the availability budget —
  a 429 is the daemon refusing work it was offered).
* **Rolling multi-window attainment + burn rate** — events are kept in
  per-cell deques and evaluated over ``RS_SLO_WINDOWS`` (default
  ``60,300,3600`` seconds).  Burn rate is the SRE convention: the
  fraction of the error budget consumed per unit of budget —
  ``bad_fraction / allowed_fraction`` — so ``1.0`` means exactly on
  budget, ``> 1`` means the objective fails if the window's rate
  holds.
* **Surfaces** — ``rs_slo_requests_total{tenant,op,verdict}`` counts
  every matched request; :meth:`SLOEngine.export_gauges` refreshes
  ``rs_slo_attainment`` / ``rs_slo_burn_rate{tenant,op,objective,
  window}`` gauges (the daemon does this on every ``/metrics`` scrape
  and ``GET /slo``); ``rs slo`` renders the same report from a live
  daemon (``--url``) or offline from ``kind=rs_request`` ledger records
  (``--runlog``).

Import cost: stdlib only (no jax, no numpy).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

from . import metrics as _metrics

DEFAULT_WINDOWS = (60.0, 300.0, 3600.0)

# Bounded per-cell history: at most this many events are consulted per
# (tenant, op) — a daemon serving far more than this inside its largest
# window reports on the most recent slice (the cap is noted in /slo).
MAX_EVENTS_PER_CELL = 8192

_QUANTILE_KEYS = {"p50": 0.50, "p90": 0.90, "p99": 0.99}


class SLOSpecError(ValueError):
    """``RS_SLO`` (or ``--slo``) did not parse."""


class Objective:
    """One parsed objective row: who it matches and what it demands."""

    __slots__ = ("tenant", "op", "latency", "avail", "spec")

    def __init__(self, tenant: str, op: str,
                 latency: dict[float, float], avail: float | None,
                 spec: str):
        self.tenant = tenant      # tenant name or "*"
        self.op = op              # op name or "*"
        self.latency = latency    # {quantile: threshold_seconds}
        self.avail = avail        # e.g. 99.9 (percent) or None
        self.spec = spec          # the original token (reports echo it)

    def matches(self, tenant: str, op: str) -> bool:
        return (self.tenant in ("*", tenant)
                and self.op in ("*", op))

    def specificity(self) -> int:
        return (self.tenant != "*") * 2 + (self.op != "*")

    def describe(self) -> dict:
        return {
            "tenant": self.tenant,
            "op": self.op,
            "latency": {f"p{int(q * 100)}": thr
                        for q, thr in sorted(self.latency.items())},
            "avail": self.avail,
            "spec": self.spec,
        }


def _parse_duration_s(text: str, where: str) -> float:
    t = text.strip().lower()
    try:
        if t.endswith("ms"):
            return float(t[:-2]) / 1e3
        if t.endswith("s"):
            return float(t[:-1])
        return float(t) / 1e3  # bare number: milliseconds
    except ValueError:
        raise SLOSpecError(
            f"{where}: bad duration {text!r} (want e.g. 250ms or 0.25s)"
        ) from None


def parse_slo(spec: str | None) -> list[Objective]:
    """Parse an ``RS_SLO`` spec into objectives (empty list for
    None/blank).  Raises :class:`SLOSpecError` with the offending token
    on any malformed piece — a half-understood objective must not
    silently gate on the wrong numbers."""
    out: list[Objective] = []
    if not spec or not spec.strip():
        return out
    for token in spec.split(";"):
        token = token.strip()
        if not token:
            continue
        parts = token.split(":", 2)
        if len(parts) != 3:
            raise SLOSpecError(
                f"objective {token!r}: want TENANT:OP:KEY=VAL[,...]")
        tenant, op, body = (p.strip() for p in parts)
        if not tenant or not op or not body:
            raise SLOSpecError(
                f"objective {token!r}: empty tenant/op/targets")
        latency: dict[float, float] = {}
        avail: float | None = None
        for kv in body.split(","):
            key, sep, val = kv.partition("=")
            key = key.strip().lower()
            if not sep:
                raise SLOSpecError(
                    f"objective {token!r}: target {kv!r} needs KEY=VAL")
            if key in _QUANTILE_KEYS:
                latency[_QUANTILE_KEYS[key]] = _parse_duration_s(
                    val, f"objective {token!r}")
            elif key == "avail":
                try:
                    avail = float(val)
                except ValueError:
                    raise SLOSpecError(
                        f"objective {token!r}: bad avail {val!r}"
                    ) from None
                if not 0 < avail < 100:
                    raise SLOSpecError(
                        f"objective {token!r}: avail must be in (0, 100)"
                    )
            else:
                raise SLOSpecError(
                    f"objective {token!r}: unknown target {key!r} "
                    f"(want p50/p90/p99/avail)")
        if not latency and avail is None:
            raise SLOSpecError(f"objective {token!r}: no targets")
        out.append(Objective(tenant, op, latency, avail, token))
    return out


def windows() -> tuple[float, ...]:
    """``RS_SLO_WINDOWS``: comma-separated rolling window lengths in
    seconds (default ``60,300,3600``)."""
    raw = os.environ.get("RS_SLO_WINDOWS")
    if not raw:
        return DEFAULT_WINDOWS
    try:
        vals = tuple(sorted(float(v) for v in raw.split(",") if v.strip()))
    except ValueError:
        return DEFAULT_WINDOWS
    return tuple(v for v in vals if v > 0) or DEFAULT_WINDOWS


def configured() -> bool:
    """Whether any SLO objectives are configured via the environment."""
    return bool(os.environ.get("RS_SLO", "").strip())


class SLOEngine:
    """Rolling per-(tenant, op) SLO evaluation over a bounded event
    history.  Thread-safe: handler threads :meth:`observe`, scrape
    threads :meth:`report`."""

    def __init__(self, spec: str | None = None,
                 window_lengths: tuple[float, ...] | None = None):
        self.objectives = parse_slo(
            os.environ.get("RS_SLO") if spec is None else spec)
        self.windows = tuple(window_lengths) if window_lengths else \
            windows()
        self._lock = threading.Lock()
        # (tenant, op) -> deque[(t, wall_s, ok)]
        self._events: dict[tuple, deque] = {}

    def match(self, tenant: str, op: str) -> Objective | None:
        best = None
        for obj in self.objectives:
            if obj.matches(tenant, op) and (
                    best is None
                    or obj.specificity() > best.specificity()):
                best = obj
        return best

    def observe(self, tenant: str, op: str, wall_s: float, ok: bool,
                t: float | None = None) -> None:
        """Record one finished request (``wall_s`` = admission to
        response; ``ok`` = HTTP 200).  Requests no objective matches are
        ignored — the engine costs nothing for unconfigured traffic."""
        obj = self.match(tenant, op)
        if obj is None:
            return
        t = time.monotonic() if t is None else t
        with self._lock:
            cell = self._events.get((tenant, op))
            if cell is None:
                cell = self._events[(tenant, op)] = deque(
                    maxlen=MAX_EVENTS_PER_CELL)
            cell.append((t, float(wall_s), bool(ok)))
        verdict = "good"
        if not ok:
            verdict = "error"
        elif any(wall_s > thr for thr in obj.latency.values()):
            verdict = "slow"
        _metrics.counter(
            "rs_slo_requests_total",
            "requests matched by an SLO objective, by per-request verdict",
        ).labels(tenant=tenant, op=op, verdict=verdict).inc()

    # -- reporting -----------------------------------------------------------

    @staticmethod
    def _window_rates(events: list[tuple], obj: Objective) -> dict:
        """Attainment + burn for one window's event slice.

        Latency SLIs are computed over SERVED (ok) requests only: a
        window of sub-millisecond 429s must not mask the one successful
        request that blew the target (rejections already burn the
        availability budget — counting their walls as latency-good
        would let an overloaded daemon never fail its latency SLO).  A
        window with traffic but zero served requests reports the
        latency objective with ``attainment: None`` / ``met: None`` —
        no latency evidence is not a latency pass."""
        total = len(events)
        out: dict = {"total": total, "objectives": {}}
        if not total:
            return out
        walls = sorted(e[1] for e in events if e[2])  # served only
        oks = len(walls)
        out["served"] = oks
        for q, thr in sorted(obj.latency.items()):
            entry: dict = {"target_s": thr, "target_fraction": q}
            if oks:
                good = sum(1 for w in walls if w <= thr)
                frac = good / oks
                allowed = 1.0 - q
                burn = ((1.0 - frac) / allowed) if allowed > 0 else None
                entry.update(
                    attainment=round(frac, 6),
                    burn_rate=round(burn, 4) if burn is not None
                    else None,
                    met=frac >= q,
                )
            else:
                entry.update(attainment=None, burn_rate=None, met=None)
            out["objectives"][f"p{int(q * 100)}"] = entry
        if obj.avail is not None:
            frac = oks / total
            target = obj.avail / 100.0
            allowed = 1.0 - target
            burn = ((1.0 - frac) / allowed) if allowed > 0 else None
            out["objectives"]["avail"] = {
                "target_fraction": target,
                "attainment": round(frac, 6),
                "burn_rate": round(burn, 4) if burn is not None else None,
                "met": frac >= target,
            }
        return out

    def report(self, now: float | None = None) -> dict:
        """The full SLO document (the ``GET /slo`` payload): per matched
        (tenant, op) cell, attainment and burn rate over every rolling
        window, plus the parsed objective table."""
        now = time.monotonic() if now is None else now
        with self._lock:
            cells = {key: list(dq) for key, dq in self._events.items()}
        rows = []
        for (tenant, op), events in sorted(cells.items()):
            obj = self.match(tenant, op)
            if obj is None:  # objective removed after traffic flowed
                continue
            row = {
                "tenant": tenant, "op": op, "objective": obj.describe(),
                "history_capped": len(events) >= MAX_EVENTS_PER_CELL,
                "windows": {},
            }
            for w in self.windows:
                cut = now - w
                row["windows"][str(int(w))] = self._window_rates(
                    [e for e in events if e[0] >= cut], obj)
            rows.append(row)
        return {
            "kind": "rs_slo",
            "configured": bool(self.objectives),
            "objectives": [o.describe() for o in self.objectives],
            "windows_s": list(self.windows),
            "cells": rows,
        }

    def export_gauges(self, now: float | None = None) -> dict:
        """Refresh the ``rs_slo_attainment`` / ``rs_slo_burn_rate``
        gauges from a fresh report (rolling windows age out even with no
        new traffic, so gauges are recomputed at scrape time, not at
        observe time).  Returns the report it exported."""
        report = self.report(now)
        att = _metrics.gauge(
            "rs_slo_attainment",
            "fraction of requests meeting the objective, rolling window")
        burn = _metrics.gauge(
            "rs_slo_burn_rate",
            "error-budget burn rate (1.0 = exactly on budget), rolling "
            "window")
        for row in report["cells"]:
            for win, rates in row["windows"].items():
                for name, vals in rates.get("objectives", {}).items():
                    labels = dict(tenant=row["tenant"], op=row["op"],
                                  objective=name, window=win)
                    if vals["attainment"] is not None:
                        att.labels(**labels).set(vals["attainment"])
                    if vals["burn_rate"] is not None:
                        burn.labels(**labels).set(vals["burn_rate"])
        return report


def breaches(report: dict) -> list[dict]:
    """Every (cell, window, objective) in ``report`` currently missing
    its target — the gate `rs loadgen --slo` and `rs slo --check` fail
    on.  Empty windows never breach (no traffic is not a violation)."""
    out = []
    for row in report.get("cells", []):
        for win, rates in row.get("windows", {}).items():
            for name, vals in rates.get("objectives", {}).items():
                if vals.get("met") is False:
                    out.append({
                        "tenant": row["tenant"], "op": row["op"],
                        "window": win, "objective": name,
                        "attainment": vals["attainment"],
                        "burn_rate": vals["burn_rate"],
                    })
    return out


def render(report: dict) -> str:
    """Human-readable SLO report: one line per (cell, window,
    objective)."""
    lines = []
    if not report.get("configured"):
        lines.append("slo: no objectives configured (set RS_SLO)")
    else:
        specs = ", ".join(o["spec"] for o in report["objectives"])
        lines.append(f"slo objectives: {specs}")
    for row in report.get("cells", []):
        for win, rates in sorted(row["windows"].items(),
                                 key=lambda kv: float(kv[0])):
            total = rates["total"]
            if not total:
                continue
            for name, vals in rates["objectives"].items():
                if vals["met"] is None:  # traffic but nothing served
                    lines.append(
                        f"[--] {row['tenant']}/{row['op']} {name} "
                        f"@{win}s: no served requests "
                        f"({total} total, all rejected/failed)")
                    continue
                mark = "ok" if vals["met"] else "!!"
                burn = vals["burn_rate"]
                lines.append(
                    f"[{mark}] {row['tenant']}/{row['op']} {name} "
                    f"@{win}s: attainment "
                    f"{vals['attainment'] * 100:.3f}% "
                    f"(target {vals['target_fraction'] * 100:g}%), "
                    f"burn {burn if burn is not None else '-'} "
                    f"over {total} requests")
    if len(lines) == 1 and report.get("configured"):
        lines.append("(no matched traffic yet)")
    return "\n".join(lines)


def replay_ledger(path: str, spec: str | None = None) -> dict:
    """Offline report: feed ``kind=rs_request`` ledger records (the
    reqtrace wide events, docs/OBSERVABILITY.md) through a fresh engine.
    Windows are evaluated relative to the newest record's wall-clock
    ``ts``."""
    from . import runlog as _runlog

    engine = SLOEngine(spec=spec)
    records = [r for r in _runlog.read_records(path)
               if r.get("kind") == "rs_request"]
    last_ts = 0.0
    for rec in records:
        ts = float(rec.get("ts") or 0.0)
        last_ts = max(last_ts, ts)
        wall = rec.get("wall_s")
        if not isinstance(wall, (int, float)):
            continue
        engine.observe(rec.get("tenant") or "default",
                       rec.get("op") or "?", float(wall),
                       ok=rec.get("outcome") == "ok", t=ts)
    report = engine.report(now=last_ts)
    report["records"] = len(records)
    report["source"] = path
    return report


def main(argv=None) -> int:
    """The ``rs slo`` subcommand."""
    import argparse
    import urllib.request

    ap = argparse.ArgumentParser(
        prog="rs slo",
        description="Per-tenant SLO attainment + burn rates: scrape a "
        "live daemon's GET /slo, or replay kind=rs_request ledger "
        "records offline (docs/SERVE.md 'Request lifecycle').",
    )
    ap.add_argument("--url", default=None,
                    help="daemon base URL (e.g. http://127.0.0.1:9470)")
    ap.add_argument("--runlog", default=None,
                    help="offline: replay rs_request records from this "
                    "ledger (default $RS_RUNLOG when --url is absent)")
    ap.add_argument("--slo", metavar="SPEC", default=None,
                    help="objective spec for --runlog replay (default "
                    "$RS_SLO)")
    ap.add_argument("--check", action="store_true",
                    help="exit 4 when any window misses its objective "
                    "(the CI/cron gate form)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report document as JSON")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    if args.url:
        try:
            with urllib.request.urlopen(
                    args.url.rstrip("/") + "/slo", timeout=10) as resp:
                report = json.loads(resp.read())
        except Exception as e:
            print(f"rs slo: cannot scrape {args.url}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 1
    else:
        ledger = args.runlog or os.environ.get("RS_RUNLOG")
        if not ledger:
            print("rs slo: pass --url, or --runlog/RS_RUNLOG for an "
                  "offline replay", file=sys.stderr)
            return 2
        try:
            report = replay_ledger(ledger, spec=args.slo)
        except SLOSpecError as e:
            print(f"rs slo: bad SLO spec: {e}", file=sys.stderr)
            return 2
        except OSError as e:
            print(f"rs slo: cannot read {ledger!r}: {e}", file=sys.stderr)
            return 1

    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    if args.check:
        bad = breaches(report)
        if bad:
            for b in bad:
                print(f"rs slo: BREACH {b['tenant']}/{b['op']} "
                      f"{b['objective']} @{b['window']}s: attainment "
                      f"{b['attainment']}, burn {b['burn_rate']}",
                      file=sys.stderr)
            return 4
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
