"""Streaming tail-latency quantiles — fixed-size, mergeable estimators.

The histogram API (obs/metrics.py) answers "how is latency distributed
across these fixed bucket edges"; the planned ``rs serve`` workload and
the roofline attribution layer need *percentiles* — p50/p90/p99/max of
per-segment dispatch, writer-lane drain and file-op wall — without
guessing bucket edges up front.  This module provides the estimator the
:class:`~.metrics.Quantile` metric type wraps:

* **Fixed-size reservoir** (`Vitter's algorithm R`): O(cap) memory per
  series regardless of stream length; while the stream is shorter than
  the reservoir the sample is *exact* (every value retained).  ``sum``,
  ``count``, ``min`` and ``max`` are tracked exactly on the side, so the
  headline ``max`` (the tail the percentile family exists for) is never
  an estimate.
* **Deterministic** — replacement decisions come from a PRNG seeded per
  estimator, so the same observation stream always yields the same
  state (the property tests replay streams).
* **Mergeable** — :func:`merge_states` folds N per-process estimator
  states into one: exact concatenation while the union fits the cap,
  count-weighted sampling beyond it (the multi-host contract
  obs/aggregate.py applies to ``--metrics-json`` parts, mirroring how
  counters sum and histograms add bucket-wise).

Import cost: stdlib only (no jax, no numpy) — same constraint as the
rest of ``obs/``.
"""

from __future__ import annotations

import random

# 512 samples bound the p99 estimate's standard error near 0.4% of rank
# while keeping a snapshot's reservoir list JSON-friendly.
DEFAULT_RESERVOIR = 512

# The percentile family every surface reports (rs stats, /metrics,
# rs analyze): median, tail, deep tail.
DEFAULT_QUANTILES = (0.5, 0.9, 0.99)


def quantile_of(values, q: float) -> float | None:
    """Linear-interpolated quantile of a sequence (None when empty).

    The one quantile definition shared by the estimator, the aggregator
    and ``rs history`` — two surfaces disagreeing about interpolation
    would report different p99s for the same data.
    """
    vals = sorted(values)
    if not vals:
        return None
    if len(vals) == 1:
        return float(vals[0])
    pos = (len(vals) - 1) * min(max(q, 0.0), 1.0)
    lo = int(pos)
    frac = pos - lo
    if frac == 0.0:
        return float(vals[lo])
    return float(vals[lo] + (vals[lo + 1] - vals[lo]) * frac)


class QuantileEstimator:
    """One streaming quantile series: reservoir + exact count/sum/min/max."""

    __slots__ = ("cap", "count", "sum", "min", "max", "reservoir", "_rng")

    def __init__(self, cap: int = DEFAULT_RESERVOIR, _seed: int = 0x5EED):
        if cap < 1:
            raise ValueError(f"reservoir cap must be >= 1, got {cap}")
        self.cap = cap
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self.reservoir: list[float] = []
        # Seeded per estimator: replacement decisions are a pure function
        # of the observation sequence, so tests (and re-runs) reproduce.
        self._rng = random.Random(_seed)

    def observe(self, v) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if len(self.reservoir) < self.cap:
            self.reservoir.append(v)
            return
        # Algorithm R: keep each of the count seen so far with equal
        # probability cap/count.
        j = self._rng.randrange(self.count)
        if j < self.cap:
            self.reservoir[j] = v

    def quantile(self, q: float) -> float | None:
        return quantile_of(self.reservoir, q)

    def quantiles(self, qs=DEFAULT_QUANTILES) -> dict:
        """``{"0.5": v, ...}`` — string keys, JSON/Prometheus-ready."""
        return {repr(float(q)): self.quantile(q) for q in qs}

    def state(self) -> dict:
        """JSON-ready estimator state (what metric snapshots embed and
        :func:`merge_states` consumes)."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "cap": self.cap,
            "reservoir": list(self.reservoir),
        }


def merge_states(states: list[dict], cap: int | None = None) -> dict:
    """Fold N estimator states into one (the multi-host merge).

    Exact while the union of reservoirs fits ``cap`` (each part whose
    ``count`` <= its own cap carries every value it saw).  Beyond that,
    parts are down-sampled *count-weighted*: a part that observed 10x the
    events contributes ~10x the samples, so the merged reservoir
    approximates the distribution a single process observing every event
    would have sampled.  ``count``/``sum``/``min``/``max`` merge exactly.
    Deterministic: the sampling PRNG is seeded from the merged counts.
    """
    states = [s for s in states if isinstance(s, dict)]
    if not states:
        return QuantileEstimator().state()
    cap = cap or max(int(s.get("cap") or DEFAULT_RESERVOIR) for s in states)
    count = sum(int(s.get("count") or 0) for s in states)
    total_sum = sum(float(s.get("sum") or 0.0) for s in states)
    mins = [s["min"] for s in states if s.get("min") is not None]
    maxs = [s["max"] for s in states if s.get("max") is not None]
    pooled: list[float] = []
    weights: list[float] = []
    for s in states:
        res = [float(v) for v in (s.get("reservoir") or [])]
        if not res:
            continue
        # Each retained sample stands for count/len(reservoir) events.
        w = max(1.0, float(s.get("count") or len(res)) / len(res))
        pooled.extend(res)
        weights.extend([w] * len(res))
    if len(pooled) > cap:
        # Efraimidis-Spirakis A-Res: weighted sample without replacement —
        # keep the cap items with the largest u^(1/w) keys.
        rng = random.Random(count ^ 0xA6E5)
        keyed = sorted(
            (rng.random() ** (1.0 / w), v) for w, v in zip(weights, pooled)
        )
        pooled = [v for _, v in keyed[-cap:]]
    return {
        "count": count,
        "sum": total_sum,
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "cap": cap,
        "reservoir": pooled,
    }


def state_quantiles(state: dict, qs=DEFAULT_QUANTILES) -> dict:
    """Quantile family of a (possibly merged) estimator state."""
    res = state.get("reservoir") or []
    return {repr(float(q)): quantile_of(res, q) for q in qs}
