"""`rs doctor` — one-shot environment diagnostic.

Every support thread for this system starts with the same questions:
which backend is actually serving, is the native library built, which
RS_* knobs are set, is the ledger writable, is anything scraping the
metrics endpoint, and is the roofline calibration `rs analyze` depends
on still fresh?  This module answers them in one run, human-readable or
``--json`` (a schema-stable document — tests pin the section keys, so
fleet tooling can depend on them).

Sections:

* ``python`` / ``jax`` — interpreter, jax version, default backend,
  local device platforms/counts (degrading to the import error when no
  backend initialises).
* ``native`` — native C++ library presence, source digest, build error
  if any.
* ``mesh`` — local device count, ``jax.shard_map`` availability (the
  carried mesh-failure set's signature), forced-host-device flags.
* ``env`` — every ``RS_*`` knob currently set (the knobs are read per
  call across the codebase, so this is the live configuration).
* ``update`` — delta-update/append capability (docs/UPDATE.md):
  supported layouts, crash-safety machinery, CRC fix-up mode.
* ``strategies`` — GEMM-strategy capability (docs/XOR.md): per-backend
  ``auto`` candidates and verdict (the tune.py autotuner), plus cached
  XOR-schedule stats (term counts before/after CSE).
* ``ledger`` — RS_RUNLOG presence, record count, writability.
* ``metrics_endpoint`` — RS_METRICS_PORT reachability (one local HTTP
  probe of ``/healthz``).
* ``serve`` — the resident daemon (docs/SERVE.md): configured port and
  queue/batch knobs, plus a live ``/healthz`` probe of a running
  daemon (queue depth, draining state).
* ``slo`` — per-tenant SLO objectives (``RS_SLO``, obs/slo.py): the
  parsed objective table, rolling-window config, and — when a daemon
  is configured and probing is on — its live ``/slo`` breach summary.
* ``roofline`` — per-host calibration from the ledger and its age vs
  ``RS_ROOFLINE_MAX_AGE_S`` (obs/attrib.py).

Module import cost: stdlib only; jax loads lazily inside
:func:`collect`.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import socket
import sys
import time

from . import attrib as _attrib, runlog as _runlog

SCHEMA_VERSION = 1

# The --json document's stable surface (pinned by tests): these keys are
# always present, whatever the environment looks like.
SECTIONS = ("python", "jax", "native", "mesh", "env", "decoder", "update",
            "store", "strategies", "ledger", "metrics_endpoint", "serve",
            "slo", "roofline", "health", "perf", "maint")


def _jax_section() -> dict:
    out: dict = {"importable": False, "version": None, "backend": None,
                 "devices": [], "device_count": 0, "error": None}
    try:
        import jax

        out["importable"] = True
        out["version"] = getattr(jax, "__version__", None)
        out["backend"] = jax.default_backend()
        devs = jax.local_devices()
        out["device_count"] = len(devs)
        out["devices"] = sorted({d.platform for d in devs})
    except Exception as e:  # backend init can fail any number of ways
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _native_section() -> dict:
    out: dict = {"available": False, "lib_path": None, "src_digest": None,
                 "error": None}
    try:
        from .. import native

        out["lib_path"] = getattr(native, "_SO", None)
        src = getattr(native, "_SRC", None)
        if src and os.path.exists(src):
            with open(src, "rb") as fp:
                out["src_digest"] = hashlib.sha256(
                    fp.read()
                ).hexdigest()[:12]
        out["available"] = native.available()
        if not out["available"]:
            out["error"] = "native toolchain unavailable (NumPy fallback)"
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _mesh_section(jax_info: dict) -> dict:
    out: dict = {
        "local_device_count": jax_info.get("device_count", 0),
        "shard_map_available": False,
        "forced_host_devices": None,
        "distributed_env": {},
    }
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" in xla_flags:
        out["forced_host_devices"] = xla_flags
    for var in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        if os.environ.get(var):
            out["distributed_env"][var] = os.environ[var]
    jax = sys.modules.get("jax")
    if jax is not None:
        # Resolved through the compat shim (parallel/_compat.py): old
        # jax pins serve jax.experimental.shard_map — the lookup that
        # carried the 14-test mesh failure set until it was shimmed
        # (docs/STATUS.md, ROADMAP item 4).
        try:
            from ..parallel._compat import shard_map_available

            out["shard_map_available"] = shard_map_available()
        except Exception:
            out["shard_map_available"] = hasattr(jax, "shard_map")
    return out


def _decoder_section() -> dict:
    """Decoder capability matrix (schema-stable): what this build can
    recover from.  ``erasure`` is the Vandermonde + Gauss-Jordan path the
    paper ships; ``locate`` is the gf_decode error-locating path (silent
    bitrot without CRCs — docs/RESILIENCE.md "Error location")."""
    out: dict = {
        "erasure": True,
        "locate": False,
        "supported_w": [8, 16],
        "syndrome_kernel": None,
        "locate_bound": "2*errors + erasures <= n - k per symbol column",
        "error": None,
    }
    try:
        from .. import gf_decode  # noqa: F401
        from ..codec import RSCodec

        out["locate"] = True
        out["syndrome_kernel"] = (
            "plan-cached GF-GEMM (codec.syndrome)"
            if hasattr(RSCodec, "syndrome") else None
        )
    except Exception as e:  # pragma: no cover - import-degraded env
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _update_section() -> dict:
    """Update/append capability matrix (schema-stable): whether this
    build can mutate archives in place (docs/UPDATE.md) and with which
    layouts/safety machinery."""
    out: dict = {
        "delta_update": False,
        "append": False,
        "layouts": [],
        "crash_safety": None,
        "crc_fixup": None,
        # Group-commit write combining (docs/UPDATE.md "Group commit"):
        # effective config + process-lifetime tallies, schema-stable.
        "group_commit": {
            "available": False,
            "window_max_edits": None,
            "groups": 0,
            "edits": 0,
            "bytes": 0,
            "max_group_seen": 0,
            "journal_fsyncs": 0,
            "metadata_commits": 0,
        },
        "error": None,
    }
    try:
        from ..update import apply_append, apply_update  # noqa: F401
        from ..update import group_stats as _group_stats

        out["delta_update"] = True
        out["append"] = True
        out["layouts"] = ["row", "interleaved"]
        out["crash_safety"] = (
            "undo journal + atomic generation-bumped .METADATA rewrite"
        )
        out["crc_fixup"] = "seekable crc32-combine (no full-chunk re-hash)"
        out["group_commit"].update(available=True, **_group_stats())
    except Exception as e:  # pragma: no cover - import-degraded env
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _store_section(root: str | None = None) -> dict:
    """Object-store façade health (docs/STORE.md): buckets probed
    read-only under ``root`` (``--root`` / ``RS_STORE_ROOT``) — objects,
    live/dead bytes, rolled-back index records pending a rewrite,
    pending compactions — plus the knob dump.  Schema-stable: every key
    present even with no root configured."""
    out: dict = {
        "root": None, "probed": False, "buckets": {},
        "objects": 0, "live_bytes": 0, "dead_bytes": 0,
        "pending_compactions": 0, "pending_drops": 0,
        "snapshots": 0, "segments": 0,
        "knobs": {}, "error": None,
    }
    try:
        from ..serve.objcache import cache_bytes_env
        from ..store import (compact_dead_frac, probe,
                             snapshot_keep_env, snapshot_records_env,
                             stripe_bytes_env)

        out["knobs"] = {
            "RS_STORE_STRIPE_BYTES": stripe_bytes_env(),
            "RS_STORE_COMPACT_DEAD_FRAC": compact_dead_frac(),
            "RS_STORE_SNAPSHOT_RECORDS": snapshot_records_env(),
            "RS_STORE_SNAPSHOT_KEEP": snapshot_keep_env(),
            "RS_OBJ_CACHE_BYTES": cache_bytes_env(),
            "RS_STORE_K": os.environ.get("RS_STORE_K"),
            "RS_STORE_P": os.environ.get("RS_STORE_P"),
        }
        root = root or os.environ.get("RS_STORE_ROOT")
        if not root:
            return out
        out["root"] = os.path.abspath(root)
        doc = probe(root)
        out["probed"] = True
        out["buckets"] = doc["buckets"]
        for b in doc["buckets"].values():
            if "error" in b:
                continue
            out["objects"] += b["objects"]
            out["live_bytes"] += b["live_bytes"]
            out["dead_bytes"] += b["dead_bytes"]
            out["pending_compactions"] += b["pending_compactions"]
            out["pending_drops"] += b["pending_drops"]
            out["snapshots"] += b.get("snapshots", 0)
            out["segments"] += b.get("segments", 0)
    except Exception as e:  # diagnostic must never crash
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _strategies_section() -> dict:
    """GEMM-strategy capability matrix (schema-stable): which strategies
    this build offers, what ``auto`` resolves to on this backend (the
    autotuner verdict — docs/XOR.md), the cached XOR-schedule stats
    (term counts before/after CSE) so plan-cache bloat is visible, the
    persistent schedule/autotune store facts, and the generation-keyed
    survivor-subset cache tallies."""
    out: dict = {
        "valid": [],
        "candidates": [],
        "auto": {"strategy": None, "mode": None, "source": None},
        "xor": {
            "supported_w": [8, 16],
            "cse_default": None,
            "schedules": [],
            "pipelines": 0,
            "opt": None,
        },
        "ring": {
            "supported_w": [8, 16],
            "schedules": [],
            "pipelines": 0,
            "store": {},
            "params": None,
        },
        "autotune_decisions": {},
        "store": {
            "path": None,
            "enabled": False,
            "entries": None,
            "hits": 0,
            "misses": 0,
            "stored": 0,
            "corrupt": 0,
            "built": 0,
            "ledger_autotune": 0,
        },
        "inverse_cache": {"entries": 0, "hits": 0, "misses": 0,
                          "stale": 0},
        "error": None,
    }
    try:
        from ..ops import xor_gemm as _xg
        from .. import tune as _tune

        out["valid"] = list(_tune.VALID_STRATEGIES)
        out["candidates"] = list(_tune.candidate_strategies())
        mode = _tune.mode()
        decisions = _tune.decisions()
        # The verdict an auto codec gets today, mirroring resolve_auto:
        # `off` mode ignores the cache; measured/ledger decisions are per
        # (k, p, w) class, so a unanimous winner reports with its source
        # and split winners fall back to the prior label with the
        # per-class table below telling the full story.
        winners = sorted({d["strategy"] for d in decisions.values()})
        sources = sorted({
            d.get("source") or "measured" for d in decisions.values()
        })
        if mode == "off" or not winners:
            auto = {"strategy": _tune.static_choice(), "source": "prior"}
        elif len(winners) == 1:
            auto = {
                "strategy": winners[0],
                "source": sources[0] if len(sources) == 1 else "mixed",
            }
        else:
            auto = {"strategy": _tune.static_choice(), "source": "mixed"}
        out["auto"] = dict(auto, mode=mode)
        scheds = _xg.schedule_stats()
        out["xor"]["cse_default"] = _xg._cse_enabled()
        out["xor"]["schedules"] = scheds
        out["xor"]["pipelines"] = len(_xg.pipeline_stats())
        # Schedule-optimizer pass facts (ops/xor_opt.py): the resolved
        # knob state plus per-pipeline stats — what the pass actually
        # did (nodes moved, tile choice, unpack split) per compiled
        # pipeline, xor and ring alike.
        from ..ops import ring_gemm as _rg
        from ..ops import xor_opt as _xopt

        out["xor"]["opt"] = {
            "enabled": _xopt.opt_enabled(),
            "tile_override": _xopt.tile_override(),
            "tile_budget_bytes": _xopt.tile_budget_bytes(),
            "pipelines": [
                {"digest": p_["digest"], **p_["opt"]}
                for p_ in _xg.pipeline_stats() if p_.get("opt")
            ] + [
                {"digest": p_["digest"], **p_["opt"]}
                for p_ in _rg.ring_pipeline_stats() if p_.get("opt")
            ],
        }
        out["ring"]["schedules"] = _rg.ring_schedule_stats()
        out["ring"]["pipelines"] = len(_rg.ring_pipeline_stats())
        out["ring"]["store"] = _rg.ring_store_stats(load=True)
        out["ring"]["params"] = _rg.ring_params(8)
        out["autotune_decisions"] = decisions
        # Persistent-store facts (docs/XOR.md "The persistent store"):
        # resolved path, on-disk schedule entries (load=True forces one
        # index read — doctor is a diagnostic, the parse is the point),
        # this process's hit/miss/stored/corrupt tallies, and how many
        # cached autotune verdicts came from the ledger.
        out["store"].update(_xg.store_stats(load=True))
        out["store"]["ledger_autotune"] = sum(
            1 for d in decisions.values() if d.get("source") == "ledger"
        )
        from ..api import subset_cache_stats

        out["inverse_cache"] = subset_cache_stats()
    except Exception as e:  # pragma: no cover - import-degraded env
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _ledger_section() -> tuple[dict, list[dict]]:
    """Ledger facts plus the parsed records — read ONCE and shared with
    the roofline section (a rotation-bound ledger is several MB; the
    one-shot diagnostic must not JSON-parse it twice)."""
    p = _runlog.path()
    records: list[dict] = []
    out: dict = {"path": p, "exists": False, "records": 0,
                 "damage_records": 0, "health_snapshots": 0,
                 "writable": None, "error": None}
    if not p:
        out["error"] = "RS_RUNLOG unset (no persistent run ledger)"
        return out, records
    out["exists"] = os.path.exists(p) or os.path.exists(p + ".1")
    if out["exists"]:
        try:
            records = _runlog.read_records(p)
            out["records"] = len(records)
            # Damage-plane volume (obs/health.py): how much of the
            # ledger is the durability event stream vs op history.
            out["damage_records"] = sum(
                1 for r in records if r.get("kind") == "rs_damage")
            out["health_snapshots"] = sum(
                1 for r in records
                if r.get("kind") == "rs_health_snapshot")
        except Exception as e:
            out["error"] = f"{type(e).__name__}: {e}"
    # Writability probe that MUTATES NOTHING: doctor diagnoses state, it
    # must not create the ledger file a later existence check would read
    # as "some operation wrote here".
    if os.path.exists(p):
        try:
            fd = os.open(p, os.O_RDWR | os.O_APPEND)
            os.close(fd)
            out["writable"] = True
        except OSError as e:
            out["writable"] = False
            out["error"] = f"{type(e).__name__}: {e}"
    else:
        parent = os.path.dirname(p) or "."
        out["writable"] = os.access(parent, os.W_OK | os.X_OK)
        if not out["writable"]:
            out["error"] = f"parent directory {parent!r} not writable"
    return out, records


def _health_section(ledger_records: list[dict]) -> dict:
    """Fleet durability-health facts (obs/health.py, docs/HEALTH.md):
    replay the shared ledger-record list — parsed once by
    :func:`_ledger_section` — into health state and report snapshot
    freshness, the at-risk count and the repair work-queue depth."""
    out: dict = {"enabled": _runlog.enabled(), "tracked": 0, "at_risk": 0,
                 "work_queue_depth": 0, "buckets": None, "events": 0,
                 "snapshots": 0, "snapshots_corrupt": 0,
                 "snapshot_age_s": None, "events_since_snapshot": 0,
                 "error": None}
    if not out["enabled"]:
        out["error"] = "RS_RUNLOG unset (no damage ledger)"
        return out
    try:
        from . import health as _health

        state = _health.replay(ledger_records)
        report = _health.fleet_report(state)
        out["tracked"] = report["total"]
        out["at_risk"] = report["at_risk"]
        out["work_queue_depth"] = report["work_queue_depth"]
        out["buckets"] = report["buckets"]
        out["events"] = report["events"]
        out["snapshots"] = report["snapshots"]
        out["snapshots_corrupt"] = report["snapshots_corrupt"]
        out["events_since_snapshot"] = report["events_since_snapshot"]
        if report["snapshot_ts"]:
            out["snapshot_age_s"] = round(
                max(0.0, time.time() - report["snapshot_ts"]), 3)
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _maint_section(ledger_records: list[dict]) -> dict:
    """Background-maintenance facts (maint/controller.py, docs/MAINT.md):
    is the daemon plane enabled, what would the controller work on right
    now (repair / scrub / compaction-eligible counts from the shared
    ledger replay), and the throttle knobs it would run under."""
    out: dict = {"enabled": False, "tenant": None, "repairs": 0,
                 "scrubs": 0, "claimed": 0,
                 "knobs": {k: os.environ.get(k) for k in
                           ("RS_MAINT", "RS_MAINT_TENANT",
                            "RS_MAINT_BYTES_PER_S", "RS_MAINT_BURN_PAUSE",
                            "RS_MAINT_RESUME", "RS_MAINT_LEASE_S",
                            "RS_MAINT_INTERVAL_S")},
                 "error": None}
    try:
        from ..maint import controller as _maint

        out["enabled"] = _maint.enabled()
        out["tenant"] = _maint.tenant_env()
        if not _runlog.enabled():
            out["error"] = "RS_RUNLOG unset (no damage ledger)"
            return out
        from . import health as _health

        state = _health.replay(ledger_records)
        now = time.time()
        for item in _health.work_queue(state, now=now):
            if item["action"] == "repair":
                out["repairs"] += 1
            else:
                out["scrubs"] += 1
            if item.get("claimed_by"):
                out["claimed"] += 1
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _perf_section(ledger_records: list[dict]) -> dict:
    """Perf-baseline facts (obs/perfbase.py, docs/OBSERVABILITY.md
    "Perf attribution & baselines"): replay the shared ledger-record
    list into the drift report — is a baseline blessed, how many cells
    have current evidence, and how far the worst cell has drifted."""
    out: dict = {"enabled": _runlog.enabled(), "baseline": False,
                 "baseline_cells": 0, "current_cells": 0, "samples": 0,
                 "worst_cell": None, "worst_ratio": None, "breach": False,
                 "drift_frac": None,
                 "knobs": {k: os.environ.get(k) for k in
                           ("RS_PROF", "RS_PROF_SAMPLE",
                            "RS_PERF_DRIFT_FRAC")},
                 "error": None}
    if not out["enabled"]:
        out["error"] = "RS_RUNLOG unset (no perf evidence stream)"
        return out
    try:
        from . import perfbase as _perfbase

        rep = _perfbase.report(ledger_records)
        out["baseline"] = rep["baseline"]
        out["baseline_cells"] = rep["baseline_cells"]
        out["current_cells"] = rep["current_cells"]
        out["samples"] = rep["samples"]
        out["drift_frac"] = rep["drift_frac"]
        out["breach"] = rep["breach"]
        if rep["worst"] is not None:
            out["worst_cell"] = rep["worst"]["cell"]
            out["worst_ratio"] = rep["worst"]["ratio"]
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _endpoint_section(probe: bool = True) -> dict:
    port = os.environ.get("RS_METRICS_PORT")
    out: dict = {"port": port, "reachable": None, "error": None}
    if not port:
        out["error"] = "RS_METRICS_PORT unset (no live /metrics endpoint)"
        return out
    if not probe:
        return out
    try:
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{int(port)}/healthz", timeout=2
        ) as resp:
            out["reachable"] = resp.status == 200
    except Exception as e:
        out["reachable"] = False
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _serve_section(probe: bool = True) -> dict:
    """Serve-daemon facts (docs/SERVE.md): the configured port and queue
    knobs (env-resolved, same precedence the daemon uses) plus one local
    ``/healthz`` probe of a running daemon when a port is configured."""
    from ..serve.batcher import DEFAULT_BATCH_MS, DEFAULT_MAX_BATCH
    from ..serve.daemon import DEFAULT_PORT
    from ..serve.queue import DEFAULT_DEPTH, DEFAULT_QUANTUM
    from ..utils.env import float_env, int_env

    port = os.environ.get("RS_SERVE_PORT")
    out: dict = {
        "port": port,
        "default_port": DEFAULT_PORT,
        "depth": int_env("RS_SERVE_DEPTH", DEFAULT_DEPTH),
        "quantum": int_env("RS_SERVE_QUANTUM", DEFAULT_QUANTUM),
        "batch_ms": float_env("RS_SERVE_BATCH_MS", DEFAULT_BATCH_MS),
        "max_batch": int_env("RS_SERVE_MAX_BATCH", DEFAULT_MAX_BATCH),
        "workers": int_env("RS_SERVE_WORKERS", 2),
        "reachable": None,
        "daemon": None,
        "error": None,
    }
    if not port:
        out["error"] = "RS_SERVE_PORT unset (no resident daemon configured)"
        return out
    if not probe:
        return out
    try:
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{int(port)}/healthz", timeout=2
        ) as resp:
            body = json.loads(resp.read())
            out["reachable"] = resp.status == 200
            # The live daemon's own answer (queue depth, draining,
            # inflight) — the facts a support thread asks for first.
            out["daemon"] = {
                key: body.get(key)
                for key in ("uptime_s", "draining", "queue_depth",
                            "inflight", "requests_done",
                            "requests_failed")
            }
    except Exception as e:
        out["reachable"] = False
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _slo_section(probe: bool = True) -> dict:
    """SLO-objective facts (docs/SERVE.md "Request lifecycle"): the
    parsed ``RS_SLO`` table and rolling-window config, plus one live
    ``GET /slo`` probe of a configured daemon summarizing current
    breaches.  A malformed spec surfaces here as the parse error the
    daemon would refuse to start with."""
    from . import slo as _slo

    out: dict = {
        "configured": False,
        "source": None,  # "env" | "daemon" (rs serve --slo)
        "spec": os.environ.get("RS_SLO") or None,
        "objectives": [],
        "windows_s": list(_slo.windows()),
        "reqtrace_ring": None,
        "attainment": None,
        "error": None,
    }
    try:
        from . import reqtrace as _reqtrace

        out["reqtrace_ring"] = _reqtrace.ring_capacity()
    except Exception:
        pass
    if out["spec"]:
        try:
            objectives = _slo.parse_slo(out["spec"])
        except _slo.SLOSpecError as e:
            out["error"] = f"SLOSpecError: {e}"
            return out
        out["configured"] = True
        out["source"] = "env"
        out["objectives"] = [o.describe() for o in objectives]
    port = os.environ.get("RS_SERVE_PORT")
    if probe and port:
        # Probe regardless of the env spec: a daemon started with
        # `rs serve --slo ...` is configured even when this shell's
        # RS_SLO is unset — its /slo report is the truth.
        try:
            import urllib.request

            with urllib.request.urlopen(
                f"http://127.0.0.1:{int(port)}/slo", timeout=2
            ) as resp:
                report = json.loads(resp.read())
            if report.get("configured") and not out["configured"]:
                out["configured"] = True
                out["source"] = "daemon"
                out["objectives"] = report.get("objectives", [])
            if report.get("configured"):
                out["attainment"] = {
                    "cells": len(report.get("cells", [])),
                    "breaches": _slo.breaches(report),
                }
        except Exception as e:
            if out["configured"]:
                out["error"] = f"{type(e).__name__}: {e}"
    if not out["configured"] and out["error"] is None:
        out["error"] = "RS_SLO unset (no SLO objectives)"
    return out


def _roofline_section(ledger_records: list[dict]) -> dict:
    out: dict = {"cached": False, "age_s": None, "fresh": None,
                 "triad_gbps": None, "gemm_gflops": None,
                 "max_age_s": _attrib.roofline_max_age_s()}
    host = socket.gethostname()
    rec = next(
        (r for r in reversed(ledger_records)
         if r.get("kind") == "rs_roofline" and r.get("host") == host),
        None,
    )
    if rec is None:
        return out
    out["cached"] = True
    out["triad_gbps"] = rec.get("triad_gbps")
    out["gemm_gflops"] = rec.get("gemm_gflops")
    age = time.time() - float(rec.get("ts") or 0)
    out["age_s"] = round(age, 1)
    out["fresh"] = 0 <= age < out["max_age_s"]
    return out


def collect(probe_endpoint: bool = True,
            store_root: str | None = None) -> dict:
    """The full diagnostic document (the ``--json`` payload)."""
    jax_info = _jax_section()
    ledger, ledger_records = _ledger_section()
    report = {
        "kind": "rs_doctor",
        "schema": SCHEMA_VERSION,
        "ts": time.time(),
        "host": socket.gethostname(),
        "python": {
            "version": platform.python_version(),
            "executable": sys.executable,
        },
        "jax": jax_info,
        "native": _native_section(),
        "mesh": _mesh_section(jax_info),
        "env": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith("RS_")
        },
        "decoder": _decoder_section(),
        "update": _update_section(),
        "store": _store_section(store_root),
        "strategies": _strategies_section(),
        "ledger": ledger,
        "metrics_endpoint": _endpoint_section(probe_endpoint),
        "serve": _serve_section(probe_endpoint),
        "slo": _slo_section(probe_endpoint),
        "roofline": _roofline_section(ledger_records),
        "health": _health_section(ledger_records),
        "perf": _perf_section(ledger_records),
        "maint": _maint_section(ledger_records),
    }
    warnings = []
    if not jax_info["importable"]:
        warnings.append(f"jax failed to import: {jax_info['error']}")
    if not report["native"]["available"]:
        warnings.append("native library unavailable — host paths run on "
                        "the NumPy fallback")
    if not report["mesh"]["shard_map_available"]:
        warnings.append("jax.shard_map missing — mesh paths will fail "
                        "(the carried mesh-failure signature, "
                        "docs/STATUS.md)")
    if report["ledger"]["path"] and report["ledger"]["writable"] is False:
        warnings.append(f"run ledger not writable: "
                        f"{report['ledger']['error']}")
    if report["roofline"]["cached"] and not report["roofline"]["fresh"]:
        warnings.append("roofline calibration is stale — rs analyze will "
                        "re-probe (or pass --refresh-roofline)")
    if report["health"]["at_risk"]:
        warnings.append(f"{report['health']['at_risk']} archive(s) at "
                        "risk — run `rs health` for the ranked fleet "
                        "table and repair the top entries")
    if report["perf"]["breach"]:
        warnings.append(f"perf drift: worst cell "
                        f"{report['perf']['worst_cell']} at "
                        f"{report['perf']['worst_ratio']}x of baseline "
                        "— run `rs perf` for the per-cell table")
    report["warnings"] = warnings
    return report


def render(report: dict) -> str:
    """Human-readable doctor output: one ok/!! line per fact."""

    def mark(ok) -> str:
        return "ok" if ok else "!!"

    j = report["jax"]
    n = report["native"]
    m = report["mesh"]
    led = report["ledger"]
    ep = report["metrics_endpoint"]
    sv = report["serve"]
    sl = report["slo"]
    rl = report["roofline"]
    if not sl["configured"]:
        slo_line = f"[--] slo: RS_SLO unset (ring {sl['reqtrace_ring']})"
        if sl["spec"]:  # set but unparseable — that IS a problem
            slo_line = f"[!!] slo: {sl['error']}"
    else:
        n_breach = (len(sl["attainment"]["breaches"])
                    if sl["attainment"] else None)
        spec = sl["spec"] if sl["source"] == "env" \
            else "from the live daemon"
        slo_line = (
            f"[{mark(not n_breach)}] slo: "
            f"{len(sl['objectives'])} objective(s) ({spec}), "
            f"windows {sl['windows_s']}"
            + (f"; live: {sl['attainment']['cells']} cell(s), "
               f"{n_breach} breach(es)" if sl["attainment"] is not None
               else "; not probed")
        )
    h = report["health"]
    if not h["enabled"] or h["error"]:
        health_line = ("[--] health: " + (h["error"] or "unavailable")
                       if not h["enabled"]
                       else f"[!!] health: {h['error']}")
    else:
        health_line = (
            f"[{mark(not h['at_risk'])}] health: {h['tracked']} archive(s) "
            f"tracked, {h['at_risk']} at risk, work queue "
            f"{h['work_queue_depth']}, {h['snapshots']} snapshot(s)"
            + (f" (last {h['snapshot_age_s']}s ago, "
               f"{h['events_since_snapshot']} delta(s) since)"
               if h["snapshot_age_s"] is not None else "")
            + (f", {h['snapshots_corrupt']} corrupt snapshot(s) skipped"
               if h["snapshots_corrupt"] else "")
        )
    mt = report["maint"]
    if mt["error"]:
        maint_line = f"[--] maint: {mt['error']}"
    else:
        mt_knobs = ", ".join(f"{k}={v}" for k, v in mt["knobs"].items()
                             if v is not None) or "knobs default"
        maint_line = (
            f"[{'ok' if mt['enabled'] else '--'}] maint: "
            + ("daemon tenant on" if mt["enabled"]
               else "daemon tenant off (RS_MAINT unset)")
            + f" — queue {mt['repairs']} repair(s), {mt['scrubs']} "
              f"scrub(s), {mt['claimed']} claimed; {mt_knobs}"
        )
    pf = report["perf"]
    if not pf["enabled"] or pf["error"]:
        perf_line = ("[--] perf: " + (pf["error"] or "unavailable")
                     if not pf["enabled"]
                     else f"[!!] perf: {pf['error']}")
    else:
        knobs = ", ".join(f"{k}={v}" for k, v in pf["knobs"].items()
                          if v is not None) or "knobs default"
        perf_line = (
            f"[{mark(not pf['breach'])}] perf: "
            + (f"baseline {pf['baseline_cells']} cell(s)"
               if pf["baseline"] else "no blessed baseline")
            + f", {pf['current_cells']} current, {pf['samples']} "
              f"sample(s)"
            + (f", worst {pf['worst_cell']} @ {pf['worst_ratio']}x "
               f"(gate {pf['drift_frac']}x)"
               if pf["worst_cell"] else "")
            + f"; {knobs}"
        )
    lines = [
        f"rs doctor @ {report['host']} "
        f"(python {report['python']['version']})",
        f"[{mark(j['importable'])}] jax {j['version'] or '-'}: backend "
        f"{j['backend'] or '-'}, {j['device_count']} device(s) "
        f"{j['devices'] or ''}"
        + (f" — {j['error']}" if j["error"] else ""),
        f"[{mark(n['available'])}] native lib: "
        + (f"{n['lib_path']} (src {n['src_digest']})"
           if n["available"] else str(n["error"])),
        f"[{mark(m['shard_map_available'])}] mesh: "
        f"{m['local_device_count']} local device(s), shard_map "
        f"{'present' if m['shard_map_available'] else 'MISSING'}"
        + (f", {m['distributed_env']}" if m["distributed_env"] else ""),
        "[--] RS_* knobs: "
        + (", ".join(f"{k}={v}" for k, v in report["env"].items())
           or "(none set)"),
        f"[{mark(report['decoder']['locate'])}] decoder: erasure"
        + ("+locate" if report["decoder"]["locate"] else " ONLY")
        + f", w {report['decoder']['supported_w']}, syndrome kernel "
        + (report["decoder"]["syndrome_kernel"] or "unavailable"),
        f"[{mark(report['update']['delta_update'])}] update: "
        + (
            f"delta update + append, layouts "
            f"{report['update']['layouts']}, "
            f"{report['update']['crash_safety']}; group commit "
            f"<={report['update']['group_commit']['window_max_edits']} "
            f"edits/group, {report['update']['group_commit']['groups']} "
            f"committed (max {report['update']['group_commit']['max_group_seen']})"
            if report["update"]["delta_update"]
            else f"unavailable ({report['update']['error']})"
        ),
        f"[{'--' if not report['store']['probed'] else mark(not report['store']['error'])}] "
        "store: "
        + (
            f"{len(report['store']['buckets'])} bucket(s), "
            f"{report['store']['objects']} objects, "
            f"{report['store']['live_bytes']} live / "
            f"{report['store']['dead_bytes']} dead bytes, "
            f"{report['store']['pending_compactions']} pending "
            f"compaction(s)"
            + (f", {report['store']['pending_drops']} rolled-back "
               "record(s) pending rewrite"
               if report["store"]["pending_drops"] else "")
            + (f", {report['store']['snapshots']} index snapshot(s) / "
               f"{report['store']['segments']} sealed segment(s)"
               if report["store"].get("snapshots")
               or report["store"].get("segments") else "")
            if report["store"]["probed"]
            else (report["store"]["error"]
                  or "no root (pass --root or set RS_STORE_ROOT)")
        )
        + f"; stripe {report['store']['knobs'].get('RS_STORE_STRIPE_BYTES')} B"
          f" seal, compact @"
          f"{report['store']['knobs'].get('RS_STORE_COMPACT_DEAD_FRAC')}"
          f", snapshot every "
          f"{report['store']['knobs'].get('RS_STORE_SNAPSHOT_RECORDS')}"
          f" records, obj cache "
          f"{report['store']['knobs'].get('RS_OBJ_CACHE_BYTES')} B",
        f"[{mark(not report['strategies']['error'])}] strategies: "
        + (
            f"{'/'.join(report['strategies']['candidates'])} compete for "
            f"auto -> {report['strategies']['auto']['strategy']} "
            f"({report['strategies']['auto']['source']}, mode "
            f"{report['strategies']['auto']['mode']}); xor schedules "
            f"{len(report['strategies']['xor']['schedules'])} cached, "
            "store "
            + (
                f"{report['strategies']['store']['entries'] or 0} "
                f"entries "
                f"({report['strategies']['store']['hits']} hits/"
                f"{report['strategies']['store']['misses']} misses)"
                if report["strategies"]["store"]["enabled"]
                else "disabled"
            )
            + (
                ", " + ", ".join(
                    f"{s['digest']}:{s['terms_naive']}->{s['xors']} xors"
                    for s in report["strategies"]["xor"]["schedules"][:3]
                )
                if report["strategies"]["xor"]["schedules"] else ""
            )
            if not report["strategies"]["error"]
            else f"unavailable ({report['strategies']['error']})"
        ),
        f"[{mark(led['writable'])}] ledger: "
        + (f"{led['path']} ({led['records']} records, "
           f"{led.get('damage_records', 0)} damage, "
           f"{led.get('health_snapshots', 0)} health snapshot(s))"
           if led["path"] else "RS_RUNLOG unset"),
        # reachable is None when the probe was skipped (--no-probe): an
        # untested endpoint must not render as an outage.
        f"[{'--' if ep['reachable'] is None and ep['port'] else mark(ep['reachable'])}] "
        "metrics endpoint: "
        + (f"port {ep['port']} "
           + ("not probed" if ep["reachable"] is None
              else "reachable" if ep["reachable"] else "UNREACHABLE")
           if ep["port"] else "RS_METRICS_PORT unset"),
        f"[{'--' if sv['reachable'] is None and sv['port'] else mark(sv['reachable'])}] "
        "serve daemon: "
        + (f"port {sv['port']} "
           + ("not probed" if sv["reachable"] is None
              else (f"reachable (queue {sv['daemon']['queue_depth']}, "
                    f"{'draining' if sv['daemon']['draining'] else 'live'})"
                    if sv["reachable"] and sv["daemon"] else "reachable")
              if sv["reachable"] else "UNREACHABLE")
           if sv["port"] else "RS_SERVE_PORT unset")
        + f"; knobs depth={sv['depth']} batch_ms={sv['batch_ms']} "
          f"max_batch={sv['max_batch']} workers={sv['workers']}",
        slo_line,
        f"[{mark(rl['cached'] and rl['fresh'])}] roofline: "
        + (f"{rl['triad_gbps']} GB/s triad / {rl['gemm_gflops']} GFLOP/s "
           f"gemm, age {rl['age_s']}s "
           f"({'fresh' if rl['fresh'] else 'STALE'})"
           if rl["cached"] else "not calibrated (run rs analyze)"),
        health_line,
        perf_line,
        maint_line,
    ]
    for w in report.get("warnings", []):
        lines.append(f"  warning: {w}")
    return "\n".join(lines)


def main(argv=None) -> int:
    """The ``rs doctor`` subcommand."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="rs doctor",
        description="One-shot environment diagnostic: backends/devices, "
        "native lib, mesh sanity, RS_* knobs, ledger and metrics-endpoint "
        "reachability, roofline calibration freshness.",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit the schema-stable JSON document")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the live /healthz endpoint probe")
    ap.add_argument("--root", default=None,
                    help="object-store root to probe for the store "
                    "section (default $RS_STORE_ROOT; read-only)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    report = collect(probe_endpoint=not args.no_probe,
                     store_root=args.root)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    # Exit 0 even with warnings: doctor diagnoses, CI gates elsewhere.
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
