"""Per-dispatch stage profiler — where ONE GEMM dispatch's wall goes.

The reference CUDA tool attributed its wall per overlap stage (PCIe copy
vs kernel, encode.cu's cudaEvent pairs); this reproduction's dispatch
pipeline has grown far past two stages — bit-plane pack, XOR chain (or
the ring lowering's ring-in / shift-accumulate / ring-out triple),
unpack, plan compile, host->device staging — and ROADMAP item 1 steers
by per-stage shares ("pack is ~60% of one-pass xor wall") that until now
lived only in hand-run captures.  This module is the measurement seam:

* **Opt-in, sampled** — ``RS_PROF`` truthy (or :func:`force_enable`)
  turns the plane on; ``RS_PROF_SAMPLE=1/N`` profiles one dispatch in N.
  Stage timing must ``block_until_ready`` between stages, which
  collapses the async pack->chain overlap the pipeline exists to create
  — the same reason ``RS_XOR_PACK_TIMING`` is opt-in — so a
  metrics-scraping daemon samples sparsely instead of serializing every
  dispatch.  With ``RS_PROF`` unset, :func:`begin` returns None after
  one env read, no stage dict is allocated, and nothing registers
  (tests/test_profiler.py guards the disabled path like
  tests/test_reqtrace.py guards the request plane).
* **One wide event per profiled dispatch** — op + strategy + width +
  shape bucket, bytes moved, per-stage seconds (summing to >=95% of the
  dispatch wall by construction: every stage is timed inside the wall),
  and cache attribution (plan-bucket hit, PackedOperand reused vs
  packed, schedule memory/store hit vs built, optimizer wall) — fanned
  out to (1) the run ledger as ``kind=rs_perf`` (the ``rs perf``
  baseline feed, dropped from ``rs history`` trend views), (2)
  ``rs_prof_stage_seconds{stage,strategy,op}`` streaming quantiles, and
  (3) retroactive Perfetto child spans (lane ``prof:<stage>``) under
  PR 14's request spans, so a served request's flamegraph descends into
  pack/chain/unpack.
* **Thread-local** — the active profile rides thread-local state, not
  plumbed arguments, because the seams live five layers apart
  (codec._count_segment names the op; plan.dispatch opens the profile;
  the pipeline __call__ deep in ops/ times the stages).  Concurrent
  daemon workers each profile their own dispatches.

Import cost: stdlib only (no jax, no numpy); jax is imported lazily and
only while a profile is actually active.
"""

from __future__ import annotations

import os
import threading
import time

from . import metrics as _metrics, runlog as _runlog, tracing as _tracing

# Canonical stage vocabulary (docs/OBSERVABILITY.md "Perf attribution &
# baselines").  ``h2d`` (host->device staging) is observed into the same
# quantile family but kept OUT of the per-dispatch stages dict: staging
# happens before dispatch opens, so folding it in would break the
# stages-sum-to-dispatch-wall invariant the capture gate asserts.
STAGES = ("pack", "chain", "ring_in", "shift_acc", "ring_out", "unpack",
          "compile")

_TRUTHY = ("1", "true", "on", "yes")

# force_enable() latch: xor_ab/bench.py profile one extra dispatch per
# arm without asking the user to export RS_PROF.
_FORCED = False

_LOCK = threading.Lock()
_SEEN = 0  # dispatches seen since process start — the sampling clock

_TLS = threading.local()


def enabled() -> bool:
    """Whether the profiler plane is on: ``RS_PROF`` truthy (read per
    call so tests can monkeypatch) or :func:`force_enable` latched."""
    return _FORCED or os.environ.get("RS_PROF", "").lower() in _TRUTHY


def force_enable(on: bool = True) -> None:
    """Latch the profiler on (off) regardless of ``RS_PROF`` — the
    in-process equivalent of exporting the env var (tools, tests)."""
    global _FORCED
    _FORCED = on


def forced() -> bool:
    """Current latch state, so tools can save/restore it."""
    return _FORCED


def sample_every() -> int:
    """``RS_PROF_SAMPLE``: profile one dispatch in N (accepts ``1/N`` or
    bare ``N``; default 1 = every dispatch).  Malformed values degrade
    to 1 — a typo must widen observation, not silently disable it."""
    v = os.environ.get("RS_PROF_SAMPLE", "").strip()
    if not v:
        return 1
    if "/" in v:
        v = v.split("/", 1)[1].strip()
    try:
        return max(1, int(v))
    except ValueError:
        return 1


def _sampled() -> bool:
    global _SEEN
    n = sample_every()
    with _LOCK:
        _SEEN += 1
        return n <= 1 or _SEEN % n == 1  # first dispatch always sampled


def reset() -> None:
    """Drop all thread-local + sampling state (tests)."""
    global _SEEN
    with _LOCK:
        _SEEN = 0
    for attr in ("prof", "op", "staging", "last"):
        try:
            delattr(_TLS, attr)
        except AttributeError:
            pass


class DispatchProfile:
    """The in-flight record of one profiled dispatch."""

    __slots__ = ("op", "strategy", "w", "bucket", "bytes_in", "bytes_out",
                 "t0", "stages", "spans", "cache", "staging_s",
                 "staging_bytes")

    def __init__(self, *, op, strategy, w, bucket, bytes_in):
        self.op = op
        self.strategy = strategy
        self.w = w
        self.bucket = bucket
        self.bytes_in = bytes_in
        self.bytes_out = None
        self.t0 = time.monotonic()
        self.stages: dict[str, float] = {}
        self.spans: list[tuple[str, float, float]] = []
        self.cache: dict = {}
        self.staging_s = 0.0
        self.staging_bytes = 0

    def add(self, name: str, dt: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + dt


def note_op(op: str) -> None:
    """Name the file-level op the NEXT dispatch serves (codec seam:
    ``_count_segment`` calls this right before ``_matmul``).  Without a
    noted op a profiled dispatch reports ``op="matmul"``."""
    if enabled():
        _TLS.op = op


def note_staging(dt: float, nbytes: int) -> None:
    """Record one host->device staging wall (plan.stage_segment seam).
    Held thread-locally and folded into the NEXT profile opened on this
    thread — staging happens before its dispatch."""
    if not enabled():
        return
    s, b = getattr(_TLS, "staging", (0.0, 0))
    _TLS.staging = (s + dt, b + int(nbytes))


def begin(*, strategy, w=None, bucket=None, bytes_in=None):
    """Open a profile for the dispatch starting NOW, or None when the
    plane is off / this dispatch is not sampled.  Consumes the
    thread-local op name and any pending staging walls either way (a
    skipped sample must not leak its staging onto a later dispatch)."""
    if not enabled():
        return None
    op = getattr(_TLS, "op", None)
    _TLS.op = None
    staging = getattr(_TLS, "staging", None)
    _TLS.staging = (0.0, 0)
    # force_enable() means "profile THIS dispatch" (xor_ab's extra
    # profiled run) — ambient RS_PROF_SAMPLE must not skip it.
    if not _sampled() and not _FORCED:
        return None
    prof = DispatchProfile(op=op or "matmul", strategy=str(strategy),
                           w=w, bucket=bucket, bytes_in=bytes_in)
    if staging is not None:
        prof.staging_s, prof.staging_bytes = staging
    _TLS.prof = prof
    return prof


def active():
    """The profile opened on this thread, or None (the pipeline seams'
    one-getattr gate: disabled path costs one thread-local read)."""
    return getattr(_TLS, "prof", None)


def discard(prof) -> None:
    """Drop an open profile without emitting (the dispatch raised)."""
    if prof is not None and getattr(_TLS, "prof", None) is prof:
        _TLS.prof = None


def _block(out):
    import jax

    return jax.block_until_ready(out)


def run_stage(name: str, fn, *args):
    """Run ``fn(*args)`` as stage ``name`` of the active profile:
    device-blocked timing + a retroactive span.  With no active profile
    the call is forwarded untouched — callers use this unconditionally
    only on already-profiled paths; hot paths gate on :func:`active`."""
    prof = active()
    if prof is None:
        return fn(*args)
    t0 = time.monotonic()
    out = _block(fn(*args))
    t1 = time.monotonic()
    prof.add(name, t1 - t0)
    prof.spans.append((name, t0, t1))
    return out


def attr(**kv) -> None:
    """Attach cache-attribution fields to the active profile (plan
    bucket hit/miss, PackedOperand reused/packed, schedule outcome)."""
    prof = active()
    if prof is not None:
        prof.cache.update(kv)


def add_compile(dt: float) -> None:
    """Fold a compile wall (plan build, pipeline split-stage compile)
    into the active profile's ``compile`` stage."""
    prof = active()
    if prof is not None and dt > 0:
        prof.add("compile", dt)


def note_opt(dt: float, **kv) -> None:
    """Attribute one XOR-optimizer pass (ops/xor_opt.py seam): wall into
    the cache-attribution block (it is compile-time work, not a dispatch
    stage), plus any pass stats the optimizer reports."""
    prof = active()
    if prof is None:
        return
    prof.cache["opt_s"] = round(prof.cache.get("opt_s", 0.0) + dt, 6)
    for k, v in kv.items():
        prof.cache[k] = v


def last_event() -> dict | None:
    """The most recent wide event emitted on this thread (the
    tools/xor_ab.py + bench.py `stages` capture hook)."""
    return getattr(_TLS, "last", None)


def finish(prof, out=None) -> dict | None:
    """Close a profile: block the dispatch output, stamp the wall, fold
    into the canonical wide event and fan it out (ledger ``kind=rs_perf``,
    ``rs_prof_stage_seconds`` quantiles, retroactive trace spans).
    Returns the event; None-tolerant so call sites need no guard."""
    if prof is None:
        return None
    if getattr(_TLS, "prof", None) is prof:
        _TLS.prof = None
    if out is not None:
        try:
            out = _block(out)
            prof.bytes_out = getattr(out, "nbytes", None)
        except Exception:
            pass  # profiling must never fail the dispatch it observes
    wall = time.monotonic() - prof.t0
    stages = {k: round(v, 9) for k, v in prof.stages.items() if v > 0}
    event = {
        "kind": "rs_perf",
        "op": prof.op,
        "strategy": prof.strategy,
        "w": prof.w,
        "bucket": prof.bucket,
        "bytes": prof.bytes_in,
        "bytes_out": prof.bytes_out,
        "wall_s": round(wall, 9),
        "stages": stages,
        "coverage": round(sum(stages.values()) / wall, 4) if wall > 0
        else None,
        "cache": dict(prof.cache),
    }
    if prof.staging_s > 0:
        event["staging_s"] = round(prof.staging_s, 9)
        event["staging_bytes"] = prof.staging_bytes
    q = _metrics.quantile(
        "rs_prof_stage_seconds",
        "per-dispatch stage walls (pack/chain/ring_in/shift_acc/"
        "ring_out/unpack/compile + h2d staging), streaming quantiles",
    )
    for name, dt in stages.items():
        q.labels(stage=name, strategy=prof.strategy,
                 op=prof.op).observe(dt)
    if prof.staging_s > 0:
        q.labels(stage="h2d", strategy=prof.strategy,
                 op=prof.op).observe(prof.staging_s)
    if _tracing.active() is not None:
        for name, t0, t1 in prof.spans:
            _tracing.complete(name, f"prof:{name}", t0, t1,
                              strategy=prof.strategy, op=prof.op)
    if _runlog.enabled():
        _runlog.record(dict(event))
    _TLS.last = event
    return event
