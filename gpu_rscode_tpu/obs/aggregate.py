"""Multi-host telemetry aggregation — merge per-process snapshots + traces.

A multi-process job (tests/multihost_worker.py, the ``--devices`` CLI
under ``JAX_NUM_PROCESSES``) runs the SAME instrumented program on every
host, so each process produces its own metrics snapshot and its own
Chrome trace — per-process files named ``{path}.p{process_index}``.  This
module is the fleet-side half that fuses them:

* **Snapshot merge** (:func:`merge_snapshots`): counters sum, gauges keep
  the max (peak across the fleet) plus the per-process last values,
  histograms sum bucket-wise — cumulative ``le`` counts (``+Inf``
  included), ``_sum`` and ``_count`` all add, so the merged histogram is
  exactly the histogram a single process observing every event would have
  produced (the property the tests/test_aggregate.py suite checks).
* **Trace fusion** (:func:`merge_traces`): each process becomes one
  Perfetto *process lane* (distinct ``pid``, labeled with host + process
  index), keeping its internal thread lanes, with timestamps aligned onto
  one axis via the shared epoch captured at ``jax.distributed.initialize``
  time (``otherData.rs_epoch`` / ``rs_wall_t0``, obs/tracing.py).

CLI::

    python -m gpu_rscode_tpu.obs.aggregate --snapshot-out merged.json  m.json
    python -m gpu_rscode_tpu.obs.aggregate --trace-out fleet.trace     t.json

where each input is either an explicit part file or a base path whose
``.p0, .p1, ...`` parts are discovered (:func:`find_parts`).

Import cost: stdlib only (no jax, no numpy) — the aggregator typically
runs on a machine that saw none of the work.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

from . import percentile as _percentile

_PART_RE = re.compile(r"\.p(\d+)$")


def find_parts(base: str) -> list[str]:
    """Per-process part files for ``base``: ``base.p0, base.p1, ...``
    sorted by process index (numeric — ``.p10`` after ``.p9``).  Falls
    back to ``[base]`` when no parts exist but the base file does (a
    single-process run needs no merge but should flow through the same
    pipeline)."""
    d = os.path.dirname(base) or "."
    name = os.path.basename(base)
    parts = []
    try:
        entries = os.listdir(d)
    except OSError:
        entries = []
    for e in entries:
        if e.startswith(name):
            m = _PART_RE.fullmatch(e[len(name):])
            if m:
                parts.append((int(m.group(1)), os.path.join(d, e)))
    if parts:
        return [p for _, p in sorted(parts)]
    return [base] if os.path.exists(base) else []


def part_path(base: str, process_index: int, process_count: int) -> str:
    """Where one process of a multi-process job dumps its telemetry:
    ``base.p{i}`` when the job spans processes, ``base`` itself when it
    does not (so single-process behavior is unchanged)."""
    return f"{base}.p{process_index}" if process_count > 1 else base


# -- snapshot merge ----------------------------------------------------------


def _is_histogram_value(v) -> bool:
    return isinstance(v, dict) and "buckets" in v


def _is_quantile_value(v) -> bool:
    return isinstance(v, dict) and "reservoir" in v


def _merge_quantile(acc: dict | None, v: dict) -> dict:
    """Fold one part's estimator state into the accumulator: exact
    count/sum/min/max, count-weighted reservoir merge (the
    obs/percentile.py contract — a part that saw 10x the events
    contributes ~10x the samples), percentile family recomputed from the
    merged reservoir."""
    merged = _percentile.merge_states([acc, v] if acc else [v])
    merged["quantiles"] = _percentile.state_quantiles(merged)
    return merged


def _merge_histogram(acc: dict | None, v: dict) -> dict:
    if acc is None:
        acc = {"count": 0, "sum": 0.0, "buckets": {}}
    out_buckets = dict(acc["buckets"])
    for le, cum in v.get("buckets", {}).items():
        out_buckets[le] = out_buckets.get(le, 0) + cum
    return {
        "count": acc["count"] + v.get("count", 0),
        "sum": acc["sum"] + v.get("sum", 0.0),
        "buckets": out_buckets,
    }


def merge_snapshots(snaps: list[dict]) -> dict:
    """Merge N per-process REGISTRY snapshots into one.

    Input/output shape is ``Registry.snapshot()``'s:
    ``{name: {"type", "help", "values": {label_str: value}}}``.  Merge
    semantics per metric type:

    - **counter** — sum per labeled series (the fleet's total).
    - **gauge** — max per series (the fleet-wide peak: queue depths,
      ring occupancy — the saturation question "did ANY worker max out"),
      with every process's final value preserved under ``"last"``
      (``{label_str: [v_p0, v_p1, ...]}``) so per-host residue is not
      lost.
    - **histogram** — bucket-wise sum of the cumulative ``le`` counts
      (``+Inf`` preserved), plus summed ``sum``/``count`` — equal to the
      single-process histogram of the union of events.

    A name carrying different types across parts raises ValueError
    (summing a gauge into a counter would corrupt the series).
    """
    out: dict = {}
    for snap in snaps:
        for name, fam in snap.items():
            dst = out.get(name)
            if dst is None:
                dst = out[name] = {
                    "type": fam.get("type", "untyped"),
                    "help": fam.get("help", ""),
                    "values": {},
                }
                if fam.get("type") == "gauge":
                    dst["last"] = {}
            elif dst["type"] != fam.get("type", "untyped"):
                raise ValueError(
                    f"metric {name!r} has conflicting types across parts: "
                    f"{dst['type']} vs {fam.get('type')}"
                )
            kind = dst["type"]
            for label, v in fam.get("values", {}).items():
                if kind == "histogram" or _is_histogram_value(v):
                    dst["values"][label] = _merge_histogram(
                        dst["values"].get(label), v
                    )
                elif kind == "quantile" or _is_quantile_value(v):
                    dst["values"][label] = _merge_quantile(
                        dst["values"].get(label), v
                    )
                elif kind == "gauge":
                    prev = dst["values"].get(label)
                    dst["values"][label] = v if prev is None else max(prev, v)
                    dst["last"].setdefault(label, []).append(v)
                else:  # counter (and untyped numerics): sum
                    dst["values"][label] = dst["values"].get(label, 0) + v
    return out


# Plan-cache fields that are configured BOUNDS, not accumulations:
# summing them would claim a limit no process has.
_NON_ADDITIVE_KEYS = frozenset({"max_size"})


def _sum_numeric_tree(parts: list, key: str | None = None):
    """Fold plan-cache style stat dicts: numeric leaves sum (bound-style
    keys like ``max_size`` take the max instead), lists concatenate (so
    a merged ``plans`` list stays consistent with its summed
    ``executables`` count), dict leaves recurse, anything else keeps the
    first part's value."""
    if not parts:
        return None
    first = parts[0]
    if isinstance(first, bool):
        return first
    if isinstance(first, (int, float)):
        nums = [p for p in parts if isinstance(p, (int, float))]
        return max(nums) if key in _NON_ADDITIVE_KEYS else sum(nums)
    if isinstance(first, list):
        return [item for p in parts if isinstance(p, list) for item in p]
    if isinstance(first, dict):
        keys: list = []
        for p in parts:
            if isinstance(p, dict):
                keys.extend(k for k in p if k not in keys)
        return {
            k: _sum_numeric_tree(
                [p[k] for p in parts if isinstance(p, dict) and k in p], k
            )
            for k in keys
        }
    return first


def merge_unified_snapshots(snaps: list[dict]) -> dict:
    """Merge N ``obs.metrics.unified_snapshot()`` dumps (what
    ``--metrics-json`` writes per process): the ``metrics`` registries
    merge per :func:`merge_snapshots`, the plan-cache stats sum their
    numeric counters, and the autotune decisions union (first writer
    wins on a key conflict — every process autotunes the same shapes)."""
    out: dict = {
        "metrics_enabled": any(s.get("metrics_enabled") for s in snaps),
        "merged_from": len(snaps),
        "metrics": merge_snapshots([s.get("metrics", {}) for s in snaps]),
    }
    for key in ("plan_cache", "mesh_plan_cache"):
        present = [s[key] for s in snaps if key in s]
        if present:
            out[key] = _sum_numeric_tree(present)
    autotune: dict = {}
    for s in snaps:
        for k, v in (s.get("autotune_decisions") or {}).items():
            autotune.setdefault(k, v)
    out["autotune_decisions"] = autotune
    return out


def merge_snapshot_files(paths: list[str]) -> dict:
    snaps = []
    for p in paths:
        with open(p) as fp:
            snaps.append(json.load(fp))
        if not isinstance(snaps[-1], dict):
            raise ValueError(f"{p} is not a snapshot (expected a JSON "
                             "object)")
        if "traceEvents" in snaps[-1]:
            raise ValueError(f"{p} is a trace payload — merge traces "
                             "with --trace-out")
    if not snaps:
        raise ValueError("no snapshot parts to merge")
    # any(), not all(): a process that crashed before dump_metrics leaves
    # its part as the CLI's "{}" writability-probe placeholder — an empty
    # part contributes nothing but must not reroute (or crash) the merge
    # of the parts that did land.
    if any("metrics" in s or "metrics_enabled" in s for s in snaps):
        return merge_unified_snapshots(snaps)
    return merge_snapshots(snaps)


def render_text(metrics_snapshot: dict) -> str:
    """Prometheus text exposition of a (merged) registry snapshot — the
    scrape-format counterpart of ``Registry.render_text()`` for snapshots
    that no longer have a live registry behind them."""
    lines = []
    for name in sorted(metrics_snapshot):
        fam = metrics_snapshot[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        ftype = fam.get("type", "untyped")
        # Prometheus has no native "quantile" type; the closest scrape
        # vocabulary is a summary (pre-computed quantile labels).
        lines.append(
            f"# TYPE {name} {'summary' if ftype == 'quantile' else ftype}"
        )
        for label, v in sorted(fam.get("values", {}).items()):
            if _is_quantile_value(v):
                inner = label[1:-1] if label else ""
                sep = "," if inner else ""
                qs = v.get("quantiles") or _percentile.state_quantiles(v)
                for q, qv in sorted(qs.items(), key=lambda kv: float(kv[0])):
                    if qv is not None:
                        lines.append(
                            f'{name}{{{inner}{sep}quantile="{q}"}} {qv}'
                        )
                lines.append(f"{name}_sum{label} {v.get('sum', 0.0)}")
                lines.append(f"{name}_count{label} {v.get('count', 0)}")
                if v.get("max") is not None:
                    lines.append(f"{name}_max{label} {v['max']}")
            elif _is_histogram_value(v):
                inner = label[1:-1] if label else ""
                sep = "," if inner else ""
                for le, cum in v["buckets"].items():
                    lines.append(
                        f'{name}_bucket{{{inner}{sep}le="{le}"}} {cum}'
                    )
                lines.append(f"{name}_sum{label} {v['sum']}")
                lines.append(f"{name}_count{label} {v['count']}")
            else:
                lines.append(f"{name}{label} {v}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- trace fusion ------------------------------------------------------------


def merge_traces(payloads: list[dict], labels: list[str] | None = None) -> dict:
    """Fuse per-process Chrome-trace payloads into one Perfetto file.

    Each input becomes one process lane: its events keep their thread
    (``tid``) structure but get a distinct ``pid`` (process index + 1),
    and its ``process_name`` metadata is rewritten to identify the host
    (``rs_host``) and process index.  Timestamps are aligned onto a
    shared axis:

    - every part carries ``otherData.rs_epoch`` (the barrier wall clock
      captured at ``jax.distributed.initialize``) → each part shifts by
      ``(rs_wall_t0 - rs_epoch)``, placing all lanes relative to the
      common barrier;
    - otherwise, parts with ``rs_wall_t0`` align to the earliest part's
      wall clock;
    - with no anchors at all, lanes share t=0 (overlap is approximate).
    """
    if not payloads:
        raise ValueError("no trace parts to merge")
    others = [p.get("otherData", {}) for p in payloads]
    wall = [o.get("rs_wall_t0") for o in others]
    epoch = [o.get("rs_epoch") for o in others]
    if all(e is not None and w is not None for e, w in zip(epoch, wall)):
        offsets = [(w - e) * 1e6 for w, e in zip(wall, epoch)]
    elif all(w is not None for w in wall):
        base = min(wall)
        offsets = [(w - base) * 1e6 for w in wall]
    else:
        offsets = [0.0] * len(payloads)

    events: list[dict] = []
    merged_other: dict = {"rs_merged_parts": len(payloads)}
    for i, payload in enumerate(payloads):
        pid = i + 1
        other = others[i]
        host = other.get("rs_host", "?")
        proc = other.get("rs_process_index", i)
        label = labels[i] if labels else f"p{proc} {host}"
        off = offsets[i]
        saw_process_name = False
        for ev in payload.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                base_name = (ev.get("args") or {}).get("name", "")
                ev["args"] = {"name": f"{base_name} [{label}]"}
                saw_process_name = True
            elif "ts" in ev:
                ev["ts"] = ev["ts"] + off
            events.append(ev)
        if not saw_process_name:
            events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": label},
            })
        merged_other[f"part{i}"] = {"host": host, "process_index": proc,
                                    "offset_us": off}
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": merged_other,
    }


def merge_trace_files(paths: list[str]) -> dict:
    payloads = []
    for p in paths:
        with open(p) as fp:
            payloads.append(json.load(fp))
        if not isinstance(payloads[-1], dict) or \
                "traceEvents" not in payloads[-1]:
            # The mirror of merge_snapshot_files' guard: a snapshot fed
            # to the trace fuser would silently emit an empty-lane file.
            raise ValueError(f"{p} is not a trace payload (no "
                             "traceEvents) — merge snapshots with "
                             "--snapshot-out")
    return merge_traces(payloads)


# -- CLI ---------------------------------------------------------------------


def _resolve_inputs(inputs: list[str]) -> list[str]:
    paths: list[str] = []
    for inp in inputs:
        if _PART_RE.search(inp):  # explicit part file
            if not os.path.exists(inp):
                raise FileNotFoundError(f"part file not found: {inp!r}")
            found = [inp]
        else:
            found = find_parts(inp)
            if not found:
                raise FileNotFoundError(f"no parts found for {inp!r}")
        paths.extend(found)
    return paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gpu_rscode_tpu.obs.aggregate",
        description="Merge per-process metrics snapshots and/or Chrome "
        "traces from a multi-host run (inputs may be base paths whose "
        ".p<N> parts are discovered).",
    )
    ap.add_argument("inputs", nargs="+", help="part files or base paths")
    ap.add_argument("--snapshot-out", help="write the merged snapshot JSON")
    ap.add_argument("--trace-out", help="write the merged Perfetto JSON")
    ap.add_argument(
        "--text", action="store_true",
        help="with --snapshot-out (or alone): also print the merged "
        "metrics as Prometheus text exposition",
    )
    try:
        args = ap.parse_args(argv)
        if not (args.snapshot_out or args.trace_out or args.text):
            ap.error("pick --snapshot-out, --trace-out and/or --text")
    except SystemExit as e:
        # Same int-return contract as the other rs subcommands: argparse
        # must not raise through a programmatic main() caller.
        return int(e.code or 0)
    try:
        paths = _resolve_inputs(args.inputs)
        print(f"# merging {len(paths)} parts: {', '.join(paths)}",
              file=sys.stderr)
        if args.trace_out:
            merged = merge_trace_files(paths)
            with open(args.trace_out, "w") as fp:
                json.dump(merged, fp)
            print(f"# wrote {args.trace_out}", file=sys.stderr)
        if args.snapshot_out or args.text:
            merged = merge_snapshot_files(paths)
            if args.snapshot_out:
                with open(args.snapshot_out, "w") as fp:
                    json.dump(merged, fp)
                    fp.write("\n")
                print(f"# wrote {args.snapshot_out}", file=sys.stderr)
            if args.text:
                print(render_text(merged.get("metrics", merged)), end="")
    except (OSError, ValueError) as e:
        # Missing/corrupt part files (json.JSONDecodeError is a
        # ValueError) or conflicting metric types: print-and-exit like
        # every other rs subcommand, never a traceback.
        print(f"aggregate: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
