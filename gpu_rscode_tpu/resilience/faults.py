"""Deterministic, seedable fault-injection plane (``RS_FAULTS`` / ``--faults``).

The reference's only fault model was a hand-written conf file naming which
chunks to pretend are lost (unit-test.sh); nothing could provoke the
failures the retry/degraded-decode machinery exists for.  This module is
the injection side of that story: a :class:`FaultPlan` parsed from a
compact spec string, consulted at the I/O boundaries ONLY and compiled
to a shared no-op when unset (``active()`` returns ``None`` after one
env read; nothing parses, nothing allocates — the same tier-1 overhead
contract as the disabled metrics registry).

The boundaries: ``api._open_chunk`` (decode opens + scrub CRC reads),
the segment-gather stages of the encode/decode/repair loops (which also
cover the fallback read pool one level down), and the write-behind drain
lanes of ``parallel.io_executor`` — each crossed exactly once per
logical I/O, uniformly across native and toolchain-less builds.

Spec grammar (specs separated by ``;``, params by ``,``)::

    <scope>:<kind>[@key=val[,key=val...]]

    scope: read | write | scrub | chunk<N>   (chunk<N> fires at read AND
           scrub boundaries, only for chunk index N; add scope=read or
           scope=scrub to pin it to one boundary)
    kind:  ioerror  params p= (probability, default 1), from= (first call
                    number that may fire, default 1), times= (max fires),
                    scope= (chunk<N> boundary pin)
           delay    params ms= (sleep), plus p=/from=/times=/scope=
           bitrot   params count= (bits to flip, default 1), p=, scope=
                    (fires at its spec's boundary only: read:bitrot at
                    decode reads, scrub:bitrot at scan CRC reads)
           torn     params after= (bytes; the write lane dies once its
                    cumulative attempted bytes cross this — persistent,
                    classified FATAL by retry)
    sizes: plain ints or KiB/MiB/GiB suffixes (1MiB, 512KiB)

Examples: ``read:ioerror@p=0.02``, ``chunk2:bitrot@count=8``,
``write:torn@after=1MiB``, ``read:delay@ms=50``.

Determinism: every probabilistic decision is a pure hash of
``(seed, kind, scope, target-basename, call-number)`` — no RNG state —
so the same seed and the same call sequence produce the same schedule
regardless of wall clock, and targets are keyed by BASENAME so a run in
a different temp dir replays identically.  ``RS_FAULTS_SEED`` (or the
``seed=`` argument) selects the schedule.

Injected faults raise :class:`InjectedReadError` /
:class:`InjectedWriteError` (``OSError`` subclasses carrying kind, scope,
target and chunk index) and count ``rs_faults_injected_total{kind,scope}``
plus a ``fault`` instant on the ``faults`` trace lane.

Import cost: stdlib only (numpy is imported lazily inside the bitrot
path, which only runs when a bitrot fault actually fires).
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from contextlib import contextmanager

from ..obs import metrics as _metrics, tracing as _tracing

_READ_SCOPES = ("read", "scrub")
_SIZE_SUFFIXES = (
    ("kib", 1024), ("mib", 1024 ** 2), ("gib", 1024 ** 3),
    ("k", 1024), ("m", 1024 ** 2), ("g", 1024 ** 3), ("b", 1),
)


class InjectedFault(OSError):
    """A fault raised by the plane at an I/O boundary.

    ``transient`` steers retry classification (:mod:`.retry`): ``ioerror``
    models a device hiccup (retryable), ``torn`` a writer that died
    mid-stream (fatal — retrying a dead stream is lying to the caller).
    """

    def __init__(self, kind: str, scope: str, target: str,
                 index: int | None = None, transient: bool = True):
        self.kind = kind
        self.scope = scope
        self.target = target
        self.index = index
        self.transient = transient
        where = target + (f" (chunk {index})" if index is not None else "")
        super().__init__(
            f"injected {kind} fault at {scope} boundary: {where}"
        )


class InjectedReadError(InjectedFault):
    pass


class InjectedWriteError(InjectedFault):
    pass


def _parse_size(text: str) -> int:
    t = text.strip().lower()
    for suffix, mult in _SIZE_SUFFIXES:
        if t.endswith(suffix):
            return int(float(t[: -len(suffix)]) * mult)
    return int(t)


_ALLOWED_PARAMS = {
    "ioerror": {"p", "from", "times", "scope"},
    "delay": {"ms", "p", "from", "times", "scope"},
    "bitrot": {"count", "p", "scope"},
    "torn": {"after"},
}


class FaultSpec:
    """One parsed ``scope:kind@params`` spec."""

    __slots__ = ("scope", "kind", "params", "chunk", "text")

    def __init__(self, scope: str, kind: str, params: dict, text: str):
        self.scope = scope
        self.kind = kind
        self.params = params
        self.text = text
        self.chunk = (
            int(scope[len("chunk"):])
            if scope.startswith("chunk") else None
        )

    @classmethod
    def parse(cls, token: str) -> "FaultSpec":
        token = token.strip()
        head, _, tail = token.partition("@")
        scope, sep, kind = head.partition(":")
        if not sep or not scope or not kind:
            raise ValueError(
                f"bad fault spec {token!r}: want <scope>:<kind>[@k=v,...]"
            )
        scope, kind = scope.strip().lower(), kind.strip().lower()
        if scope.startswith("chunk"):
            if not scope[len("chunk"):].isdigit():
                raise ValueError(
                    f"bad fault scope {scope!r}: chunk scope is chunk<N>"
                )
        elif scope not in ("read", "write", "scrub"):
            raise ValueError(
                f"bad fault scope {scope!r}: "
                "want read|write|scrub|chunk<N>"
            )
        if kind not in _ALLOWED_PARAMS:
            raise ValueError(
                f"bad fault kind {kind!r}: want "
                f"{'|'.join(sorted(_ALLOWED_PARAMS))}"
            )
        if kind == "torn" and scope != "write":
            raise ValueError(f"torn faults are write-scope only: {token!r}")
        if kind == "bitrot" and scope == "write":
            raise ValueError(
                f"bitrot faults fire at read boundaries: {token!r}"
            )
        params: dict = {}
        if tail:
            for kv in tail.split(","):
                key, sep2, val = kv.partition("=")
                key = key.strip().lower()
                if not sep2 or key not in _ALLOWED_PARAMS[kind]:
                    raise ValueError(
                        f"bad fault param {kv!r} for kind {kind!r} "
                        f"(allowed: {sorted(_ALLOWED_PARAMS[kind])})"
                    )
                if key in ("after",):
                    params[key] = _parse_size(val)
                elif key in ("from", "times", "count"):
                    params[key] = int(val)
                elif key == "scope":
                    val = val.strip().lower()
                    if val not in _READ_SCOPES:
                        raise ValueError(
                            f"fault scope= pin must be read|scrub: {kv!r}"
                        )
                    params[key] = val
                else:  # p, ms
                    params[key] = float(val)
        if kind == "delay" and "ms" not in params:
            raise ValueError(f"delay fault needs ms=: {token!r}")
        if kind == "torn" and "after" not in params:
            raise ValueError(f"torn fault needs after=: {token!r}")
        p = params.get("p", 1.0)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault probability p={p} outside [0, 1]")
        return cls(scope, kind, params, token)

    def matches_read(self, scope: str, index: int | None) -> bool:
        if self.chunk is not None:
            if scope not in _READ_SCOPES or index != self.chunk:
                return False
            pin = self.params.get("scope")
            return pin is None or pin == scope
        return self.scope == scope


class FaultPlan:
    """A parsed fault schedule with deterministic per-boundary decisions.

    Thread-safe; all mutable state (per-target call counters, per-lane
    byte accumulators, per-spec fire counts) sits under one lock.  The
    ``injected`` dict mirrors the ``rs_faults_injected_total`` series so
    tests can assert without enabling the metrics registry.
    """

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self._read_specs = [
            s for s in self.specs
            if s.kind in ("ioerror", "delay")
            and (s.chunk is not None or s.scope in _READ_SCOPES)
        ]
        self._bitrot_specs = [s for s in self.specs if s.kind == "bitrot"]
        self._write_specs = [
            s for s in self.specs if s.scope == "write"
        ]
        self._lock = threading.Lock()
        self._calls: dict[tuple, int] = {}
        self._lane_bytes: dict[str, int] = {}
        self._fires: dict[int, int] = {}
        self.injected: dict[tuple, int] = {}

    # -- deterministic decisions ---------------------------------------------

    def _draw(self, *key) -> float:
        h = zlib.crc32(repr((self.seed,) + key).encode())
        return h / 2 ** 32

    @staticmethod
    def _target(path) -> str:
        # Basename keying: a chaos run in a different temp dir must replay
        # the same schedule.
        return os.path.basename(path) if path else "<anon>"

    def _next_call(self, scope: str, name: str) -> int:
        with self._lock:
            key = (scope, name)
            n = self._calls.get(key, 0) + 1
            self._calls[key] = n
            return n

    def _take_fire(self, spec: FaultSpec) -> bool:
        """Respect the spec's ``times=`` cap (unlimited when absent)."""
        times = spec.params.get("times")
        with self._lock:
            fired = self._fires.get(id(spec), 0)
            if times is not None and fired >= times:
                return False
            self._fires[id(spec)] = fired + 1
            return True

    def _record(self, kind: str, scope: str, target: str,
                index: int | None) -> None:
        with self._lock:
            key = (kind, scope)
            self.injected[key] = self.injected.get(key, 0) + 1
        _metrics.counter(
            "rs_faults_injected_total", "faults fired by the injection plane"
        ).labels(kind=kind, scope=scope).inc()
        _tracing.instant(
            "fault", lane="faults", kind=kind, scope=scope,
            target=target, index=index,
        )

    # -- boundary hooks ------------------------------------------------------

    def on_read(self, path, index: int | None = None,
                scope: str = "read") -> None:
        """One read-boundary crossing; may sleep (delay) or raise
        :class:`InjectedReadError` (ioerror)."""
        name = self._target(path)
        n = self._next_call(scope, name)
        for spec in self._read_specs:
            if not spec.matches_read(scope, index):
                continue
            if n < spec.params.get("from", 1):
                continue
            if self._draw(spec.kind, scope, name, n) >= spec.params.get(
                "p", 1.0
            ):
                continue
            if not self._take_fire(spec):
                continue
            self._record(spec.kind, scope, name, index)
            if spec.kind == "delay":
                time.sleep(spec.params["ms"] / 1000.0)
            else:
                raise InjectedReadError("ioerror", scope, name, index)

    def corrupt_read(self, path, index: int | None, arr,
                     scope: str = "read"):
        """Apply matching bitrot specs to read bytes; returns ``arr``
        untouched when none fire, else a corrupted COPY (the on-disk file
        is not modified — this models rot between platter and host).
        ``scope`` distinguishes decode reads from scrub CRC reads, so
        ``read:bitrot`` and ``scrub:bitrot`` target their own boundary.

        Boundary note: bitrot fires at WHOLE-CHUNK reads through
        ``api._open_chunk`` — scrub/verify CRC passes, decode survivor
        opens, and the native-passthrough row copies that read those
        views.  The streaming segment gathers read through their own fds
        (native pread) and do not see this corruption — to exercise
        decode-INPUT rot end to end, corrupt the file on disk the way
        ``rs chaos`` does.  (On toolchain-less builds the fallback
        gather reads the opened views, so decode inputs may see the rot
        there too — a test-plane edge, not a contract.)"""
        if not self._bitrot_specs:
            return arr
        name = self._target(path)
        # Per-crossing draws on a dedicated counter family (the caller's
        # on_read already consumed this crossing's (scope, name) count),
        # so p= is a fresh coin per read like ioerror/delay, and read-
        # vs scrub-boundary specs draw independently.
        n = self._next_call(f"bitrot@{scope}", name)
        out = arr
        for spec in self._bitrot_specs:
            if not spec.matches_read(scope, index):
                continue
            if self._draw("bitrot", scope, name, n) >= spec.params.get(
                "p", 1.0
            ):
                continue
            nbits = len(out) * 8
            if nbits == 0:
                continue
            if not self._take_fire(spec):
                continue
            import numpy as np

            if out is arr:
                out = np.array(arr, dtype=np.uint8, copy=True)
            count = max(1, spec.params.get("count", 1))
            positions = set()
            salt = 0
            while len(positions) < min(count, nbits):
                bit = int(self._draw("bitrot_pos", scope, name, n,
                                     len(positions), salt) * nbits) % nbits
                salt += 1
                positions.add(bit)
            for bit in sorted(positions):
                out[bit // 8] ^= 1 << (bit % 8)
            self._record("bitrot", scope, name, index)
        return out

    def on_write(self, lane: str, nbytes: int = 0) -> None:
        """One write-lane crossing of ``nbytes`` attempted bytes; may
        sleep, raise a transient :class:`InjectedWriteError` (ioerror) or
        a fatal one (torn — the lane's cumulative attempted bytes crossed
        ``after=``, and stays dead)."""
        if not self._write_specs:
            return
        with self._lock:
            before = self._lane_bytes.get(lane, 0)
            self._lane_bytes[lane] = before + max(0, nbytes)
        n = self._next_call("write", lane)
        for spec in self._write_specs:
            if spec.kind == "torn":
                if before + max(0, nbytes) > spec.params["after"]:
                    self._record("torn", "write", lane, None)
                    raise InjectedWriteError(
                        "torn", "write", lane, transient=False
                    )
                continue
            if n < spec.params.get("from", 1):
                continue
            if self._draw(spec.kind, "write", lane, n) >= spec.params.get(
                "p", 1.0
            ):
                continue
            if not self._take_fire(spec):
                continue
            self._record(spec.kind, "write", lane, None)
            if spec.kind == "delay":
                time.sleep(spec.params["ms"] / 1000.0)
            else:
                raise InjectedWriteError("ioerror", "write", lane)

    def describe(self) -> str:
        return ";".join(s.text for s in self.specs)


def parse_plan(text: str, seed: int = 0) -> FaultPlan:
    """Parse a full ``RS_FAULTS`` spec string into a :class:`FaultPlan`.
    Raises ``ValueError`` on any malformed spec (fail loudly at parse
    time, not silently at the first boundary)."""
    specs = [
        FaultSpec.parse(tok)
        for tok in text.replace("\n", ";").split(";")
        if tok.strip()
    ]
    if not specs:
        raise ValueError(f"empty fault spec {text!r}")
    return FaultPlan(specs, seed=seed)


# -- module-level plane (the hook surface) -----------------------------------
#
# The disabled path is the contract: with RS_FAULTS unset and no activate()
# override, active() is one env read returning the shared None — nothing
# parses, nothing allocates, no per-call state.  Guarded by
# tests/test_resilience.py::test_disabled_fault_plane_is_noop.

_OVERRIDE: FaultPlan | None = None
_CACHE_KEY: tuple | None = None
_CACHE_PLAN: FaultPlan | None = None
_STATE_LOCK = threading.Lock()


def env_seed() -> int:
    """The ``RS_FAULTS_SEED`` env seed (0 when unset or malformed) — the
    one parse shared by :func:`active` and the CLI's ``--faults``."""
    try:
        return int(os.environ.get("RS_FAULTS_SEED", "0"))
    except ValueError:
        return 0


def active() -> FaultPlan | None:
    """The live plan: an :func:`activate` override, else the parsed (and
    cached) ``RS_FAULTS`` env plan, else None."""
    plan = _OVERRIDE
    if plan is not None:
        return plan
    text = os.environ.get("RS_FAULTS")
    if not text:
        return None
    seed = env_seed()
    global _CACHE_KEY, _CACHE_PLAN
    key = (text, seed)
    with _STATE_LOCK:
        if _CACHE_KEY != key:
            _CACHE_PLAN = parse_plan(text, seed=seed)
            _CACHE_KEY = key
        return _CACHE_PLAN


@contextmanager
def activate(plan: FaultPlan | None):
    """Install ``plan`` as the process's fault plane for the block (the
    chaos harness's per-iteration scoping; nests by restoring the prior
    override)."""
    global _OVERRIDE
    prev = _OVERRIDE
    _OVERRIDE = plan
    try:
        yield plan
    finally:
        _OVERRIDE = prev


def on_read(path, index: int | None = None, scope: str = "read") -> None:
    plan = active()
    if plan is not None:
        plan.on_read(path, index=index, scope=scope)


def on_reads(paths, indices, scope: str = "read") -> None:
    """Per-survivor read hook for the segment-gather stages (one boundary
    crossing per chunk per segment)."""
    plan = active()
    if plan is not None:
        for path, index in zip(paths, indices):
            plan.on_read(path, index=index, scope=scope)


def corrupt(path, index, arr, scope: str = "read"):
    plan = active()
    if plan is None:
        return arr
    return plan.corrupt_read(path, index, arr, scope=scope)


def on_write(lane: str, nbytes: int = 0) -> None:
    plan = active()
    if plan is not None:
        plan.on_write(lane, nbytes)
