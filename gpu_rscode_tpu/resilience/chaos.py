"""Seeded chaos harness — ``rs chaos`` (docs/RESILIENCE.md).

The differential loop the whole resilience subsystem is verified by:

    seeded encode -> corrupt per schedule -> scrub / auto-decode / repair
    -> differential-check every output against the native oracle

Every iteration is a pure function of ``(seed, iteration)``: the config
(k, p, w, size), the file bytes, the corruption schedule (bitrot /
torn-write truncation / unlink) and the fault plan injected during the
recovery phase (read ioerror, delay, chunk-scoped mid-stream faults) all
derive from one ``random.Random`` stream, and the fault plane's own
decisions hash from the same derived seed — so ``rs chaos --seed S`` is
bit-reproducible: the same seed yields the same schedule and the same
pass/fail verdict every run, anywhere (targets are keyed by basename,
never by temp-dir path).

``--silent`` selects the SILENT corruption class instead
(:func:`plan_silent_iteration`): CRC-less bitrot proven recovered — or
refused — by the error-locating decode path (gf_decode/,
docs/RESILIENCE.md "Error location").  Its schedules derive from their
own seed stream, so the classic classes' digests are unchanged by its
existence.

Checks per iteration (any miss is a failure):

* encode differential: every chunk file byte-equals the native oracle's
  encode of the same data (``native.gemm`` for w=8 — the cpu-rs oracle —
  or the GF(2^16) host oracle for wide symbols);
* scrub exactness: ``scan_file`` reports exactly the damaged chunks, and
  its ``decodable`` verdict matches the schedule's damage count vs p;
* recoverable archives: ``auto_decode_file`` output byte-equals the
  original AND an independent oracle decode of the conf it chose;
  ``repair_file`` rebuilds exactly the damaged set and leaves every
  chunk byte-equal to the oracle encode;
* unrecoverable archives (damage > p): decode and repair must raise
  (never fabricate bytes), and surviving chunks must be left untouched.

A failing iteration is shrunk greedily — drop one schedule event (or the
fault plan) at a time, keep what still fails — and reported as ONE line::

    REPRODUCE: {"seed": S, "iter": I, "k": .., "events": [..], ...}

which ``rs chaos --repro '<that json>'`` replays directly (``--seed S
--only I`` replays the unshrunk original).  Outcomes are recorded through
the run ledger (``RS_RUNLOG``, obs/runlog.py) as ``op="chaos_iter"``
records plus the ``rs_chaos_iterations_total{verdict}`` counter.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
from contextlib import contextmanager, nullcontext

from ..obs import metrics as _metrics, runlog as _runlog
from . import faults as _faults, retry as _retry

# Small segments force multi-segment streaming even for the harness's
# small files, so the mid-stream (degraded decode) paths actually run.
_SEGMENT_BYTES = 4096

# The bit-reproducibility contract requires verdicts to be a function of
# the seed ALONE, so every knob the recovery path reads from the env is
# pinned for the iteration's duration: ambient RS_RETRY_* would change
# how many injected faults are absorbed (RS_RETRY_ATTEMPTS=0 fails seeds
# verified green; a high value silently skips the degraded-swap path the
# times= budgets are tuned to), and an ambient RS_FAULTS would stack a
# second schedule under iterations that planned none.
_PINNED_ENV = {
    "RS_FAULTS": None,          # the iteration's plan activates explicitly
    "RS_FAULTS_SEED": None,
    "RS_RETRY_ATTEMPTS": "3",   # the default the times= fire budgets match
    "RS_RETRY_BASE_MS": "1",
    "RS_RETRY_MAX_MS": "20",
    "RS_RETRY_SEED": "0",
    "RS_RETRY_BUDGET": "256",
    "RS_RETRY_RESELECT": "3",
    "RS_RETRY_SUBSET_ATTEMPTS": "3",
    # The silent class's verdicts hinge on the locate escalation rung:
    # an ambient RS_LOCATE=off would flip every recoverable silent
    # iteration to a failure.  Pin the default (auto).
    "RS_LOCATE": None,
    # The update class drives the crash knob itself, per scheduled op;
    # an ambient value would tear every un-scheduled update too.
    "RS_UPDATE_CRASH": None,
    # The grouped-update class's torn groups must tear as ONE window
    # group: an ambient small window would split a scheduled group into
    # several commits, so the "torn group rolls back ALL edits" check
    # would see the earlier sub-groups legitimately committed.
    "RS_UPDATE_GROUP_WINDOW": None,
    # The object class's schedules carry their own stripe/compaction
    # geometry in the config; ambient store knobs would change which
    # puts roll stripes and which archives compact — verdict drift.
    "RS_STORE_STRIPE_BYTES": None,
    "RS_STORE_COMPACT_DEAD_FRAC": None,
    "RS_STORE_K": None,
    "RS_STORE_P": None,
    # Index snapshots must FIRE under the object class's torn-op
    # schedules (a checkpoint every 32 records lands several per
    # iteration) without moving any verdict: the snapshot plane changes
    # how the index is reloaded, never what it says — the class digest
    # is the proof.  An ambient disable/keep would skip or prune that
    # coverage.
    "RS_STORE_SNAPSHOT_RECORDS": "32",
    "RS_STORE_SNAPSHOT_KEEP": None,
    "RS_STORE_SNAPSHOT_DISABLE": None,
    # The maint class drives the controller directly (and the crash
    # knob per schedule); ambient maint knobs would change job pacing,
    # lease lifetimes or inject crashes into every other class's
    # repairs — verdict drift.
    "RS_MAINT": None,
    "RS_MAINT_TENANT": None,
    "RS_MAINT_BYTES_PER_S": None,
    "RS_MAINT_BURN_PAUSE": None,
    "RS_MAINT_RESUME": None,
    "RS_MAINT_LEASE_S": None,
    "RS_MAINT_INTERVAL_S": None,
    "RS_MAINT_CRASH": None,
}


@contextmanager
def _pinned_env():
    saved = {k: os.environ.get(k) for k in _PINNED_ENV}
    try:
        for k, v in _PINNED_ENV.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class ChaosFailure(Exception):
    """One iteration's verdict went wrong; ``cfg`` is the iteration
    config that reproduces it."""

    def __init__(self, cfg: dict, what: str):
        self.cfg = cfg
        self.what = what
        super().__init__(f"iter {cfg.get('iter')}: {what}")


def _iter_rng(seed: int, i: int) -> random.Random:
    return random.Random(f"rs-chaos:{seed}:{i}")


def plan_silent_iteration(seed: int, i: int, max_bytes: int = 49152) -> dict:
    """The ``silent`` corruption class: bitrot with CRC verification
    DISABLED (archives encoded without checksum lines), recovered by the
    error-locating decode path (gf_decode/, ``rs decode --locate``).

    Schedule grammar: every event is ``{"kind": "silent", "chunk": c,
    ...}`` with either sparse distinct bit flips (``count``) or a dense
    random-byte window (``dense: [off, len]``).  Two flavors per seed
    stream:

    * recoverable — at most ``t = floor(p/2)`` damaged chunks, sparse
      flips: every symbol column carries <= t errors, so the locate
      decoder must recover BIT-IDENTICALLY and the syndrome scrub must
      attribute exactly the damaged chunk set (no CRCs involved);
    * unrecoverable (> t) — t+1.. chunks damaged over one SHARED dense
      window with nonzero random bytes: the window's columns all carry
      > t errors, so decode must FAIL LOUDLY (never fabricate bytes) and
      the scrub verdict must be ``unlocatable``.

    Deterministic from ``(seed, i)`` on its own derived stream
    (``rs-chaos-silent:*``) — the classic classes' schedules (seeded
    from ``rs-chaos:*``) are byte-identical with or without this class
    existing, so pinned CI seeds keep their verdict digests.
    """
    rng = random.Random(f"rs-chaos-silent:{seed}:{i}")
    k = rng.randint(2, 6)
    p = rng.randint(2, 4)          # p >= 2: t >= 1, location possible
    w = 16 if rng.random() < 0.2 else 8
    size = rng.randint(256, max_bytes)
    t = p // 2
    overkill = rng.random() < 0.3
    if overkill:
        n_damage = rng.randint(t + 1, min(k + p, p + 2))
    else:
        n_damage = rng.randint(0, t)
    targets = sorted(rng.sample(range(k + p), n_damage))
    events = []
    if overkill and targets:
        # One SHARED window across all victims: those columns all carry
        # n_damage > t errors — provably past the locate bound.
        from ..utils.fileformat import chunk_size_for

        chunk = chunk_size_for(size, k, w // 8)
        ln = max(w // 8, min(chunk, rng.randint(16, 512)))
        off = rng.randint(0, max(0, chunk - ln))
        for c in targets:
            events.append({"kind": "silent", "chunk": c,
                           "dense": [off, ln]})
    else:
        for c in targets:
            events.append({"kind": "silent", "chunk": c,
                           "count": rng.randint(1, 12)})
    return {
        "seed": seed,
        "iter": i,
        "mode": "silent",
        "k": k,
        "p": p,
        "w": w,
        "size": size,
        "events": events,
        "faults": "",
    }


def plan_update_iteration(seed: int, i: int, max_bytes: int = 49152) -> dict:
    """The ``update`` workload class (``rs chaos --update``): a random
    schedule of in-place edits, appends and TORN ops (RS_UPDATE_CRASH
    at a random stage) against one archive, on its own derived seed
    stream (``rs-chaos-update:*`` — classic/silent digests unchanged).

    Validation per the ROADMAP's stated contract: after the schedule,
    the delta-updated/appended archive must be byte-identical — every
    chunk file AND every CRC line — to a from-scratch full re-encode of
    the final logical bytes, scrub must report it fully healthy, and
    auto-decode must return exactly those bytes.  Every torn op must
    roll back to the byte-exact pre-op archive via the journal.
    """
    rng = random.Random(f"rs-chaos-update:{seed}:{i}")
    k = rng.randint(2, 6)
    p = rng.randint(1, 3)
    w = 16 if rng.random() < 0.2 else 8
    layout = "interleaved" if rng.random() < 0.6 else "row"
    size = rng.randint(64, max_bytes)
    sym = w // 8
    from ..utils.fileformat import chunk_size_for_layout

    chunk0 = chunk_size_for_layout(size, k, sym, layout)
    total = size
    ops = []
    for _ in range(rng.randint(1, 5)):
        kinds = ["update", "update", "crash_update"]
        if layout == "interleaved":
            kinds += ["append", "append", "crash_append"]
        else:
            # Row-major appends are slack-bounded: schedule one only
            # while it provably fits (chunk size unchanged).
            if k * chunk0 - total > 0:
                kinds.append("append")
        kind = rng.choice(kinds)
        if kind.endswith("update"):
            at = rng.randrange(0, total)
            ln = rng.randint(1, min(4096, total - at))
            op = {"op": "update", "at": at, "len": ln}
        else:
            ln = (
                rng.randint(1, 4096) if layout == "interleaved"
                else rng.randint(1, k * chunk0 - total)
            )
            op = {"op": "append", "len": ln}
        if kind.startswith("crash"):
            op["crash"] = rng.choice(
                ["after_journal", "mid_patch", "before_commit"]
            )
        elif op["op"] == "append":
            total += ln
        ops.append(op)
    faults = ""
    if rng.random() < 0.3:
        # Transient write hiccups on the patch lane: the bounded retry
        # plane must absorb them without changing any verdict.
        faults = "write:delay@ms=1,p=0.05"
    return {
        "seed": seed,
        "iter": i,
        "mode": "update",
        "k": k,
        "p": p,
        "w": w,
        "layout": layout,
        "size": size,
        "events": ops,
        "faults": faults,
    }


def plan_update_group_iteration(seed: int, i: int,
                                max_bytes: int = 49152) -> dict:
    """The GROUPED update workload class (``rs chaos --update --group``):
    random schedules of group-committed edit batches against one archive
    — each event is one ``api.update_file_many`` call of 1..6 mixed
    edits/appends, some torn (RS_UPDATE_CRASH at a random stage
    mid-group), on its OWN derived seed stream
    (``rs-chaos-update-group:*`` — the classic/silent/update classes'
    schedules and digests are untouched by this class existing).

    Validation per group: a torn group must roll back EVERY edit in the
    batch byte-exactly (one journal covers the whole window group); a
    committed group must leave the archive byte-identical to applying
    its edits sequentially (the tracked mirror), healthy under scrub,
    and — after the whole schedule — chunk- and CRC-identical to a
    from-scratch re-encode twin of the final logical bytes."""
    rng = random.Random(f"rs-chaos-update-group:{seed}:{i}")
    k = rng.randint(2, 6)
    p = rng.randint(1, 3)
    w = 16 if rng.random() < 0.2 else 8
    layout = "interleaved" if rng.random() < 0.6 else "row"
    size = rng.randint(256, max_bytes)
    from ..utils.fileformat import chunk_size_for_layout

    chunk0 = chunk_size_for_layout(size, k, w // 8, layout)
    total = size
    events = []
    for _ in range(rng.randint(1, 4)):
        gtotal = total
        edits = []
        for _ in range(rng.randint(1, 6)):
            kinds = ["update", "update"]
            if layout == "interleaved":
                kinds.append("append")
            elif k * chunk0 - gtotal > 0:
                kinds.append("append")
            kind = rng.choice(kinds)
            if kind == "update":
                at = rng.randrange(0, gtotal)
                ln = rng.randint(1, min(2048, gtotal - at))
                edits.append({"op": "update", "at": at, "len": ln})
            else:
                ln = (
                    rng.randint(1, 2048) if layout == "interleaved"
                    else rng.randint(1, k * chunk0 - gtotal)
                )
                edits.append({"op": "append", "len": ln})
                gtotal += ln
        ev = {"group": edits}
        if rng.random() < 0.35:
            ev["crash"] = rng.choice(
                ["after_journal", "mid_patch", "before_commit"]
            )
        else:
            total = gtotal  # only a committed group advances the size
        events.append(ev)
    faults = ""
    if rng.random() < 0.3:
        faults = "write:delay@ms=1,p=0.05"
    return {
        "seed": seed,
        "iter": i,
        "mode": "update_group",
        "k": k,
        "p": p,
        "w": w,
        "layout": layout,
        "size": size,
        "events": events,
        "faults": faults,
    }


def plan_object_iteration(seed: int, i: int,
                          max_bytes: int = 49152) -> dict:
    """The OBJECT-STORE workload class (``rs chaos --object``): seeded
    PUT/DELETE/compact schedules against one bucket, some ops torn at a
    random ``RS_UPDATE_CRASH`` stage, on its OWN derived seed stream
    (``rs-chaos-object:*`` — every other class's schedules and digests
    are untouched).

    Contract checked per event and at the end (store/bucket.py): the
    bucket's live contents stay byte-identical to a sequential mirror
    that applies exactly the COMMITTED ops — a torn PUT batch commits
    nothing (its index records are invalidated through the archive's
    journal rollback: the index never references bytes a rolled-back
    group wrote), a torn DELETE is committed (the tombstone fsyncs
    before the zeroing patch), and a torn compaction leaves either the
    old archive or the new locations fully live.  Every GET is
    byte-exact or a clean 404 — never silently wrong."""
    rng = random.Random(f"rs-chaos-object:{seed}:{i}")
    k = rng.randint(2, 5)
    p = rng.randint(1, 3)
    w = 16 if rng.random() < 0.2 else 8
    stripe_bytes = rng.choice([4096, 8192, 16384])
    keys = [f"obj{j}" for j in range(rng.randint(3, 8))]
    put_ever: set[str] = set()
    events = []
    for _ in range(rng.randint(5, 12)):
        roll = rng.random()
        if roll < 0.55 or not put_ever:
            batch = [
                {"key": rng.choice(keys),
                 "len": rng.randint(64, min(4096, max_bytes))}
                for _ in range(rng.randint(1, 4))
            ]
            ev = {"op": "put", "batch": batch}
            put_ever.update(b["key"] for b in batch)
        elif roll < 0.8:
            ev = {"op": "delete", "key": rng.choice(sorted(put_ever))}
        else:
            ev = {"op": "compact", "force": rng.random() < 0.5}
        if rng.random() < 0.3:
            ev["crash"] = rng.choice(
                ["after_journal", "mid_patch", "before_commit"]
            )
        events.append(ev)
    return {
        "seed": seed,
        "iter": i,
        "mode": "object",
        "k": k,
        "p": p,
        "w": w,
        "stripe_bytes": stripe_bytes,
        "keys": keys,
        "events": events,
        "faults": "",
    }


def plan_health_iteration(seed: int, i: int, max_bytes: int = 49152) -> dict:
    """The ``health`` convergence class: prove the fleet durability
    plane (obs/health.py, docs/HEALTH.md) tracks reality end to end —
    induced damage surfaces at the TOP of the risk ranking with the
    exact per-chunk damage map, repair clears it, and replaying the
    damage ledger is restart-stable (snapshot+delta replay byte-equal to
    pure-delta replay from genesis).

    Each iteration runs a small fleet (2-4 archives, one designated
    victim) against its own private ledger; damage is 1..p chunks of the
    victim — always within the repair bound, because the contract under
    test is CONVERGENCE (damage -> ranked -> repaired -> cleared), the
    unrecoverable verdicts belong to the classic/silent classes.

    Deterministic from ``(seed, i)`` on its own derived stream
    (``rs-chaos-health:*``); verdict rows carry only ints/bools (never
    risk floats or timestamps — risk depends on wall-clock scrub age),
    so the verdict digest stays a function of the seed alone.
    """
    rng = random.Random(f"rs-chaos-health:{seed}:{i}")
    k = rng.randint(2, 5)
    p = rng.randint(1, 3)
    w = 16 if rng.random() < 0.2 else 8
    n_archives = rng.randint(2, 4)
    sizes = [rng.randint(256, max_bytes) for _ in range(n_archives)]
    victim = rng.randrange(n_archives)
    n_damage = rng.randint(1, p)
    targets = sorted(rng.sample(range(k + p), n_damage))
    events = []
    for c in targets:
        kind = rng.choice(("bitrot", "torn", "unlink"))
        if kind == "bitrot":
            events.append({"kind": "bitrot", "chunk": c,
                           "count": rng.randint(1, 64)})
        elif kind == "torn":
            events.append({"kind": "torn", "chunk": c,
                           "keep_frac": rng.random() * 0.9})
        else:
            events.append({"kind": "unlink", "chunk": c})
    return {
        "seed": seed,
        "iter": i,
        "mode": "health",
        "k": k,
        "p": p,
        "w": w,
        "archives": n_archives,
        "sizes": sizes,
        "victim": victim,
        "events": events,
        "faults": "",
    }


def plan_maint_iteration(seed: int, i: int, max_bytes: int = 49152) -> dict:
    """The MAINT convergence class (``rs chaos --maint``): prove the
    background-maintenance plane (maint/controller.py, docs/MAINT.md)
    converges through crashes.  Each iteration builds a small fleet
    with one damaged victim plus a store bucket driven dead-heavy by a
    seeded put/delete schedule, then drains a :class:`MaintController`
    that may be killed (``RS_MAINT_CRASH``) at a random job stage —
    after claiming a repair, mid-repair before the clean rescan, after
    claiming a scrub, or before/after a compaction.  A second
    controller with the SAME owner (the restarted daemon) must then
    converge: empty work queue, zero pending compactions, the victim's
    chunks byte-identical to their pre-damage snapshot, the bucket's
    live objects byte-identical to a sequential mirror, and
    snapshot+delta ledger replay equal to pure-delta replay even with a
    live claim checkpointed mid-history.

    Deterministic from ``(seed, i)`` on its own derived stream
    (``rs-chaos-maint:*`` — the classic/silent/update/object/health
    schedules and digests are untouched by this class existing).
    """
    rng = random.Random(f"rs-chaos-maint:{seed}:{i}")
    k = rng.randint(2, 4)
    p = rng.randint(1, 2)
    w = 8
    n_archives = rng.randint(2, 3)
    sizes = [rng.randint(256, max_bytes) for _ in range(n_archives)]
    victim = rng.randrange(n_archives)
    n_damage = rng.randint(1, p)
    targets = sorted(rng.sample(range(k + p), n_damage))
    events = []
    for c in targets:
        kind = rng.choice(("bitrot", "torn", "unlink"))
        if kind == "bitrot":
            events.append({"kind": "bitrot", "chunk": c,
                           "count": rng.randint(1, 64)})
        elif kind == "torn":
            events.append({"kind": "torn", "chunk": c,
                           "keep_frac": rng.random() * 0.9})
        else:
            events.append({"kind": "unlink", "chunk": c})
    # Bucket schedule: small stripes + deleting most objects drives
    # sealed archives past RS_STORE_COMPACT_DEAD_FRAC deterministically.
    stripe_bytes = rng.choice([4096, 8192])
    n_objects = rng.randint(4, 7)
    puts = [{"key": f"o{j}", "len": rng.randint(64, 2048)}
            for j in range(n_objects)]
    keep = rng.randint(1, 2)
    kept = set(rng.sample([pt["key"] for pt in puts], keep))
    deletes = [pt["key"] for pt in puts if pt["key"] not in kept]
    crash = rng.choice([None, "repair:claimed", "repair:mid",
                        "scrub:claimed", "compact:claimed",
                        "compact:done"])
    return {
        "seed": seed,
        "iter": i,
        "mode": "maint",
        "k": k,
        "p": p,
        "w": w,
        "archives": n_archives,
        "sizes": sizes,
        "victim": victim,
        "events": events,
        "stripe_bytes": stripe_bytes,
        "puts": puts,
        "deletes": deletes,
        "crash": crash,
        "faults": "",
    }


def plan_iteration(seed: int, i: int, max_bytes: int = 49152) -> dict:
    """The deterministic schedule for iteration ``i`` of master ``seed``."""
    rng = _iter_rng(seed, i)
    k = rng.randint(2, 6)
    p = rng.randint(1, 3)
    w = 16 if rng.random() < 0.2 else 8
    size = rng.randint(1, max_bytes)
    # ~15% of iterations damage MORE than p chunks: the harness must also
    # prove the stack says "unrecoverable" instead of fabricating bytes.
    overkill = rng.random() < 0.15
    n_damage = (
        rng.randint(p + 1, min(k + p, p + 2)) if overkill
        else rng.randint(0, p)
    )
    targets = sorted(rng.sample(range(k + p), n_damage))
    events = []
    for t in targets:
        kind = rng.choice(["bitrot", "torn", "unlink"])
        if kind == "bitrot":
            events.append({"kind": "bitrot", "chunk": t,
                           "count": rng.randint(1, 16)})
        elif kind == "torn":
            # A torn write: only a prefix of the chunk landed.
            events.append({"kind": "torn", "chunk": t,
                           "keep_frac": round(rng.random() * 0.9, 3)})
        else:
            events.append({"kind": "unlink", "chunk": t})
    fault_bits = []
    if rng.random() < 0.5:
        fault_bits.append(
            f"read:ioerror@p={round(rng.uniform(0.005, 0.03), 4)}"
        )
    if rng.random() < 0.25:
        fault_bits.append("read:delay@ms=1,p=0.05")
    healthy_natives = [c for c in range(k) if c not in targets]
    if (
        rng.random() < 0.5
        and 0 < len(targets) < p          # spare healthy chunks exist
        and any(t < k for t in targets)   # recovery decode, not passthrough
        and healthy_natives
    ):
        # A healthy NATIVE that dies MID-STREAM (its open is fine, the
        # later segment gathers fail, bounded by times=): natives-first
        # selection guarantees it is a chosen survivor of a recovery
        # decode, so the fault really fires and exercises degraded
        # decode's in-place survivor swap + resume.  Pinned to the read
        # boundary so scrub CRC passes don't consume the fire budget.
        victim = rng.choice(healthy_natives)
        fault_bits.append(
            f"chunk{victim}:ioerror@from=2,times=4,scope=read"
        )
    return {
        "seed": seed,
        "iter": i,
        "k": k,
        "p": p,
        "w": w,
        "size": size,
        "events": events,
        "faults": ";".join(fault_bits),
    }


# -- oracle -------------------------------------------------------------------


def _oracle_chunks(data: bytes, k: int, p: int, w: int, total_mat):
    """Every chunk's bytes per the native oracle: natives are straight
    zero-padded stripes; parity is the oracle GEMM (``native.gemm`` — the
    cpu-rs reference path — for w=8, the GF host oracle for w=16)."""
    import numpy as np

    from .. import native
    from ..ops.gf import get_field
    from ..utils.fileformat import chunk_size_for

    sym = w // 8
    chunk = chunk_size_for(len(data), k, sym)
    padded = np.zeros(k * chunk, dtype=np.uint8)
    padded[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    natives = padded.reshape(k, chunk)
    gf = get_field(w)
    mat = np.asarray(total_mat, dtype=gf.dtype)[k:]
    if w == 8:
        parity = native.gemm(mat.astype(np.uint8), natives)
    else:
        parity = np.ascontiguousarray(
            gf.matmul(mat, natives.view(np.uint16))
        ).view(np.uint8)
    return [natives[i].tobytes() for i in range(k)] + [
        parity[j].tobytes() for j in range(p)
    ]


def _oracle_decodable(total_mat, healthy, k: int, w: int) -> bool:
    """Ground-truth decodability: some k-subset of the healthy chunks
    inverts under the host oracle.  Exhaustive — the harness's chunk
    counts keep the combination space tiny — so scrub's verdict is
    checked against truth even for non-MDS Vandermonde corners where
    "damage <= p" over-promises."""
    from itertools import combinations

    import numpy as np

    from ..ops.gf import get_field
    from ..ops.inverse import SingularMatrixError, invert_matrix

    if len(healthy) < k:
        return False
    gf = get_field(w)
    mat = np.asarray(total_mat, dtype=gf.dtype)
    for subset in combinations(healthy, k):
        try:
            invert_matrix(mat[list(subset)], gf)
            return True
        except SingularMatrixError:
            continue
    return False


def _oracle_decode(in_file: str, conf_path: str, total_size: int, k: int,
                   w: int, total_mat) -> bytes:
    """Independent host/native reconstruction from the conf the decode
    under test actually used — the differential witness."""
    import numpy as np

    from .. import native
    from ..ops.gf import get_field
    from ..ops.inverse import invert_matrix
    from ..utils.fileformat import (
        chunk_size_for, parse_chunk_index, read_conf,
    )

    sym = w // 8
    chunk = chunk_size_for(total_size, k, sym)
    names = read_conf(conf_path)
    rows = [parse_chunk_index(nm) for nm in names]
    base = os.path.dirname(os.path.abspath(in_file))
    stacked = np.stack([
        np.fromfile(os.path.join(base, os.path.basename(nm)),
                    dtype=np.uint8, count=chunk)
        for nm in names
    ])
    gf = get_field(w)
    sub = np.asarray(total_mat, dtype=gf.dtype)[rows]
    if w == 8:
        inv = native.invert(sub.astype(np.uint8))
        out = native.gemm(inv, stacked)
    else:
        inv = invert_matrix(sub, gf)
        out = np.ascontiguousarray(
            gf.matmul(inv, stacked.view(np.uint16))
        ).view(np.uint8)
    return out.reshape(-1).tobytes()[:total_size]


# -- one iteration ------------------------------------------------------------


def _apply_events(fname: str, events, chunk: int, rng: random.Random) -> None:
    from ..utils.fileformat import chunk_file_name

    for ev in events:
        path = chunk_file_name(fname, ev["chunk"])
        if ev["kind"] == "unlink":
            os.unlink(path)
        elif ev["kind"] == "torn":
            keep = int(chunk * ev["keep_frac"])
            if keep >= chunk:
                keep = max(0, chunk - 1)
            with open(path, "r+b") as fp:
                fp.truncate(keep)
        elif ev["kind"] == "silent":
            # The silent class (CRC-less bitrot): sparse distinct flips,
            # or a dense nonzero-random-byte window shared across the
            # iteration's victims (guarantees > t errors per column in
            # the unrecoverable flavor).
            with open(path, "r+b") as fp:
                buf = bytearray(fp.read())
                if "dense" in ev:
                    off, ln = ev["dense"]
                    for s in range(off, min(off + ln, len(buf))):
                        buf[s] ^= rng.randint(1, 255)
                else:
                    nbits = max(1, len(buf) * 8)
                    for bit in rng.sample(range(nbits),
                                          min(ev["count"], nbits)):
                        buf[bit // 8] ^= 1 << (bit % 8)
                fp.seek(0)
                fp.write(bytes(buf))
        else:  # bitrot
            # DISTINCT positions (capped at the chunk's bit count): with
            # replacement, an even number of hits on one bit nets to
            # zero corruption and the scrub-exactness check would fail
            # on a perfectly healthy stack.
            nbits = max(1, chunk * 8)
            with open(path, "r+b") as fp:
                buf = bytearray(fp.read())
                for bit in rng.sample(range(nbits),
                                      min(ev["count"], nbits)):
                    buf[bit // 8] ^= 1 << (bit % 8)
                fp.seek(0)
                fp.write(bytes(buf))


def _check(cond: bool, cfg: dict, what: str) -> None:
    if not cond:
        raise ChaosFailure(cfg, what)


def run_iteration(cfg: dict, workdir: str, *, keep: bool = False) -> dict:
    """Execute one scheduled iteration under the pinned recovery env
    (verdicts are a function of the seed alone); returns its outcome
    record or raises :class:`ChaosFailure` with the reproducing config."""
    with _pinned_env():
        if cfg.get("mode") == "silent":
            return _run_silent_iteration(cfg, workdir, keep=keep)
        if cfg.get("mode") == "update":
            return _run_update_iteration(cfg, workdir, keep=keep)
        if cfg.get("mode") == "update_group":
            return _run_update_group_iteration(cfg, workdir, keep=keep)
        if cfg.get("mode") == "object":
            return _run_object_iteration(cfg, workdir, keep=keep)
        if cfg.get("mode") == "health":
            return _run_health_iteration(cfg, workdir, keep=keep)
        if cfg.get("mode") == "maint":
            return _run_maint_iteration(cfg, workdir, keep=keep)
        return _run_iteration(cfg, workdir, keep=keep)


def _archive_snapshot(fname: str, n: int) -> list[bytes]:
    """Every chunk file's bytes plus .METADATA — the byte-exact rollback
    witness for torn update/append ops."""
    from ..utils.fileformat import chunk_file_name, metadata_file_name

    out = []
    for c in range(n):
        path = chunk_file_name(fname, c)
        out.append(open(path, "rb").read() if os.path.exists(path) else None)
    out.append(open(metadata_file_name(fname), "rb").read())
    return out


def _run_update_iteration(cfg: dict, workdir: str, *,
                          keep: bool = False) -> dict:
    """One ``update``-class iteration: encode, run the scheduled mix of
    edits / appends / torn ops, and prove the delta math against a
    from-scratch re-encode twin (chunk files AND CRC lines byte-equal),
    plus byte-exact journal rollback for every torn op."""
    from .. import api
    from ..update import SimulatedCrash
    from ..update.journal import journal_path
    from ..utils.fileformat import (
        chunk_file_name, metadata_file_name, read_archive_meta,
    )

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w, size = cfg["k"], cfg["p"], cfg["w"], cfg["size"]
    layout = cfg["layout"]
    base = os.path.join(workdir, f"iter{i}")
    os.makedirs(base, exist_ok=True)
    fname = os.path.join(base, f"chaos_update_{i}.bin")
    data = random.Random(f"rs-chaos-data:{seed}:{i}").randbytes(size)
    ok = False
    try:
        with open(fname, "wb") as fp:
            fp.write(data)
        api.encode_file(
            fname, k, p, checksums=True, w=w, layout=layout,
            segment_bytes=_SEGMENT_BYTES,
        )
        mirror = bytearray(data)
        plan = (
            _faults.parse_plan(cfg["faults"], seed=(seed * 1_000_003 + i))
            if cfg["faults"] else None
        )
        _retry.reset_budget()
        with _faults.activate(plan) if plan else nullcontext():
            for j, op in enumerate(cfg["events"]):
                payload = random.Random(
                    f"rs-chaos-update-data:{seed}:{i}:{j}"
                ).randbytes(op["len"])
                crash = op.get("crash")
                if crash:
                    pre = _archive_snapshot(fname, k + p)
                    os.environ["RS_UPDATE_CRASH"] = crash
                    try:
                        if op["op"] == "update":
                            api.update_file(
                                fname, op["at"], payload,
                                segment_bytes=_SEGMENT_BYTES,
                            )
                        else:
                            api.append_file(
                                fname, payload,
                                segment_bytes=_SEGMENT_BYTES,
                            )
                        _check(False, cfg,
                               f"crash stage {crash} did not fire (op {j})")
                    except SimulatedCrash:
                        pass
                    finally:
                        os.environ.pop("RS_UPDATE_CRASH", None)
                    _check(os.path.exists(journal_path(fname)), cfg,
                           f"torn op {j} left no journal")
                    verdict = api.recover_archive(fname)
                    _check(verdict == "rolled_back", cfg,
                           f"recovery verdict {verdict!r} on torn op {j}")
                    _check(_archive_snapshot(fname, k + p) == pre, cfg,
                           f"torn op {j} did not roll back byte-exact")
                elif op["op"] == "update":
                    api.update_file(
                        fname, op["at"], payload,
                        segment_bytes=_SEGMENT_BYTES,
                    )
                    mirror[op["at"] : op["at"] + op["len"]] = payload
                else:
                    api.append_file(
                        fname, payload, segment_bytes=_SEGMENT_BYTES
                    )
                    mirror += payload
                report = api.scan_file(
                    fname, segment_bytes=_SEGMENT_BYTES
                )
                _check(
                    report["decodable"] is True
                    and not report["corrupt"] and not report["missing"]
                    and not report["pending_journal"],
                    cfg, f"archive unhealthy after op {j}: {report}",
                )
        # The ROADMAP's stated validation: the delta-updated archive is
        # differential-checked byte-identical against a from-scratch
        # full re-encode of the final logical bytes.
        twin = os.path.join(base, f"twin_{i}.bin")
        with open(twin, "wb") as fp:
            fp.write(bytes(mirror))
        api.encode_file(
            twin, k, p, checksums=True, w=w, layout=layout,
            segment_bytes=_SEGMENT_BYTES,
        )
        for c in range(k + p):
            got = open(chunk_file_name(fname, c), "rb").read()
            want = open(chunk_file_name(twin, c), "rb").read()
            _check(got == want, cfg,
                   f"delta-updated chunk {c} != full re-encode twin")
        ma = read_archive_meta(metadata_file_name(fname))
        mb = read_archive_meta(metadata_file_name(twin))
        _check(ma.crcs == mb.crcs and ma.total_size == mb.total_size, cfg,
               "metadata CRCs/size diverge from the re-encode twin")
        out = api.auto_decode_file(
            fname, fname + ".dec", segment_bytes=_SEGMENT_BYTES
        )
        _check(open(out, "rb").read() == bytes(mirror), cfg,
               "decode != tracked logical bytes after the schedule")
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": size,
                "chaos": {
                    "seed": seed, "iter": i, "mode": "update",
                    "layout": layout, "events": cfg["events"],
                    "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "mode": "update", "layout": layout,
        "k": k, "p": p, "w": w, "size": size,
        "ops": [op["op"] + (":torn" if op.get("crash") else "")
                for op in cfg["events"]],
        "final_size": len(mirror),
        "faults": cfg["faults"], "verdict": "pass",
    }


def _run_update_group_iteration(cfg: dict, workdir: str, *,
                                keep: bool = False) -> dict:
    """One grouped-update iteration: encode, run the scheduled sequence
    of group-committed batches (torn groups included), and prove (a)
    every torn group rolls back ALL its edits byte-exactly, (b) every
    committed group equals sequential application (tracked mirror), and
    (c) the final archive is chunk- and CRC-identical to a from-scratch
    re-encode twin."""
    from .. import api
    from ..update import SimulatedCrash
    from ..update.journal import journal_path
    from ..utils.fileformat import (
        chunk_file_name, metadata_file_name, read_archive_meta,
    )

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w, size = cfg["k"], cfg["p"], cfg["w"], cfg["size"]
    layout = cfg["layout"]
    base = os.path.join(workdir, f"iter{i}")
    os.makedirs(base, exist_ok=True)
    fname = os.path.join(base, f"chaos_group_{i}.bin")
    data = random.Random(f"rs-chaos-data:{seed}:{i}").randbytes(size)
    ok = False
    try:
        with open(fname, "wb") as fp:
            fp.write(data)
        api.encode_file(
            fname, k, p, checksums=True, w=w, layout=layout,
            segment_bytes=_SEGMENT_BYTES,
        )
        mirror = bytearray(data)
        plan = (
            _faults.parse_plan(cfg["faults"], seed=(seed * 1_000_003 + i))
            if cfg["faults"] else None
        )
        _retry.reset_budget()
        with _faults.activate(plan) if plan else nullcontext():
            for j, ev in enumerate(cfg["events"]):
                edits = []
                for e, op in enumerate(ev["group"]):
                    payload = random.Random(
                        f"rs-chaos-group-data:{seed}:{i}:{j}:{e}"
                    ).randbytes(op["len"])
                    if op["op"] == "update":
                        edits.append({"op": "update", "at": op["at"],
                                      "data": payload})
                    else:
                        edits.append({"op": "append", "data": payload})
                crash = ev.get("crash")
                if crash:
                    pre = _archive_snapshot(fname, k + p)
                    os.environ["RS_UPDATE_CRASH"] = crash
                    try:
                        api.update_file_many(
                            fname, edits, segment_bytes=_SEGMENT_BYTES
                        )
                        _check(False, cfg,
                               f"crash stage {crash} did not fire "
                               f"(group {j})")
                    except SimulatedCrash:
                        pass
                    finally:
                        os.environ.pop("RS_UPDATE_CRASH", None)
                    _check(os.path.exists(journal_path(fname)), cfg,
                           f"torn group {j} left no journal")
                    verdict = api.recover_archive(fname)
                    _check(verdict == "rolled_back", cfg,
                           f"recovery verdict {verdict!r} on torn "
                           f"group {j}")
                    _check(_archive_snapshot(fname, k + p) == pre, cfg,
                           f"torn group {j} did not roll back ALL "
                           "edits byte-exact")
                else:
                    summary = api.update_file_many(
                        fname, edits, segment_bytes=_SEGMENT_BYTES
                    )
                    _check(summary["groups"] == 1, cfg,
                           f"group {j} split into {summary['groups']} "
                           "commits under the pinned window")
                    # Sequential semantics on the tracked mirror.
                    for e in edits:
                        if e["op"] == "update":
                            at = e["at"]
                            mirror[at : at + len(e["data"])] = e["data"]
                        else:
                            mirror += e["data"]
                report = api.scan_file(fname, segment_bytes=_SEGMENT_BYTES)
                _check(
                    report["decodable"] is True
                    and not report["corrupt"] and not report["missing"]
                    and not report["pending_journal"],
                    cfg, f"archive unhealthy after group {j}: {report}",
                )
        twin = os.path.join(base, f"twin_{i}.bin")
        with open(twin, "wb") as fp:
            fp.write(bytes(mirror))
        api.encode_file(
            twin, k, p, checksums=True, w=w, layout=layout,
            segment_bytes=_SEGMENT_BYTES,
        )
        for c in range(k + p):
            got = open(chunk_file_name(fname, c), "rb").read()
            want = open(chunk_file_name(twin, c), "rb").read()
            _check(got == want, cfg,
                   f"group-updated chunk {c} != full re-encode twin")
        ma = read_archive_meta(metadata_file_name(fname))
        mb = read_archive_meta(metadata_file_name(twin))
        _check(ma.crcs == mb.crcs and ma.total_size == mb.total_size, cfg,
               "metadata CRCs/size diverge from the re-encode twin")
        out = api.auto_decode_file(
            fname, fname + ".dec", segment_bytes=_SEGMENT_BYTES
        )
        _check(open(out, "rb").read() == bytes(mirror), cfg,
               "decode != tracked logical bytes after the schedule")
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": size,
                "chaos": {
                    "seed": seed, "iter": i, "mode": "update_group",
                    "layout": layout, "events": cfg["events"],
                    "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "mode": "update_group", "layout": layout,
        "k": k, "p": p, "w": w, "size": size,
        "groups": [
            f"{len(ev['group'])}" + (":torn" if ev.get("crash") else "")
            for ev in cfg["events"]
        ],
        "final_size": len(mirror),
        "faults": cfg["faults"], "verdict": "pass",
    }


def _run_object_iteration(cfg: dict, workdir: str, *,
                          keep: bool = False) -> dict:
    """One ``object``-class iteration: run the scheduled PUT/DELETE/
    compact sequence (torn ops included) against one bucket, holding a
    sequential mirror of the COMMITTED ops, and prove after every event
    that the bucket's live contents equal the mirror byte-for-byte —
    the index must never reference bytes a rolled-back group wrote, a
    GET is byte-exact or a clean 404, and compaction is all-or-nothing
    (:func:`plan_object_iteration` doc)."""
    from .. import api, store
    from ..update import SimulatedCrash

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w = cfg["k"], cfg["p"], cfg["w"]
    base = os.path.join(workdir, f"iter{i}")
    root = os.path.join(base, "root")
    os.makedirs(root, exist_ok=True)
    mirror: dict[str, bytes] = {}
    ok = False

    def check_state(bucket, what: str) -> None:
        listed = {o["key"] for o in bucket.list_objects()}
        _check(listed == set(mirror), cfg,
               f"{what}: live keys {sorted(listed)} != mirror "
               f"{sorted(mirror)}")
        for key, want in mirror.items():
            got = bucket.get(key)
            _check(got == want, cfg,
                   f"{what}: GET {key!r} returned {len(got)} bytes != "
                   "mirror (silently wrong read)")

    try:
        store.drop_cached()
        bucket = store.open_bucket(
            root, "bkt", create=True, k=k, p=p, w=w,
            stripe_bytes=cfg["stripe_bytes"],
        )
        for j, ev in enumerate(cfg["events"]):
            crash = ev.get("crash")
            payloads = {}
            if ev["op"] == "put":
                for e, b in enumerate(ev["batch"]):
                    payloads[e] = random.Random(
                        f"rs-chaos-object-data:{seed}:{i}:{j}:{e}"
                    ).randbytes(b["len"])
            if crash:
                os.environ["RS_UPDATE_CRASH"] = crash
            try:
                committed = True
                try:
                    if ev["op"] == "put":
                        bucket.put_many([
                            (b["key"], payloads[e])
                            for e, b in enumerate(ev["batch"])
                        ])
                    elif ev["op"] == "delete":
                        try:
                            bucket.delete(ev["key"])
                        except store.ObjectNotFound:
                            # Rolled-back earlier put (or double
                            # delete): legal iff the mirror agrees.
                            _check(ev["key"] not in mirror, cfg,
                                   f"event {j}: delete 404 for a key "
                                   "the mirror holds")
                            committed = False
                    else:
                        bucket.compact(force=ev.get("force", False))
                except SimulatedCrash:
                    # Torn op: simulate process death + restart, then
                    # prove the commit semantics.  A torn DELETE is
                    # COMMITTED (tombstone fsyncs before the zeroing);
                    # a torn put/compact commits nothing.
                    store.drop_cached()
                    bucket = store.open_bucket(root, "bkt")
                    if ev["op"] == "delete":
                        mirror.pop(ev["key"], None)
                    check_state(bucket, f"event {j} (torn {ev['op']} "
                                f"@{crash})")
                    continue
            finally:
                os.environ.pop("RS_UPDATE_CRASH", None)
            # The op completed (a scheduled crash stage may simply not
            # exist on this path, e.g. a stripe-creating put): committed.
            if committed and ev["op"] == "put":
                for e, b in enumerate(ev["batch"]):
                    mirror[b["key"]] = payloads[e]
            elif committed and ev["op"] == "delete":
                mirror.pop(ev["key"], None)
            check_state(bucket, f"event {j} ({ev['op']})")
        # Fresh-process differential: reload from disk and re-check,
        # then prove every surviving stripe archive is healthy.
        store.drop_cached()
        bucket = store.open_bucket(root, "bkt")
        check_state(bucket, "final reload")
        bdir = os.path.join(root, "bkt")
        for fn in sorted(os.listdir(bdir)):
            if fn.endswith(".METADATA"):
                report = api.scan_file(
                    os.path.join(bdir, fn[: -len(".METADATA")]),
                    segment_bytes=_SEGMENT_BYTES,
                )
                _check(
                    report["decodable"] is True and not report["corrupt"]
                    and not report["missing"]
                    and not report["pending_journal"],
                    cfg, f"stripe {fn} unhealthy after schedule: "
                    f"{report}",
                )
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        os.environ.pop("RS_UPDATE_CRASH", None)
        store.drop_cached()
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": sum(len(v) for v in mirror.values()),
                "chaos": {
                    "seed": seed, "iter": i, "mode": "object",
                    "stripe_bytes": cfg["stripe_bytes"],
                    "events": cfg["events"], "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "mode": "object", "k": k, "p": p, "w": w,
        "stripe_bytes": cfg["stripe_bytes"],
        "events": [
            ev["op"] + (":torn" if ev.get("crash") else "")
            for ev in cfg["events"]
        ],
        "final_objects": len(mirror),
        "final_bytes": sum(len(v) for v in mirror.values()),
        "verdict": "pass",
    }


def _run_silent_iteration(cfg: dict, workdir: str, *,
                          keep: bool = False) -> dict:
    """One ``silent``-class iteration: encode WITHOUT checksum lines,
    corrupt per schedule, then prove the error-locating plane's contract
    (docs/RESILIENCE.md "Error location"):

    * <= t damaged chunks: the syndrome scrub attributes EXACTLY the
      damaged set (no CRCs anywhere), and both the auto-decode escalation
      ladder and ``locate_decode_file`` recover bit-identical bytes;
    * > t: the scrub verdict is ``unlocatable``, ``decodable`` degrades
      to ``"unknown"``, and every decode path raises — never a silently
      wrong output.
    """
    from .. import api
    from ..utils.fileformat import (
        chunk_file_name, chunk_size_for, metadata_file_name,
        read_metadata_ext,
    )

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w, size = cfg["k"], cfg["p"], cfg["w"], cfg["size"]
    rng = random.Random(f"rs-chaos-silent-run:{seed}:{i}")
    base = os.path.join(workdir, f"iter{i}")
    os.makedirs(base, exist_ok=True)
    fname = os.path.join(base, f"chaos_silent_{i}.bin")
    data = random.Random(f"rs-chaos-data:{seed}:{i}").randbytes(size)
    ok = False
    try:
        with open(fname, "wb") as fp:
            fp.write(data)
        api.encode_file(
            fname, k, p, checksums=False, w=w, segment_bytes=_SEGMENT_BYTES
        )
        total_size, p_m, k_m, total_mat, w_m, crcs = read_metadata_ext(
            metadata_file_name(fname)
        )
        _check((k_m, p_m, w_m, total_size) == (k, p, w, size), cfg,
               "metadata disagrees with the encode config")
        _check(not crcs, cfg, "silent-class archive must carry no CRCs")
        oracle = _oracle_chunks(data, k, p, w, total_mat)
        for c in range(k + p):
            got = open(chunk_file_name(fname, c), "rb").read()
            _check(got == oracle[c], cfg,
                   f"encode differential mismatch on chunk {c}")

        chunk = chunk_size_for(size, k, w // 8)
        _apply_events(fname, cfg["events"], chunk, rng)
        damaged = sorted({ev["chunk"] for ev in cfg["events"]})
        t = p // 2
        recoverable = len(damaged) <= t

        _retry.reset_budget()
        report = api.scan_file(
            fname, syndrome=True, segment_bytes=_SEGMENT_BYTES
        )
        syn = report["syndrome"]
        if recoverable:
            _check(
                syn["verdict"] == ("silent_bitrot" if damaged else "clean"),
                cfg, f"scrub syndrome verdict {syn['verdict']!r} for "
                f"damage {damaged}",
            )
            # The attribution contract: chunk indices pinned WITHOUT CRCs
            # (the syndrome pre-check replacing subset-search oracling as
            # the first line of damage attribution).
            _check(syn["silent_bitrot"] == damaged, cfg,
                   f"syndrome attributed {syn['silent_bitrot']}, "
                   f"schedule damaged {damaged}")
            _check(report["decodable"] is True, cfg,
                   f"decodable {report['decodable']} on <=t silent damage")
            out = api.auto_decode_file(
                fname, fname + ".dec", segment_bytes=_SEGMENT_BYTES
            )
            _check(open(out, "rb").read() == data, cfg,
                   "auto-decode (locate rung) output != original bytes")
            out2 = api.locate_decode_file(
                fname, fname + ".dec2", segment_bytes=_SEGMENT_BYTES
            )
            _check(open(out2, "rb").read() == data, cfg,
                   "locate decode output != original bytes")
        else:
            _check(syn["verdict"] == "unlocatable", cfg,
                   f"scrub syndrome verdict {syn['verdict']!r} on >t "
                   "silent damage")
            _check(report["decodable"] == "unknown", cfg,
                   "decodable must degrade to 'unknown' past the t bound")
            for op_name, call in (
                ("auto_decode", lambda: api.auto_decode_file(
                    fname, fname + ".dec", segment_bytes=_SEGMENT_BYTES)),
                ("locate_decode", lambda: api.locate_decode_file(
                    fname, fname + ".dec2",
                    segment_bytes=_SEGMENT_BYTES)),
            ):
                try:
                    call()
                    _check(False, cfg,
                           f"{op_name} succeeded on >t silent damage")
                except ValueError:
                    pass  # UnlocatableError is the expected subclass
            # Never half-written: decode failures must leave no output.
            for leftover in (fname + ".dec", fname + ".dec2"):
                _check(not os.path.exists(leftover), cfg,
                       f"failed decode left {leftover}")
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": size,
                "chaos": {
                    "seed": seed, "iter": i, "mode": "silent",
                    "events": cfg["events"], "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "mode": "silent", "k": k, "p": p, "w": w, "size": size,
        "damaged": sorted({ev["chunk"] for ev in cfg["events"]}),
        "faults": cfg["faults"], "verdict": "pass",
    }


def _run_health_iteration(cfg: dict, workdir: str, *,
                          keep: bool = False) -> dict:
    """One ``health``-class iteration: encode a small fleet against a
    private damage ledger, hurt the victim, and prove the durability
    plane converges (docs/HEALTH.md):

    * a clean fleet ranks nothing for repair;
    * induced damage puts the victim at rank 1 with the EXACT per-chunk
      state map the schedule predicts (unlink -> missing, torn ->
      truncated, bitrot -> crc_mismatch), margin ``p - lost``, and a
      ``repair`` work-queue head;
    * a checkpoint snapshot taken mid-history, then repair + rescan:
      the victim's damage map clears and no repair stays queued;
    * replay is restart-stable: two fresh replays agree byte-for-byte,
      and snapshot+delta replay equals pure-delta replay from genesis —
      the daemon kill/restart contract.
    """
    from .. import api
    from ..obs import health as _health
    from ..utils.fileformat import chunk_size_for

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w = cfg["k"], cfg["p"], cfg["w"]
    rng = random.Random(f"rs-chaos-health-run:{seed}:{i}")
    base = os.path.join(workdir, f"iter{i}")
    os.makedirs(base, exist_ok=True)
    ledger = os.path.join(base, "health_ledger.jsonl")
    damaged = sorted({ev["chunk"] for ev in cfg["events"]})
    # Private ledger + pinned health knobs for the iteration: verdicts
    # must be a function of the seed alone, and the ambient ledger must
    # not absorb (or leak) this fleet's damage events.
    saved_env = {
        kk: os.environ.get(kk)
        for kk in ("RS_RUNLOG", "RS_RUNLOG_MAX_BYTES",
                   "RS_HEALTH_SCRUB_MAX_AGE_S", "RS_HEALTH_AT_RISK",
                   "RS_SCHEDULE_STORE")
    }
    ok = False
    try:
        os.environ["RS_RUNLOG"] = ledger
        os.environ.pop("RS_RUNLOG_MAX_BYTES", None)
        os.environ.pop("RS_HEALTH_SCRUB_MAX_AGE_S", None)
        os.environ.pop("RS_HEALTH_AT_RISK", None)
        os.environ["RS_SCHEDULE_STORE"] = "off"

        fnames = []
        for a, size in enumerate(cfg["sizes"]):
            fname = os.path.join(base, f"chaos_health_{i}_{a}.bin")
            data = random.Random(
                f"rs-chaos-data:{seed}:{i}:{a}").randbytes(size)
            with open(fname, "wb") as fp:
                fp.write(data)
            api.encode_file(fname, k, p, checksums=True, w=w,
                            segment_bytes=_SEGMENT_BYTES)
            fnames.append(fname)
        for f in fnames:
            api.scan_file(f, segment_bytes=_SEGMENT_BYTES)
        state = _health.load(ledger)
        _check(len(state["archives"]) == len(fnames), cfg,
               "clean scans did not track every archive")
        _check(
            not [q for q in _health.work_queue(state)
                 if q["action"] == "repair"],
            cfg, "clean fleet queued repairs",
        )

        victim = os.path.abspath(fnames[cfg["victim"]])
        chunk = chunk_size_for(cfg["sizes"][cfg["victim"]], k, w // 8)
        _apply_events(victim, cfg["events"], chunk, rng)
        for f in fnames:
            api.scan_file(f, segment_bytes=_SEGMENT_BYTES)
        state = _health.load(ledger)
        report = _health.fleet_report(state)
        top = report["archives"][0]
        _check(top["archive"] == victim, cfg,
               f"induced damage ranked {top['archive']!r} first, "
               f"not the victim")
        _check(top["lost"] == len(damaged), cfg,
               f"victim lost {top['lost']}, schedule damaged "
               f"{len(damaged)}")
        _check(top["margin"] == p - len(damaged), cfg,
               f"victim margin {top['margin']} != p - lost")
        expect = {
            str(ev["chunk"]): {"unlink": "missing", "torn": "truncated",
                               "bitrot": "crc_mismatch"}[ev["kind"]]
            for ev in cfg["events"]
        }
        _check(top["chunks"] == expect, cfg,
               f"damage map {top['chunks']} != schedule {expect}")
        wq = report["work_queue"]
        _check(
            bool(wq) and wq[0]["archive"] == victim
            and wq[0]["action"] == "repair",
            cfg, "victim is not the work queue's repair head",
        )

        # Checkpoint mid-history (the "daemon killed mid-scrub" state),
        # then keep appending deltas on top of it.
        _health.write_snapshot(state, ledger)

        rebuilt = api.repair_file(victim, segment_bytes=_SEGMENT_BYTES)
        _check(sorted(rebuilt) == damaged, cfg,
               f"repair rebuilt {sorted(rebuilt)}, schedule damaged "
               f"{damaged}")
        for f in fnames:
            api.scan_file(f, segment_bytes=_SEGMENT_BYTES)
        state = _health.load(ledger)
        report = _health.fleet_report(state)
        vrow = next(r for r in report["archives"]
                    if r["archive"] == victim)
        _check(vrow["lost"] == 0, cfg,
               "repair + rescan did not clear the victim's damage map")
        _check(
            not [q for q in report["work_queue"]
                 if q["action"] == "repair"],
            cfg, "repairs still queued after a clean rescan",
        )

        # Restart stability: replays of the same ledger agree
        # byte-for-byte, with and without the checkpoint.
        c_a = _health.canonical(_health.load(ledger))
        c_b = _health.canonical(_health.load(ledger))
        _check(c_a == c_b, cfg, "re-replay is not deterministic")
        c_pure = _health.canonical(
            _health.load(ledger, use_snapshots=False))
        _check(c_a == c_pure, cfg,
               "snapshot+delta replay != pure-delta replay")
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        for kk, vv in saved_env.items():
            if vv is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = vv
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": sum(cfg["sizes"]),
                "chaos": {
                    "seed": seed, "iter": i, "mode": "health",
                    "events": cfg["events"], "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "mode": "health", "k": k, "p": p, "w": w,
        "archives": len(cfg["sizes"]), "damaged": damaged,
        "top_is_victim": True, "risk_cleared": True,
        "replay_identical": True, "verdict": "pass",
    }


def _run_maint_iteration(cfg: dict, workdir: str, *,
                         keep: bool = False) -> dict:
    """One ``maint``-class iteration: build the damaged fleet + the
    dead-heavy bucket, drain a controller that crashes at the scheduled
    job stage, then prove a same-owner restart converges
    (:func:`plan_maint_iteration` doc)."""
    from .. import api, store
    from ..maint import controller as _maint
    from ..obs import health as _health
    from ..utils.fileformat import chunk_size_for

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w = cfg["k"], cfg["p"], cfg["w"]
    rng = random.Random(f"rs-chaos-maint-run:{seed}:{i}")
    base = os.path.join(workdir, f"iter{i}")
    root = os.path.join(base, "store")
    os.makedirs(root, exist_ok=True)
    ledger = os.path.join(base, "maint_ledger.jsonl")
    damaged = sorted({ev["chunk"] for ev in cfg["events"]})
    # Private ledger + pinned knobs (the health-class discipline):
    # verdicts must be a function of the seed alone, and the ambient
    # ledger must not absorb this fleet's damage or claim events.
    saved_env = {
        kk: os.environ.get(kk)
        for kk in ("RS_RUNLOG", "RS_RUNLOG_MAX_BYTES",
                   "RS_HEALTH_SCRUB_MAX_AGE_S", "RS_HEALTH_AT_RISK",
                   "RS_SCHEDULE_STORE", "RS_MAINT_CRASH")
    }
    ok = False
    crashed = False
    try:
        os.environ["RS_RUNLOG"] = ledger
        os.environ.pop("RS_RUNLOG_MAX_BYTES", None)
        os.environ.pop("RS_HEALTH_SCRUB_MAX_AGE_S", None)
        os.environ.pop("RS_HEALTH_AT_RISK", None)
        os.environ["RS_SCHEDULE_STORE"] = "off"
        os.environ.pop("RS_MAINT_CRASH", None)

        fnames = []
        for a, size in enumerate(cfg["sizes"]):
            fname = os.path.join(base, f"chaos_maint_{i}_{a}.bin")
            data = random.Random(
                f"rs-chaos-data:{seed}:{i}:{a}").randbytes(size)
            with open(fname, "wb") as fp:
                fp.write(data)
            api.encode_file(fname, k, p, checksums=True, w=w)
            api.scan_file(fname)
            fnames.append(fname)
        victim = os.path.abspath(fnames[cfg["victim"]])
        # Chunk bytes BEFORE damage — repair must restore them exactly
        # (snapshot drops the trailing .METADATA entry: repair rewrites
        # identical chunk bytes, metadata line order is its own).
        pre_chunks = _archive_snapshot(victim, k + p)[:-1]
        chunk = chunk_size_for(cfg["sizes"][cfg["victim"]], k, w // 8)
        _apply_events(victim, cfg["events"], chunk, rng)
        api.scan_file(victim)

        store.drop_cached()
        bucket = store.open_bucket(
            root, "bkt", create=True, k=k, p=p, w=w,
            stripe_bytes=cfg["stripe_bytes"],
        )
        mirror: dict[str, bytes] = {}
        for j, pt in enumerate(cfg["puts"]):
            data = random.Random(
                f"rs-chaos-maint-obj:{seed}:{i}:{j}").randbytes(pt["len"])
            bucket.put(pt["key"], data)
            mirror[pt["key"]] = data
        for key in cfg["deletes"]:
            bucket.delete(key)
            mirror.pop(key, None)

        # Drain #1: the controller that may die mid-job.  Same-owner
        # restart is the daemon contract (docs/MAINT.md) — a restarted
        # process reclaims its own leases immediately.
        if cfg["crash"]:
            os.environ["RS_MAINT_CRASH"] = cfg["crash"]
        ctl = _maint.MaintController(
            ledger_path=ledger, store_roots=[root],
            owner="chaos:maint", bytes_per_s=float(1 << 30),
            interval_s=0.01)
        try:
            ctl.drain()
        except _maint.MaintCrash:
            crashed = True
        _check(bool(cfg["crash"]) or not crashed, cfg,
               "controller crashed with no crash scheduled")
        os.environ.pop("RS_MAINT_CRASH", None)

        # Checkpoint mid-history — a live claim (post-crash) must ride
        # the snapshot byte-exactly (the restart-stability check below
        # replays both ways).
        _health.write_snapshot(_health.load(ledger), ledger)

        # Drain #2: the "restarted" process — fresh store view, same
        # owner — must converge with nothing left actionable.
        store.drop_cached()
        ctl2 = _maint.MaintController(
            ledger_path=ledger, store_roots=[root],
            owner="chaos:maint", bytes_per_s=float(1 << 30),
            interval_s=0.01)
        out = ctl2.drain()
        _check(out["remaining"] == 0, cfg,
               f"restart drain left {out['remaining']} job(s) queued")
        _check(out["skipped_claimed"] == 0, cfg,
               "restart drain blocked on its own leases")

        state = _health.load(ledger)
        wq = _health.work_queue(state)
        _check(not wq, cfg,
               f"work queue not empty after convergence: {wq[:2]}")
        post_chunks = _archive_snapshot(victim, k + p)[:-1]
        _check(post_chunks == pre_chunks, cfg,
               "repair did not restore the victim's chunk bytes")

        bucket = store.open_bucket(root, "bkt")
        stats = bucket.stats()
        _check(stats["pending_compactions"] == 0, cfg,
               f"{stats['pending_compactions']} dead-heavy archive(s) "
               "still pending compaction")
        listed = {o["key"] for o in bucket.list_objects()}
        _check(listed == set(mirror), cfg,
               f"live keys {sorted(listed)} != mirror {sorted(mirror)}")
        for key, want in mirror.items():
            _check(bucket.get(key) == want, cfg,
                   f"GET {key!r} != mirror after maintenance")

        # Restart stability with claims in history: snapshot+delta
        # replay must equal pure-delta replay from genesis.
        c_a = _health.canonical(_health.load(ledger))
        c_pure = _health.canonical(
            _health.load(ledger, use_snapshots=False))
        _check(c_a == c_pure, cfg,
               "snapshot+delta replay != pure-delta replay")
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        for kk, vv in saved_env.items():
            if vv is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = vv
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": sum(cfg["sizes"]),
                "chaos": {
                    "seed": seed, "iter": i, "mode": "maint",
                    "events": cfg["events"], "crash": cfg["crash"],
                    "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "mode": "maint", "k": k, "p": p, "w": w,
        "archives": len(cfg["sizes"]), "damaged": damaged,
        "objects": len(cfg["puts"]), "deleted": len(cfg["deletes"]),
        "crash": cfg["crash"] or "none", "crashed": crashed,
        "repaired": True, "pending_cleared": True,
        "mirror_match": True, "replay_identical": True,
        "verdict": "pass",
    }


def _run_iteration(cfg: dict, workdir: str, *, keep: bool = False) -> dict:
    from .. import api
    from ..utils.fileformat import (
        chunk_file_name, chunk_size_for, metadata_file_name,
        read_metadata_ext,
    )

    seed, i = cfg["seed"], cfg["iter"]
    k, p, w, size = cfg["k"], cfg["p"], cfg["w"], cfg["size"]
    rng = _iter_rng(seed, i)
    rng.random()  # decouple from plan_iteration's draws deterministically
    base = os.path.join(workdir, f"iter{i}")
    os.makedirs(base, exist_ok=True)
    fname = os.path.join(base, f"chaos_{i}.bin")
    data = random.Random(f"rs-chaos-data:{seed}:{i}").randbytes(size)
    ok = False
    try:
        with open(fname, "wb") as fp:
            fp.write(data)
        api.encode_file(
            fname, k, p, checksums=True, w=w, segment_bytes=_SEGMENT_BYTES
        )
        total_size, p_m, k_m, total_mat, w_m, _crcs = read_metadata_ext(
            metadata_file_name(fname)
        )
        _check((k_m, p_m, w_m, total_size) == (k, p, w, size), cfg,
               "metadata disagrees with the encode config")
        oracle = _oracle_chunks(data, k, p, w, total_mat)
        for c in range(k + p):
            got = open(chunk_file_name(fname, c), "rb").read()
            _check(got == oracle[c], cfg,
                   f"encode differential mismatch on chunk {c}")

        chunk = chunk_size_for(size, k, w // 8)
        _apply_events(fname, cfg["events"], chunk, rng)
        damaged = sorted({ev["chunk"] for ev in cfg["events"]})

        plan = (
            _faults.parse_plan(cfg["faults"], seed=(seed * 1_000_003 + i))
            if cfg["faults"] else None
        )
        _retry.reset_budget()
        with _faults.activate(plan) if plan else nullcontext():
            report = api.scan_file(fname, segment_bytes=_SEGMENT_BYTES)
            scan_damaged = sorted(
                set(report["corrupt"]) | set(report["missing"])
            )
            _check(scan_damaged == damaged, cfg,
                   f"scrub saw {scan_damaged}, schedule damaged {damaged}")
            recoverable = _oracle_decodable(
                total_mat, report["healthy"], k, w
            )
            _check(report["decodable"] is recoverable, cfg,
                   f"scrub verdict {report['decodable']} vs oracle "
                   f"decodable={recoverable}")
            _check(recoverable is (len(damaged) <= p) or not recoverable,
                   cfg, "oracle says decodable with more than p chunks "
                   "damaged (impossible)")
            if recoverable:
                out = api.auto_decode_file(
                    fname, fname + ".dec", segment_bytes=_SEGMENT_BYTES
                )
                _check(open(out, "rb").read() == data, cfg,
                       "auto-decode output != original bytes")
                _check(
                    _oracle_decode(fname, fname + ".auto.conf", size, k, w,
                                   total_mat) == data,
                    cfg, "oracle decode of the chosen conf != original",
                )
                rebuilt = api.repair_file(
                    fname, segment_bytes=_SEGMENT_BYTES
                )
                _check(sorted(rebuilt) == damaged, cfg,
                       f"repair rebuilt {sorted(rebuilt)}, expected "
                       f"{damaged}")
                for c in range(k + p):
                    got = open(chunk_file_name(fname, c), "rb").read()
                    _check(got == oracle[c], cfg,
                           f"post-repair differential mismatch on chunk {c}")
                post = api.scan_file(fname, segment_bytes=_SEGMENT_BYTES)
                _check(post["decodable"] is True and not post["corrupt"]
                       and not post["missing"], cfg,
                       "archive not fully healthy after repair")
            else:
                for op_name, call in (
                    ("auto_decode", lambda: api.auto_decode_file(
                        fname, fname + ".dec",
                        segment_bytes=_SEGMENT_BYTES)),
                    ("repair", lambda: api.repair_file(
                        fname, segment_bytes=_SEGMENT_BYTES)),
                ):
                    try:
                        call()
                        _check(False, cfg,
                               f"{op_name} succeeded on >p damage")
                    except ValueError:
                        pass  # includes UndecidedSubset/ChunkIntegrity
                # Nothing half-rebuilt: surviving chunks stay byte-exact.
                for c in range(k + p):
                    if c in damaged:
                        continue
                    got = open(chunk_file_name(fname, c), "rb").read()
                    _check(got == oracle[c], cfg,
                           f"survivor chunk {c} mutated by a failed repair")
        ok = True
    except ChaosFailure:
        raise
    except Exception as e:
        raise ChaosFailure(
            cfg, f"unexpected {type(e).__name__}: {e}"
        ) from e
    finally:
        verdict = "pass" if ok else "fail"
        _metrics.counter(
            "rs_chaos_iterations_total", "chaos-harness iteration verdicts"
        ).labels(verdict=verdict).inc()
        if _runlog.enabled():
            _runlog.record({
                "op": "chaos_iter",
                "config": {"k": k, "n": k + p, "w": w},
                "bytes": size,
                "chaos": {
                    "seed": seed, "iter": i, "events": cfg["events"],
                    "faults": cfg["faults"],
                },
                "outcome": "ok" if ok else "error",
            })
        if ok and not keep:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "iter": i, "k": k, "p": p, "w": w, "size": size,
        "damaged": sorted({ev["chunk"] for ev in cfg["events"]}),
        "faults": cfg["faults"], "verdict": "pass",
    }


# -- shrinking ----------------------------------------------------------------


def shrink(cfg: dict, workdir: str, run=run_iteration) -> dict:
    """Greedy one-line-reproducer shrink: drop the fault plan, then each
    schedule event, keeping any removal that still fails.  Bounded at
    one pass over the elements (len(events)+1 reruns)."""
    current = dict(cfg)
    if current.get("faults"):
        trial = {**current, "faults": ""}
        if _still_fails(trial, workdir, run):
            current = trial
    events = list(current["events"])
    idx = 0
    while idx < len(events):
        trial_events = events[:idx] + events[idx + 1:]
        trial = {**current, "events": trial_events}
        if _still_fails(trial, workdir, run):
            events = trial_events
        else:
            idx += 1
    current["events"] = events
    return current


def _still_fails(cfg: dict, workdir: str, run) -> bool:
    try:
        run(cfg, workdir)
        return False
    except ChaosFailure:
        return True


# -- CLI ----------------------------------------------------------------------


def _digest(obj) -> str:
    import hashlib

    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()
    ).hexdigest()[:16]


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="rs chaos",
        description="Seeded chaos harness: encode -> corrupt -> "
        "scrub/auto-decode/repair, differential-checked against the "
        "native oracle.  Bit-reproducible per --seed.",
    )
    ap.add_argument("--seed", type=int, default=0,
                    help="master seed (default 0)")
    ap.add_argument("--iters", type=int, default=10,
                    help="iterations to run (default 10)")
    ap.add_argument("--only", type=int, default=None, metavar="I",
                    help="run just iteration I of the seed's schedule")
    ap.add_argument("--repro", metavar="JSON", default=None,
                    help="replay one REPRODUCE line's config verbatim")
    ap.add_argument("--dir", default=None,
                    help="work directory (default: a fresh temp dir)")
    ap.add_argument("--max-bytes", type=int, default=49152,
                    help="max file size per iteration (default 48 KiB)")
    ap.add_argument("--silent", action="store_true",
                    help="run the SILENT corruption class: CRC-less "
                    "bitrot recovered (or refused) by the error-locating "
                    "decode path — own seed stream, classic schedules "
                    "unchanged")
    ap.add_argument("--update", action="store_true",
                    help="run the UPDATE workload class: random edit/"
                    "append/torn-op schedules, every archive "
                    "differential-checked byte-identical against a "
                    "from-scratch re-encode and every torn op rolled "
                    "back via the journal — own seed stream "
                    "(docs/UPDATE.md)")
    ap.add_argument("--group", action="store_true",
                    help="with --update: the GROUPED update class "
                    "instead — group-committed edit batches "
                    "(update_file_many), torn groups must roll back ALL "
                    "their edits byte-exact — own seed stream, plain "
                    "--update digests unchanged (docs/UPDATE.md "
                    "\"Group commit\")")
    ap.add_argument("--object", action="store_true",
                    help="run the OBJECT-STORE workload class: seeded "
                    "PUT/DELETE/compact schedules against one bucket "
                    "with torn ops at every crash stage — the bucket's "
                    "live contents must stay byte-identical to a "
                    "sequential mirror of the committed ops, and the "
                    "index must never reference rolled-back bytes — "
                    "own seed stream (docs/STORE.md)")
    ap.add_argument("--health", action="store_true",
                    help="run the HEALTH convergence class: encode a "
                    "small fleet against a private damage ledger, hurt "
                    "one victim, and require the durability plane to "
                    "rank it first with the exact predicted chunk-state "
                    "map, clear it after repair, and replay snapshot+"
                    "delta byte-identically — own seed stream "
                    "(docs/HEALTH.md)")
    ap.add_argument("--maint", action="store_true",
                    help="run the MAINT convergence class: a damaged "
                    "fleet plus a dead-heavy bucket drained by the "
                    "maintenance controller, killed (RS_MAINT_CRASH) at "
                    "a scheduled job stage — a same-owner restart must "
                    "converge to an empty work queue, zero pending "
                    "compactions and byte-identical archive/object "
                    "state — own seed stream (docs/MAINT.md)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line per iteration")
    ap.add_argument("--keep", action="store_true",
                    help="keep every iteration's files on disk")
    ap.add_argument("--no-shrink", action="store_true",
                    help="report the failing iteration without minimizing")
    ap.add_argument("--repro-out", metavar="PATH", default=None,
                    help="also write the REPRODUCE line to PATH on failure")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    workdir = args.dir or tempfile.mkdtemp(prefix="rs_chaos_")
    os.makedirs(workdir, exist_ok=True)
    if args.repro:
        try:
            cfgs = [json.loads(args.repro)]
        except ValueError as e:
            print(f"rs chaos: bad --repro JSON: {e}", file=sys.stderr)
            return 2
    else:
        if sum((args.silent, args.update, args.object, args.health,
                args.maint)) > 1:
            print("rs chaos: --silent / --update / --object / --health "
                  "/ --maint conflict; pick one workload class",
                  file=sys.stderr)
            return 2
        if args.group and not args.update:
            print("rs chaos: --group modifies --update (the grouped "
                  "update class)", file=sys.stderr)
            return 2
        indices = [args.only] if args.only is not None else range(args.iters)
        plan = (
            plan_update_group_iteration if args.update and args.group
            else plan_update_iteration if args.update
            else plan_silent_iteration if args.silent
            else plan_object_iteration if args.object
            else plan_health_iteration if args.health
            else plan_maint_iteration if args.maint
            else plan_iteration
        )
        cfgs = [plan(args.seed, i, args.max_bytes) for i in indices]
    schedule_digest = _digest(cfgs)

    results = []
    for cfg in cfgs:
        try:
            rec = run_iteration(cfg, workdir, keep=args.keep)
        except ChaosFailure as e:
            shrunk = (
                e.cfg if args.no_shrink else shrink(e.cfg, workdir)
            )
            line = json.dumps(shrunk, sort_keys=True)
            print(f"rs chaos: FAILED — {e.what}", file=sys.stderr)
            silent_flag = {
                "silent": "--silent ", "update": "--update ",
                "update_group": "--update --group ",
                "object": "--object ", "health": "--health ",
                "maint": "--maint ",
            }.get(cfg.get("mode"), "")
            print(
                f"rs chaos: replay the original with: rs chaos "
                f"{silent_flag}--seed {cfg['seed']} --only {cfg['iter']}",
                file=sys.stderr,
            )
            print(f"REPRODUCE: {line}")
            if args.repro_out:
                with open(args.repro_out, "w") as fp:
                    fp.write(line + "\n")
            return 1
        results.append(rec)
        if args.json:
            print(json.dumps(rec, sort_keys=True))
    print(json.dumps({
        "seed": args.seed,
        "iters": len(results),
        "passed": len(results),
        "failed": 0,
        "schedule_digest": schedule_digest,
        "verdict_digest": _digest(results),
    }, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
