"""Resilience subsystem — fault injection, retry/backoff, chaos testing.

The (n, k) Reed-Solomon pipeline exists to survive failures, so the stack
must be able to *provoke* them: this package is the fault plane and the
recovery policy the file layer (api.py) and the I/O lanes
(parallel/io_executor.py) hook into, plus the seeded chaos harness that
differential-checks the whole loop against the native oracle.

* :mod:`.faults` — a deterministic, seedable fault-injection plane
  (``RS_FAULTS`` / ``--faults`` specs like ``read:ioerror@p=0.02``),
  compiled to a shared no-op when unset so tier-1 overhead is zero.
* :mod:`.retry` — bounded exponential backoff with seeded jitter,
  transient/fatal error classification and a process-wide retry budget,
  applied to chunk reads and the write-behind drain lanes.
* :mod:`.chaos` — the ``rs chaos`` harness: seeded encode ->
  corrupt-per-schedule -> scrub/auto-decode/repair, every output
  differential-checked against the native oracle, failures shrunk to a
  one-line reproducer.

See docs/RESILIENCE.md for the fault-spec grammar, the retry knobs and
the degraded-decode semantics.

Import cost: stdlib only (no jax, no numpy) — :mod:`.faults` and
:mod:`.retry` are imported by ``parallel.io_executor``, which keeps that
contract.  :mod:`.chaos` imports the api lazily and is NOT imported here.
"""

from . import faults, retry

__all__ = ["faults", "retry"]
