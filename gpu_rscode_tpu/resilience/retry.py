"""Retry policy engine — bounded backoff with seeded jitter and a budget.

The file layer's I/O used to be one-shot: a single EIO from a flaky disk
killed a multi-GB encode even though the very next read would have
succeeded.  This module is the recovery half of the resilience subsystem
(:mod:`.faults` is the provocation half): a :class:`RetryPolicy` wraps an
I/O callable, classifies each failure transient-or-fatal, and retries
transients under bounded exponential backoff with *seeded* jitter — the
same seed replays the same delays, so chaos runs stay bit-reproducible.

Classification (:func:`is_transient`):

* :class:`..resilience.faults.InjectedFault` carries its own verdict
  (``ioerror`` transient, ``torn`` fatal);
* ``TimeoutError`` / ``InterruptedError`` / ``BlockingIOError`` and
  ``OSError`` with errno in {EIO, EAGAIN, EINTR, ETIMEDOUT, EBUSY} are
  transient;
* ``FileNotFoundError`` / ``PermissionError`` / path-shape errors and
  everything else (ValueError, ChunkIntegrityError, ...) are fatal —
  retrying them burns time without changing the outcome.

Retried callables MUST be idempotent.  The call sites keep that contract
structurally: chunk opens are pure reads, segment gathers write into
fresh buffers, and the drain lanes commit offset-addressed (or
restart-from-scratch) writes with cross-segment state (incremental CRC)
updated only AFTER the write landed (see ``api._drain_parity``).

Knobs: ``RS_RETRY_ATTEMPTS`` (retries per call, default 3; 0 disables),
``RS_RETRY_BASE_MS`` / ``RS_RETRY_MAX_MS`` (backoff ladder, default
5/250), ``RS_RETRY_SEED`` (jitter seed), ``RS_RETRY_BUDGET``
(retry budget, default 256 — a storm of transients must degrade to
failure, not retry forever; rearmed by :func:`reset_budget` at every
file-level entry point (``api._observed_file_op``) so it bounds ONE
operation's storm without a long-lived process permanently losing retry
protection; the chaos harness also rearms per iteration).

Observability: ``rs_retries_total{outcome}`` counts ``retried`` (each
backoff taken), ``recovered`` (success after >= 1 retry), ``exhausted``
(attempts or budget ran out) and ``fatal`` (a non-retryable OSError
passed straight through); each backoff records a ``retry`` instant on
the ``retry`` trace lane.

Import cost: stdlib only (no jax, no numpy).
"""

from __future__ import annotations

import errno
import os
import threading
import time
import zlib
from collections.abc import Callable

from ..obs import metrics as _metrics, tracing as _tracing
from . import faults as _faults

_TRANSIENT_ERRNO = {
    errno.EIO, errno.EAGAIN, errno.EWOULDBLOCK, errno.EINTR,
    errno.ETIMEDOUT, errno.EBUSY,
}
_FATAL_OSERRORS = (
    FileNotFoundError, PermissionError, NotADirectoryError,
    IsADirectoryError, FileExistsError,
)


def int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def is_transient(exc: BaseException) -> bool:
    """Whether ``exc`` is worth retrying (see the module doc for the
    classification table)."""
    if isinstance(exc, _faults.InjectedFault):
        return exc.transient
    if isinstance(exc, _FATAL_OSERRORS):
        return False
    if isinstance(exc, (TimeoutError, InterruptedError, BlockingIOError)):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNO
    return False


# -- process-wide retry budget -----------------------------------------------

_BUDGET_LOCK = threading.Lock()
_BUDGET: dict = {"left": None}


def take_budget() -> bool:
    """Spend one retry from the process budget; False when exhausted."""
    with _BUDGET_LOCK:
        if _BUDGET["left"] is None:
            _BUDGET["left"] = max(0, int_env("RS_RETRY_BUDGET", 256))
        if _BUDGET["left"] <= 0:
            return False
        _BUDGET["left"] -= 1
        return True


def reset_budget() -> None:
    """Rearm the process retry budget (re-read from the env on next use)."""
    with _BUDGET_LOCK:
        _BUDGET["left"] = None


def budget_left() -> int | None:
    with _BUDGET_LOCK:
        return _BUDGET["left"]


class RetryPolicy:
    """Bounded exponential backoff with seeded jitter.

    ``retries`` transient failures are retried per :meth:`call`; delay for
    attempt i is ``min(max_ms, base_ms * 2**i)`` scaled by a deterministic
    jitter factor in [0.5, 1.5) drawn from ``(seed, op, attempt, seq)`` —
    reproducible, but still decorrelated across concurrent callers.
    """

    def __init__(self, retries: int | None = None,
                 base_ms: float | None = None,
                 max_ms: float | None = None,
                 seed: int | None = None):
        self.retries = (
            max(0, int_env("RS_RETRY_ATTEMPTS", 3))
            if retries is None else max(0, retries)
        )
        self.base_ms = (
            _float_env("RS_RETRY_BASE_MS", 5.0)
            if base_ms is None else base_ms
        )
        self.max_ms = (
            _float_env("RS_RETRY_MAX_MS", 250.0)
            if max_ms is None else max_ms
        )
        self.seed = int_env("RS_RETRY_SEED", 0) if seed is None else seed
        self._seq = 0
        self._lock = threading.Lock()

    def backoff_s(self, op: str, attempt: int) -> float:
        with self._lock:
            self._seq += 1
            seq = self._seq
        exp = min(self.max_ms, self.base_ms * (2 ** attempt))
        frac = zlib.crc32(
            repr((self.seed, op, attempt, seq)).encode()
        ) / 2 ** 32
        return exp * (0.5 + frac) / 1000.0

    def call(self, fn: Callable, *, op: str = "io"):
        """Run ``fn`` retrying transient failures; re-raises the last
        error when attempts or the process budget run out."""
        attempt = 0
        while True:
            try:
                out = fn()
            except Exception as e:
                if not is_transient(e):
                    if isinstance(e, OSError):
                        _metrics.counter(
                            "rs_retries_total", "retry-policy outcomes"
                        ).labels(outcome="fatal").inc()
                    raise
                if attempt >= self.retries or not take_budget():
                    _metrics.counter(
                        "rs_retries_total", "retry-policy outcomes"
                    ).labels(outcome="exhausted").inc()
                    raise
                delay = self.backoff_s(op, attempt)
                attempt += 1
                _metrics.counter(
                    "rs_retries_total", "retry-policy outcomes"
                ).labels(outcome="retried").inc()
                _tracing.instant(
                    "retry", lane="retry", op=op, attempt=attempt,
                    error=type(e).__name__,
                    backoff_ms=round(delay * 1e3, 3),
                )
                time.sleep(delay)
                continue
            if attempt:
                _metrics.counter(
                    "rs_retries_total", "retry-policy outcomes"
                ).labels(outcome="recovered").inc()
            return out


_DEFAULT_LOCK = threading.Lock()
_DEFAULT_KEY: tuple | None = None
_DEFAULT: RetryPolicy | None = None


def default_policy() -> RetryPolicy:
    """The process's shared policy, rebuilt when the RS_RETRY_* env
    changes (so tests and the chaos harness can reconfigure mid-process)."""
    global _DEFAULT_KEY, _DEFAULT
    key = tuple(
        os.environ.get(name)
        for name in ("RS_RETRY_ATTEMPTS", "RS_RETRY_BASE_MS",
                     "RS_RETRY_MAX_MS", "RS_RETRY_SEED")
    )
    with _DEFAULT_LOCK:
        if _DEFAULT is None or key != _DEFAULT_KEY:
            _DEFAULT = RetryPolicy()
            _DEFAULT_KEY = key
        return _DEFAULT
