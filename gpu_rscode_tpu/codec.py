"""RSCodec — the stripe-level coding engine (compute only, no file IO).

This is the L3 equivalent of the reference's host-callable coding math
(``gen_encoding_matrix`` / ``encode_chunk`` / ``decode_chunk`` /
``CPU_invert_matrix``, matrix.h:63-102 + cpu-decode.h:27), re-packaged the
JAX way: a stateless object holding the (tiny) generator matrix as host
NumPy, whose encode/decode methods dispatch one jitted GF-GEMM over a
(rows, chunk_bytes) stripe.  The k x k decode inversion runs on host (same
host/device split the reference production path uses — decode.cu:333) but an
on-device inverter is available (:func:`..ops.inverse.invert_matrix_jax`).
"""

from __future__ import annotations

import jax
import numpy as np

from .models.vandermonde import generator_matrix
from .obs import metrics as _obs_metrics, profiler as _prof
from .ops.gemm import Strategy, gf_matmul_jit
from .ops.gf import get_field
from .ops.inverse import invert_matrix


import functools


@functools.lru_cache(maxsize=1)
def _pallas_failure_types() -> tuple:
    """Exception types that mean "the fused kernel can't run on this
    backend" — compile/runtime backend errors and Mosaic lowering failures.
    Anything else (a shape bug, a TypeError, an assertion) is a programming
    error and must propagate: silently demoting it to the bitplane path
    would hide a correctness bug mid-production.

    Computed lazily on the first fused-kernel failure: importing Mosaic
    lowering internals costs ~0.2 s, which import-time evaluation would
    charge to every CLI start including host-only paths (--scrub)."""
    types: list[type] = [jax.errors.JaxRuntimeError, NotImplementedError]
    try:
        from jax._src.pallas.mosaic import lowering as _ml

        for _name in ("LoweringException", "FoldingError"):
            t = getattr(_ml, _name, None)
            if isinstance(t, type):
                types.append(t)
    except Exception:  # mosaic internals moved; backend errors still caught
        pass
    return tuple(types)


# One shared definition of "on real TPU hardware" (device platform first —
# a tunnel backend may serve TPU chips under its own registration name;
# see utils/backend.py).  Module-level alias kept for tests/monkeypatching.
from .utils.backend import tpu_devices_present as _tpu_devices_present


def _gf_matmul_pallas_eager(A, B, w):
    """Single-device fused-kernel dispatch, called EAGERLY (the inner
    _pallas_matmul is itself jitted, so compute is identical to routing
    through gf_matmul_jit): the RS_PALLAS_* env knobs then resolve on
    concrete arrays, which is what lets RS_PALLAS_REFOLD=autotune time
    real kernels — under an outer jit it would see tracers and fall back
    to the static default (see pallas_gemm._autotune_refold).  Module-
    level hook (import deferred to first use, like _pallas_failure_types)
    so tests can inject kernel failures here."""
    from .ops.pallas_gemm import gf_matmul_pallas

    return gf_matmul_pallas(A, B, w)


class RSCodec:
    """(n, k) Reed-Solomon codec over GF(2^w).

    ``native_num`` = k data chunks, ``parity_num`` = n - k parity chunks.
    ``generator``: "vandermonde" (reference-compatible: the exact matrix the
    reference generates and stores in .METADATA) or "cauchy" (any-k-subset
    decodable).  ``strategy``: GEMM strategy — "auto" (default at the file
    layer) resolves through the per-backend autotuner (:mod:`.tune`:
    pallas on real TPU hardware / bitplane elsewhere unless a measured
    decision says otherwise; ``RS_STRATEGY_AUTOTUNE=measure`` lets
    table/bitplane/pallas/xor/ring/native compete on real timings);
    explicit values: "pallas", "bitplane" (MXU), "table" (VPU), "xor"
    (XOR-lowered bitsliced planes, docs/XOR.md), "ring" (polynomial-
    ring lowering, docs/XOR.md "Ring lowering"), "cpu" (native host
    codec).
    """

    def __init__(
        self,
        native_num: int,
        parity_num: int,
        w: int = 8,
        generator: str = "vandermonde",
        strategy: Strategy = "bitplane",
        mesh=None,
        stripe_sharded: bool = False,
    ):
        if native_num < 1 or parity_num < 0:
            raise ValueError(f"bad (k={native_num}, p={parity_num})")
        from .tune import VALID_STRATEGIES, resolve_auto

        if strategy not in VALID_STRATEGIES:
            raise ValueError(
                f"unknown strategy {strategy!r}: valid strategies are "
                f"{', '.join(VALID_STRATEGIES)} — 'auto' resolves per "
                "backend via the autotuner (docs/XOR.md, docs/PLAN.md)"
            )
        if strategy == "auto":
            # Resolved through the strategy autotuner (tune.py): the
            # static prior keeps the old behaviour — fused kernel on
            # real TPU hardware (the reference's multi-GPU mode runs its
            # fast kernel unconditionally, decode.cu:335-378), bitplane
            # elsewhere — and RS_STRATEGY_AUTOTUNE=measure lets xor and
            # the native codec compete on real timings.  Every fused
            # dispatch stays guarded: a Mosaic-class failure demotes to
            # bitplane and recomputes the same bytes (see _matmul), so
            # no kernel failure can corrupt output files.
            strategy = resolve_auto(
                native_num, parity_num, w, mesh=mesh, generator=generator
            )
        self.gf = get_field(w)
        self.w = w
        self.native_num = native_num
        self.parity_num = parity_num
        self.strategy: Strategy = strategy
        self.generator = generator
        self.mesh = mesh
        self.stripe_sharded = stripe_sharded
        self._pallas_checked = False
        if strategy == "cpu":
            # Validate up front: failing mid-stream would leave partial
            # output files behind.
            if w != 8:
                raise ValueError("strategy='cpu' supports GF(2^8) only")
            if mesh is not None:
                raise ValueError(
                    "strategy='cpu' is host-only; it cannot run on a device mesh"
                )
        if strategy in ("xor", "ring"):
            if w not in (8, 16):
                raise ValueError(
                    f"strategy={strategy!r} supports GF(2^8) and "
                    "GF(2^16) only"
                )
            if mesh is not None:
                raise ValueError(
                    f"strategy={strategy!r} is single-device (its "
                    "schedule is baked from concrete coefficients, "
                    "which the jitted mesh collective cannot trace); "
                    "use bitplane/table/pallas on a mesh"
                )
        if mesh is not None:
            from .parallel.mesh import COLS, STRIPE

            self._cols_size = mesh.shape[COLS]
            if stripe_sharded and native_num % mesh.shape[STRIPE]:
                raise ValueError(
                    f"k={native_num} not divisible by stripe axis "
                    f"({mesh.shape[STRIPE]} devices)"
                )
        gen = generator_matrix(generator, parity_num, native_num, self.gf)
        eye = np.eye(native_num, dtype=self.gf.dtype)
        self.total_matrix = np.concatenate([eye, gen], axis=0)  # (n, k)

    @property
    def n(self) -> int:
        return self.native_num + self.parity_num

    @property
    def parity_block(self) -> np.ndarray:
        return self.total_matrix[self.native_num :]

    # ----- stripe ops (device) ----------------------------------------------

    def _count_segment(self, op: str, data) -> None:
        """Registry accounting for one stripe dispatch (no-op unless
        RS_METRICS).  Skipped under a caller's jit trace — a Python-level
        increment there would count TRACES, not dispatches."""
        if isinstance(data, jax.core.Tracer):
            return
        # Profiler seam (obs/profiler.py): name the file-level op for the
        # dispatch this call precedes, so a sampled `rs_perf` event says
        # "decode", not "matmul".  One env read when RS_PROF is off.
        _prof.note_op(op)
        _obs_metrics.counter(
            "segments_dispatched",
            "stripe GEMM dispatches by operation and strategy",
        ).labels(op=op, strategy=self.strategy, w=self.w).inc()
        # Payload volume next to the dispatch count: the per-strategy
        # byte stream `rs analyze` divides by measured wall for achieved
        # GB/s.  True (pre-pad) columns for pipeline-staged segments —
        # bucket pad is compute, not payload.
        from . import plan as _plan
        from .ops.xor_gemm import PackedOperand

        if isinstance(data, PackedOperand):
            # True-column payload of the symbols the planes encode —
            # the pack pad is compute, not payload (same contract as
            # the staged branch below).
            nbytes = data.rows * data.cols_true * data.dtype.itemsize
        elif isinstance(data, _plan.StagedSegment):
            nbytes = (
                data.array.shape[0] * data.cols * data.array.dtype.itemsize
            )
        else:
            nbytes = getattr(data, "nbytes", 0)
        if nbytes:
            _obs_metrics.counter(
                "rs_codec_bytes_total",
                "payload bytes entering stripe GEMM dispatches",
            ).labels(op=op, strategy=self.strategy, w=self.w).inc(
                int(nbytes)
            )

    def encode(self, data):
        """(k, m) natives -> (p, m) parity.  Systematic: natives pass through
        unchanged, only parity is computed (the reference's encode kernel has
        the same shape: (n-k) x k coefficient block, matrix.cu:767-776).
        ``data`` may be a host array or a :class:`..plan.StagedSegment` the
        pipeline pre-placed on the device (see :meth:`stage_segment`)."""
        self._count_segment("encode", data)
        return self._matmul(self.parity_block, data)

    def decode(self, decode_mat, chunks):
        """(k, k) recovery matrix x (k, m) surviving chunks -> (k, m) natives."""
        self._count_segment("decode", chunks)
        return self._matmul(decode_mat, chunks)

    def update(self, parity_mat, delta):
        """(p, k) parity coefficient block x (k, m) native-symbol delta
        -> (p, m) parity delta (``parity' = parity ⊕ E·Δ``).

        The partial-stripe update kernel (update/engine.py): RS linearity
        makes the parity patch a GEMM over just the TOUCHED columns.
        Same plan-cached/pallas-guarded ``_matmul`` as encode — identical
        ``A`` shape means an update rides the very executable the encode
        path (or ``warm_plan``) already compiled — under its own ``op``
        label so dispatch counts and payload bytes attribute separately
        (docs/PLAN.md)."""
        self._count_segment("update", delta)
        return self._matmul(parity_mat, delta)

    def syndrome(self, check_mat, chunks):
        """(r, s) parity-check block x (s, m) stacked chunk rows -> (r, m)
        syndromes (zero columns == consistent codeword columns).

        The error-locating decode path's batched syndrome kernel
        (gf_decode/syndrome.py): same GF-GEMM machinery as encode/decode —
        plan-cached, strategy-aware, pallas-guarded — under its own ``op``
        label so dispatch counts and payload bytes attribute separately."""
        self._count_segment("syndrome", chunks)
        return self._matmul(check_mat, chunks)

    def stage_segment(self, seg, *, cap=None, sym: int = 1, out_rows=None):
        """Stage one segment for the next encode/decode dispatch.

        The H2D stage of the 3-stage pipeline (DeviceStagingRing): pads the
        (k, cols) host segment to its plan bucket and issues the async
        ``device_put``, returning a :class:`..plan.StagedSegment` whose
        buffer the dispatch may DONATE.  ``sym`` > 1 reinterprets the raw
        bytes as little-endian symbols first (the w=16 wide-symbol view).
        ``out_rows`` is the coming dispatch's output row count when known
        (parity rows for encode, recovery rows for decode/repair): a
        dispatch whose output cannot alias the segment (out_rows != k)
        never donates, so its stage skips the host recovery copy.
        Where planning does not apply — layer disabled, host-only codec,
        or a mesh (whose placement happens in ``_matmul`` via
        ``put_sharded``) — the (viewed) host array is returned unchanged
        and the dispatch behaves exactly as before.
        """
        if sym > 1:
            seg = seg.view(np.uint16)
        from . import plan as _plan

        if self.mesh is not None or self.strategy == "cpu" or not _plan.enabled():
            # Mesh placement happens in _matmul (put_sharded), host codec
            # never leaves the host: both count as passthrough stages so
            # the rs_io_* balance (docs/IO.md) still sees the segment.
            _obs_metrics.counter(
                "rs_io_h2d_bytes_total",
                "segment bytes entering the H2D stage of the pipeline",
            ).labels(path="passthrough").inc(seg.nbytes)
            return seg
        _obs_metrics.counter(
            "rs_io_h2d_bytes_total",
            "segment bytes entering the H2D stage of the pipeline",
        ).labels(path="plan").inc(seg.nbytes)
        return _plan.stage_segment(
            seg, cap,
            retain_host=out_rows is None or out_rows == seg.shape[0],
        )

    def pack_operand(self, data):
        """Pack a staged segment's bit-planes ONCE for reuse across the
        chained xor dispatches that consume the same ``B`` operand
        (docs/XOR.md "Packed-operand reuse"): the returned
        :class:`..ops.xor_gemm.PackedOperand` feeds
        :meth:`syndrome`/:meth:`decode` in place of the segment, and its
        :meth:`~..ops.xor_gemm.PackedOperand.select` hands a row subset
        to a follow-up dispatch with no second pack.  Returns ``None``
        whenever the reuse does not apply — non-xor strategy, mesh
        codec, plan layer off, ``RS_XOR_PACK_REUSE=0``, or a traced
        operand — so callers can fall back to the classic path with one
        ``is None`` check."""
        from . import plan as _plan
        from .ops import xor_gemm as _xg

        if (
            self.strategy not in ("xor", "ring")
            or self.mesh is not None
            or not _xg.pack_reuse_enabled()
            or not _plan.enabled()
        ):
            return None
        seg = data if isinstance(data, _plan.StagedSegment) else None
        arr = seg.array if seg is not None else data
        if isinstance(arr, jax.core.Tracer):
            return None
        cols_true = seg.cols if seg is not None else arr.shape[1]
        cap = seg.cap if seg is not None else None
        cols32 = _xg.padded_cols(arr.shape[1])
        if arr.shape[1] != cols32:
            # Ragged staged width (cap smaller than the pack alignment):
            # pad exactly as plan.dispatch would before the pipeline.
            import jax.numpy as jnp

            arr = jnp.pad(
                jnp.asarray(arr), ((0, 0), (0, cols32 - arr.shape[1]))
            )
        return _xg.pack_operand(arr, self.w, cols_true=cols_true, cap=cap)

    def _matmul(self, A, B):
        from . import plan as _plan
        from .ops.xor_gemm import PackedOperand

        if isinstance(B, PackedOperand):
            # A pre-packed plane handle (see pack_operand): only the xor
            # single-device plan path can consume it, and it is already
            # bucket-padded — dispatch directly, trimming to true cols.
            if self.strategy not in ("xor", "ring") or self.mesh is not None:
                raise ValueError(
                    "packed operands require strategy='xor' or 'ring' "
                    "on a single-device codec"
                )
            return _plan.dispatch(
                A, B, w=self.w, strategy=self.strategy, cap=B.cap,
                cols=B.cols_true,
            )
        seg = B if isinstance(B, _plan.StagedSegment) else None
        staged = seg is not None
        b_cols = seg.cols if staged else None
        plan_cap = seg.cap if staged else None
        if staged:
            B = seg.array
        if self.strategy == "cpu":
            # Native host codec (the CPU-RS oracle role, cpu-rs.c) — no
            # device involved; useful as differential baseline and fallback.
            from . import native

            return native.gemm(np.asarray(A), np.asarray(B))
        if self.mesh is None:
            # A StagedSegment is already bucket-padded: it must go through
            # the plan layer (which knows to trim) even if RS_PLAN was
            # flipped off between staging and dispatch.
            use_plan = (_plan.enabled() or staged) and not isinstance(
                B, jax.core.Tracer
            )
            if self.strategy == "pallas":
                # The fused kernel is a performance feature; a Mosaic
                # compile/runtime failure must not fail the file operation.
                # The first dispatch is materialised inside the guard (async
                # dispatch would otherwise surface the error later, outside
                # it); subsequent segments run the already-proven executable
                # fully async.  That first dispatch also runs EAGERLY
                # through the module hook — RS_PALLAS_REFOLD=autotune needs
                # concrete arrays to calibrate, and tests inject failures
                # there; once proven, the plan's AOT executable (with the
                # calibrated refold baked in) takes over and may donate
                # pipeline-staged buffers.
                try:
                    if use_plan:
                        # Donate only what can be re-staged: seg.host is
                        # the recovery copy the demote path below needs.
                        out = _plan.dispatch(
                            A, B, w=self.w, strategy="pallas",
                            cap=plan_cap, cols=b_cols,
                            donate=staged and seg.host is not None
                            and self._pallas_checked,
                            eager_fn=(
                                None if self._pallas_checked else
                                lambda a, b: _gf_matmul_pallas_eager(
                                    a, b, self.w
                                )
                            ),
                        )
                    else:
                        out = _gf_matmul_pallas_eager(A, B, self.w)
                    if not self._pallas_checked:
                        jax.block_until_ready(out)
                        self._pallas_checked = True
                    return out
                except Exception as e:
                    # Broad catch, narrow handling: only known backend /
                    # Mosaic failure types demote; anything else re-raises.
                    if not isinstance(e, _pallas_failure_types()):
                        raise
                    import warnings

                    warnings.warn(
                        f"pallas GEMM failed ({type(e).__name__}); "
                        "falling back to the XLA bitplane path",
                        stacklevel=3,
                    )
                    self.strategy = "bitplane"
                    _obs_metrics.counter(
                        "rs_pallas_demotions_total",
                        "fused-kernel failures demoted to the bitplane path",
                    ).labels(path="local", error=type(e).__name__).inc()
                    if staged and seg.host is not None and B.is_deleted():
                        # The failed dispatch DONATED the staged device
                        # buffer before raising; re-stage from the retained
                        # host copy so the demoted recompute below reads
                        # real data, not a deleted array.
                        _obs_metrics.counter(
                            "rs_donation_restages_total",
                            "donated buffers re-staged from the host copy "
                            "after a donating dispatch failed",
                        ).inc()
                        B = jax.device_put(seg.host)
            if use_plan:
                return _plan.dispatch(
                    A, B, w=self.w, strategy=self.strategy,
                    cap=plan_cap, cols=b_cols,
                    donate=staged and seg.host is not None,
                )
            if self.strategy in ("xor", "ring"):
                # Value-dependent schedule: the coefficients must stay
                # concrete, so this path never rides gf_matmul_jit
                # (which would trace A).  Works under a caller's jit
                # too — only the DATA may be traced.
                if self.strategy == "ring":
                    from .ops.ring_gemm import gf_matmul_ring

                    return gf_matmul_ring(A, B, self.w)
                from .ops.xor_gemm import gf_matmul_xor

                return gf_matmul_xor(A, B, self.w)
            return gf_matmul_jit(A, B, w=self.w, strategy=self.strategy)
        from .parallel.sharded import put_sharded, sharded_gf_matmul

        m = B.shape[1]
        pad = (-m) % self._cols_size
        if pad:
            B = np.pad(np.asarray(B), ((0, 0), (0, pad)))
        Bd = put_sharded(B, self.mesh, self.stripe_sharded)

        def _sharded(A_, B_, strategy):
            # Mesh dispatches register in the same plan cache (keyed by the
            # mesh fingerprint) so compile classes are counted uniformly;
            # the executable itself stays pinned by the jitted collective.
            run = lambda a, b: sharded_gf_matmul(  # noqa: E731
                a, b, mesh=self.mesh, w=self.w, strategy=strategy,
                stripe_sharded=self.stripe_sharded,
            )
            if not _plan.enabled():
                return run(A_, B_)
            return _plan.dispatch_mesh(
                A_, B_, w=self.w, strategy=strategy, mesh=self.mesh,
                stripe_sharded=self.stripe_sharded, fn=run,
            )

        if self.strategy == "pallas":
            # Same guard discipline as the single-device path: every
            # pallas dispatch (including tail segments, which recompile
            # for their different padded shape) demotes to bitplane on a
            # Mosaic-class failure and recomputes — output bytes are
            # identical either way, so even a mid-stream demotion cannot
            # corrupt files.  The FIRST dispatch is materialised inside
            # the guard so the common failure mode (compile) resolves
            # before any caller writes output; later segments run async
            # and a runtime wedge would surface at consumption, as on the
            # single-device path.
            try:
                out = _sharded(np.asarray(A), Bd, "pallas")
                if not self._pallas_checked:
                    jax.block_until_ready(out)
                    self._pallas_checked = True
                return out[:, :m] if pad else out
            except Exception as e:
                if not isinstance(e, _pallas_failure_types()):
                    raise
                import warnings

                warnings.warn(
                    f"sharded pallas GEMM failed ({type(e).__name__}); "
                    "demoting to the XLA bitplane path",
                    stacklevel=3,
                )
                self.strategy = "bitplane"
                _obs_metrics.counter(
                    "rs_pallas_demotions_total",
                    "fused-kernel failures demoted to the bitplane path",
                ).labels(path="mesh", error=type(e).__name__).inc()
        out = _sharded(np.asarray(A), Bd, self.strategy)
        return out[:, :m] if pad else out

    # ----- decode-matrix construction (host) --------------------------------

    def decode_matrix(self, survivor_rows) -> np.ndarray:
        """Inverse of the k x k submatrix of the total matrix selected by the
        k ``survivor_rows`` (chunk indices of the survivors, in the order
        their chunks will be stacked).  Raises SingularMatrixError if the
        survivor set is not decodable."""
        rows = list(survivor_rows)
        if len(rows) != self.native_num:
            raise ValueError(
                f"need exactly k={self.native_num} survivors, got {len(rows)}"
            )
        if any(r < 0 or r >= self.n for r in rows):
            raise ValueError(f"survivor index out of range in {rows}")
        sub = self.total_matrix[rows]
        return invert_matrix(sub, self.gf)

    def decode_matrix_from(self, total_mat: np.ndarray, survivor_rows) -> np.ndarray:
        """Same, but against an externally supplied total matrix (the one
        parsed from .METADATA — the authoritative copy for decode, matching
        the reference which trusts the file over regeneration)."""
        rows = list(survivor_rows)
        total_mat = np.asarray(total_mat)
        if any(r < 0 or r >= total_mat.shape[0] for r in rows):
            raise ValueError(
                f"survivor chunk index out of range for n={total_mat.shape[0]}: {rows}"
            )
        return invert_matrix(total_mat[rows], self.gf)
