"""The maintenance control loop — ROADMAP item 3 closed.

:class:`MaintController` drains three work sources into idempotent,
resumable jobs (docs/MAINT.md):

* **repair** — :func:`obs.health.work_queue` items with damaged chunks,
  most-at-risk first, rebuilt through :func:`api.repair_file`.  The
  emitted ``rs_damage`` repair record (plus the follow-up clean scan)
  clears the queue entry: convergence is ledger-driven, never
  in-memory, so killing the process mid-repair loses nothing — the next
  pass replays the ledger and sees exactly what remains.
* **scrub** — age/update-driven re-verification via
  :func:`api.scan_file`, honoring ``RS_HEALTH_SCRUB_MAX_AGE_S``.
  Update-aware: archives whose only signal is ``generation >
  scrub_generation`` (content changed since last verified) re-verify
  before merely age-stale ones, and the clean-scan verdict they emit
  decays their risk score.
* **compaction** — store buckets whose sealed archives crossed
  ``RS_STORE_COMPACT_DEAD_FRAC`` (``pending_compactions > 0`` in
  :meth:`store.bucket.Bucket.stats`) compact through the existing
  all-or-nothing :func:`api.compact_bucket` path.

Two throttles pace the loop.  A **burn-rate governor** polls the SLO
engine (obs/slo.py): any foreground tenant burning error budget
(``burn_rate >= RS_MAINT_BURN_PAUSE``) pauses maintenance dispatch, and
it stays paused until every objective drops back under
``RS_MAINT_RESUME`` — hysteresis, so maintenance does not flap at the
boundary.  A **token bucket** caps device bytes per second
(``RS_MAINT_BYTES_PER_S``) — the only throttle when no SLO is
configured.

Cross-process safety is leases, not lock files: a job claims its
archive in the damage ledger (:func:`obs.health.record_claim`) before
touching it, other :func:`~obs.health.work_queue` consumers skip live
claims, and the claim clears on the completing repair/scan event or on
lease expiry (``RS_MAINT_LEASE_S``) if the claimant died.

Import cost: stdlib only — jobs import the jax stack lazily when they
actually run.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from collections import deque

from ..obs import health as _health
from ..obs import metrics as _metrics
from ..obs import runlog as _runlog

# DRR cost inflation for maintenance requests: admission charges
# cost-in-bytes against each tenant's quantum, so billing maintenance
# 4x its real bytes gives the maint tenant ~1/4 of a foreground
# tenant's byte share when both are backlogged — the "dedicated
# low-weight tenant" semantics without a second scheduler.
MAINT_COST_WEIGHT = 4

# Consecutive failures per target before the controller stops retrying
# it within this process (the ledger's repair_failed history and lease
# expiry pace retries across processes).
MAX_ATTEMPTS = 3


class MaintCrash(RuntimeError):
    """Synthetic mid-job crash (``RS_MAINT_CRASH=kind:stage``) — the
    chaos harness and tests inject process death at job stages with it;
    production never raises it."""


class MaintBackpressure(RuntimeError):
    """The daemon's admission queue refused the job (full or draining);
    the current pass stops and retries next interval."""


def enabled() -> bool:
    """``RS_MAINT`` truthiness: the daemon auto-starts the plane when
    set (``rs serve --maint`` forces it on for one process)."""
    val = os.environ.get("RS_MAINT", "").strip().lower()
    return val not in ("", "0", "false", "no", "off")


def tenant_env() -> str:
    """``RS_MAINT_TENANT`` — the admission-queue tenant maintenance
    jobs bill against (default ``maint``)."""
    return os.environ.get("RS_MAINT_TENANT", "").strip() or "maint"


def burn_pause_env() -> float:
    """``RS_MAINT_BURN_PAUSE`` — pause maintenance when any foreground
    objective's burn rate reaches this (default 1.0: exactly on
    budget)."""
    try:
        return float(os.environ.get("RS_MAINT_BURN_PAUSE", 1.0))
    except ValueError:
        return 1.0


def burn_resume_env() -> float:
    """``RS_MAINT_RESUME`` — resume only once every foreground burn
    rate is back under this (default 0.5; clamped to the pause
    threshold)."""
    try:
        return float(os.environ.get("RS_MAINT_RESUME", 0.5))
    except ValueError:
        return 0.5


def bytes_per_s_env() -> float:
    """``RS_MAINT_BYTES_PER_S`` — token-bucket cap on maintenance
    device bytes (default 64 MiB/s)."""
    try:
        return float(os.environ.get("RS_MAINT_BYTES_PER_S", 64 * 2**20))
    except ValueError:
        return float(64 * 2**20)


def interval_env() -> float:
    """``RS_MAINT_INTERVAL_S`` — watch-loop poll interval (default
    5 s)."""
    try:
        return float(os.environ.get("RS_MAINT_INTERVAL_S", 5.0))
    except ValueError:
        return 5.0


def _crash_point(kind: str, stage: str) -> None:
    """Raise :class:`MaintCrash` when ``RS_MAINT_CRASH`` names this
    (kind, stage) — the harness's deterministic kill switch."""
    spec = os.environ.get("RS_MAINT_CRASH", "")
    if not spec:
        return
    want_kind, _, want_stage = spec.partition(":")
    if want_kind == kind and (not want_stage or want_stage == stage):
        raise MaintCrash(f"injected crash at {kind}:{stage}")


class TokenBucket:
    """Bytes-per-second pacing with a small burst allowance.  Debt
    model: :meth:`take` always succeeds and returns how long the caller
    must sleep before proceeding, so one oversized job borrows against
    future refill instead of deadlocking."""

    def __init__(self, rate: float, capacity: float | None = None,
                 clock=time.monotonic):
        self.rate = max(1.0, float(rate))
        # ~2 s of burst by default: enough to not meter every tiny job,
        # small enough that a pause takes effect within seconds.
        self.capacity = float(capacity if capacity is not None
                              else self.rate * 2.0)
        self._tokens = self.capacity
        self._clock = clock
        self._t = clock()
        self._lock = threading.Lock()
        self.taken = 0

    def take(self, n: float) -> float:
        """Consume ``n`` tokens; returns seconds to wait before the
        consumption is paid for (0.0 when within the burst)."""
        n = max(0.0, float(n))
        with self._lock:
            now = self._clock()
            self._tokens = min(self.capacity,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            self._tokens -= n
            self.taken += int(n)
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.rate


class BurnGovernor:
    """Pause/resume hysteresis over the SLO report's burn rates.

    Any *foreground* (non-maint-tenant) objective at or past
    ``pause_at`` pauses dispatch; dispatch resumes only when every
    foreground burn rate is back under ``resume_at``.  Cells with no
    traffic in a window report no burn and never pause — absence of
    evidence is not a breach."""

    def __init__(self, *, pause_at: float | None = None,
                 resume_at: float | None = None,
                 maint_tenant: str = "maint"):
        self.pause_at = (burn_pause_env() if pause_at is None
                         else float(pause_at))
        self.resume_at = (burn_resume_env() if resume_at is None
                          else float(resume_at))
        if self.resume_at > self.pause_at:
            self.resume_at = self.pause_at
        self.maint_tenant = maint_tenant
        self.paused = False
        self.pause_events = 0
        self.resume_events = 0
        self.last_burn = 0.0
        self.worst_cell = None  # (tenant, op, window, objective)
        self.events: deque = deque(maxlen=32)

    def observe(self, report: dict | None) -> bool:
        """Fold one SLO report; returns the (possibly new) paused
        state."""
        worst, cell = 0.0, None
        for row in (report or {}).get("cells", []):
            if row.get("tenant") == self.maint_tenant:
                continue  # our own traffic must not pause us
            for win, rates in (row.get("windows") or {}).items():
                for name, vals in (rates.get("objectives") or {}).items():
                    burn = vals.get("burn_rate")
                    if isinstance(burn, (int, float)) and burn > worst:
                        worst = float(burn)
                        cell = (row.get("tenant"), row.get("op"),
                                win, name)
        self.last_burn = worst
        self.worst_cell = cell
        if not self.paused and worst >= self.pause_at:
            self.paused = True
            self.pause_events += 1
            self.events.append({"action": "pause", "burn": round(worst, 4),
                                "cell": cell})
        elif self.paused and worst < self.resume_at:
            self.paused = False
            self.resume_events += 1
            self.events.append({"action": "resume",
                                "burn": round(worst, 4)})
        try:
            _metrics.gauge(
                "rs_maint_paused",
                "1 while the burn-rate governor has maintenance paused",
            ).set(int(self.paused))
        except Exception:
            pass
        return self.paused


class MaintController:
    """The maintenance state machine: discover -> throttle -> claim ->
    execute -> let the ledger converge.

    ``submit`` (when given — the daemon wires it) dispatches a job
    closure through the admission queue as the maint tenant under the
    per-name locks and blocks until it ran; without it (CLI mode) jobs
    execute inline.  Either way every job is idempotent and all
    progress lives in the ledger/store, so a crash at any point
    converges on the next pass."""

    def __init__(self, *, ledger_path: str | None = None,
                 store_roots=None, owner: str | None = None,
                 tenant: str | None = None, slo_report=None,
                 submit=None, bytes_per_s: float | None = None,
                 burn_pause: float | None = None,
                 burn_resume: float | None = None,
                 lease_s: float | None = None,
                 interval_s: float | None = None):
        self.ledger_path = ledger_path  # None -> ambient $RS_RUNLOG
        # store_roots: list of directories containing buckets, or a
        # zero-arg callable returning one (the daemon's tenant dirs
        # appear over time).
        self.store_roots = store_roots
        self.owner = owner or f"{socket.gethostname()}:maint:{os.getpid()}"
        self.tenant = tenant or tenant_env()
        self.slo_report = slo_report  # zero-arg callable -> report dict
        self.submit = submit
        self.lease_s = float(lease_s if lease_s is not None
                             else _health.claim_lease_s())
        self.interval_s = float(interval_s if interval_s is not None
                                else interval_env())
        self.bucket = TokenBucket(bytes_per_s if bytes_per_s is not None
                                  else bytes_per_s_env())
        self.governor = BurnGovernor(pause_at=burn_pause,
                                     resume_at=burn_resume,
                                     maint_tenant=self.tenant)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.started_at: float | None = None
        self.jobs: dict = {}          # kind -> {outcome -> count}
        self.bytes_total = 0
        self.passes = 0
        self.loop_errors = 0
        self.last_error: str | None = None
        self.last_jobs: deque = deque(maxlen=16)
        self._fail_counts: dict = {}  # (kind, target) -> consecutive fails

    # -- discovery -----------------------------------------------------------

    def _roots(self) -> list[str]:
        roots = self.store_roots
        if callable(roots):
            try:
                roots = roots()
            except Exception:
                roots = []
        return [r for r in (roots or []) if isinstance(r, str)]

    def discover(self, now: float | None = None) -> dict:
        """One snapshot of actionable work, in dispatch order: repairs
        (most-at-risk first), then scrubs (update-dirtied before merely
        age-stale), then compactions.  Items claimed by a live foreign
        lease or past :data:`MAX_ATTEMPTS` local failures are excluded
        (and counted) — a drain over only-blocked work must terminate,
        not spin."""
        now = time.time() if now is None else float(now)
        jobs: list[dict] = []
        skipped_claimed = skipped_failing = 0
        state = _health.load(self.ledger_path)
        if state is not None:
            repairs, scrubs = [], []
            for item in _health.work_queue(state, now=now):
                claimant = item.get("claimed_by")
                if claimant is not None and claimant != self.owner:
                    skipped_claimed += 1
                    continue
                job = {"kind": item["action"], "target": item["archive"],
                       "risk": item["risk"], "lost": item["lost"],
                       "reason": item.get("reason")}
                if self._fail_counts.get(
                        (job["kind"], job["target"]), 0) >= MAX_ATTEMPTS:
                    skipped_failing += 1
                    continue
                (repairs if item["action"] == "repair"
                 else scrubs).append(job)
            # Update-aware scrub ordering: content that changed since
            # its last verified scan re-verifies before content that is
            # merely old (stable within each class — risk rank holds).
            scrubs.sort(key=lambda j: 0 if j.get("reason") == "update"
                        else 1)
            jobs.extend(repairs)
            jobs.extend(scrubs)
        for root in self._roots():
            try:
                from .. import store as _store
                names = _store.list_buckets(root)
            except Exception:
                continue
            for name in names:
                target = os.path.join(root, name)
                if self._fail_counts.get(
                        ("compact", target), 0) >= MAX_ATTEMPTS:
                    skipped_failing += 1
                    continue
                try:
                    bucket = (_store.cached_bucket(root, name)
                              or _store.open_bucket(root, name))
                    stats = bucket.stats()
                except Exception:
                    continue
                pending = stats.get("pending_compactions", 0)
                if pending > 0:
                    dead = sum(
                        a.get("dead_bytes", 0)
                        for a in stats.get("archives", {}).values()
                        if a.get("compaction_candidate"))
                    jobs.append({"kind": "compact", "target": target,
                                 "root": root, "bucket": name,
                                 "pending": pending,
                                 "dead_bytes": dead})
        return {"jobs": jobs, "skipped_claimed": skipped_claimed,
                "skipped_failing": skipped_failing}

    # -- execution -----------------------------------------------------------

    def _job_bytes(self, job: dict) -> int:
        """Device-byte estimate for the token bucket: the chunk bytes a
        repair/scrub must read (k+p chunk files) or the live bytes a
        compaction rewrites.  Best effort — a fallback floor keeps the
        bucket meaningful when metadata is unreadable."""
        try:
            if job["kind"] == "compact":
                return max(1, int(job.get("dead_bytes") or 0))
            meta = job["target"] + ".METADATA"
            if os.path.exists(meta):
                from ..utils import fileformat as _ff
                total, p, k, _, _, _ = _ff.read_metadata_ext(meta)
                return max(1, int(total) * max(1, k + p) // max(1, k))
            if os.path.exists(job["target"]):
                return max(1, os.path.getsize(job["target"]))
            return 1 << 16
        except Exception:
            return 1 << 16

    def _make_work(self, job: dict):
        """The idempotent job closure.  Claims ride the damage ledger
        and clear on the completing repair/scan event; crash points are
        the chaos harness's kill stages."""
        kind, target = job["kind"], job["target"]
        ledger = self.ledger_path

        def work():
            from .. import api as _api
            if kind == "repair":
                _health.record_claim(target, self.owner,
                                     lease_s=self.lease_s,
                                     ledger_path=ledger)
                _crash_point("repair", "claimed")
                rebuilt = _api.repair_file(target)
                _crash_point("repair", "mid")
                # The follow-up full scan emits the clean verdict that
                # decays risk AND clears the claim (ledger-driven).
                _api.scan_file(target)
                return {"rebuilt": len(rebuilt)}
            if kind == "scrub":
                _health.record_claim(target, self.owner,
                                     lease_s=self.lease_s,
                                     ledger_path=ledger)
                _crash_point("scrub", "claimed")
                report = _api.scan_file(target)
                bad = (len(report.get("corrupt") or [])
                       + len(report.get("missing") or [])) \
                    if isinstance(report, dict) else 0
                return {"bad_chunks": bad}
            if kind == "compact":
                _crash_point("compact", "claimed")
                out = _api.compact_bucket(job["root"], job["bucket"])
                _crash_point("compact", "done")
                return {"retired": len(out.get("archives_retired") or []),
                        "bytes_moved": out.get("bytes_moved", 0)}
            raise ValueError(f"unknown maint job kind {kind!r}")

        return work

    def run_job(self, job: dict) -> str:
        """Throttle, dispatch and account one job; returns the outcome
        (``ok``/``error``/``deferred``/``aborted``).  A
        :class:`MaintCrash` propagates — that IS the simulated process
        death."""
        est = self._job_bytes(job)
        wait = self.bucket.take(est)
        deadline = time.monotonic() + wait
        while wait > 0 and not self._stop.is_set():
            time.sleep(min(0.05, wait))
            wait = deadline - time.monotonic()
        if self._stop.is_set():
            self._account(job, "aborted", 0, 0.0)
            return "aborted"
        work = self._make_work(job)
        t0 = time.monotonic()
        outcome, detail = "ok", {}
        try:
            if self.submit is not None:
                detail = self.submit(work, name=job["target"],
                                     cost=est * MAINT_COST_WEIGHT)
            else:
                detail = work()
        except MaintCrash:
            self._account(job, "crash", est, time.monotonic() - t0)
            raise
        except MaintBackpressure:
            outcome, detail = "deferred", {}
        except Exception as e:  # noqa: BLE001 — the no-wedge contract
            outcome = "error"
            detail = {"error": f"{type(e).__name__}: {e}"}
            self.last_error = detail["error"]
        self._account(job, outcome, est, time.monotonic() - t0,
                      detail if isinstance(detail, dict) else {})
        key = (job["kind"], job["target"])
        if outcome == "ok":
            self._fail_counts.pop(key, None)
        elif outcome == "error":
            self._fail_counts[key] = self._fail_counts.get(key, 0) + 1
        return outcome

    def _account(self, job: dict, outcome: str, est: int,
                 wall: float, detail: dict | None = None) -> None:
        with self._lock:
            per = self.jobs.setdefault(job["kind"], {})
            per[outcome] = per.get(outcome, 0) + 1
            if outcome != "deferred":
                self.bytes_total += est
            self.last_jobs.append({
                "kind": job["kind"],
                "target": job["target"],
                "outcome": outcome,
                "wall_s": round(wall, 4),
                "bytes": est,
                **({"detail": detail} if detail else {}),
            })
        try:
            _metrics.counter(
                "rs_maint_jobs_total",
                "maintenance jobs dispatched, by kind and outcome",
            ).labels(kind=job["kind"], outcome=outcome).inc()
            if outcome != "deferred":
                _metrics.counter(
                    "rs_maint_bytes_total",
                    "estimated device bytes moved by maintenance jobs",
                ).inc(est)
        except Exception:
            pass

    # -- the loop ------------------------------------------------------------

    def step(self, max_jobs: int | None = None) -> dict:
        """One controller pass: poll the governor, discover, run.
        Re-polls the governor between jobs so a foreground burn that
        starts mid-pass stops dispatch within one job."""
        if self.governor.observe(self.slo_report()
                                 if self.slo_report else None):
            with self._lock:
                self.passes += 1
            return {"ran": 0, "paused": True, "deferred": False,
                    "pending": None}
        found = self.discover()
        ran = 0
        deferred = False
        for job in found["jobs"]:
            if self._stop.is_set():
                break
            if max_jobs is not None and ran >= max_jobs:
                break
            if ran and self.governor.observe(
                    self.slo_report() if self.slo_report else None):
                break
            outcome = self.run_job(job)
            if outcome == "deferred":
                deferred = True
                break
            if outcome != "aborted":
                ran += 1
        with self._lock:
            self.passes += 1
        return {"ran": ran, "paused": self.governor.paused,
                "deferred": deferred,
                "pending": max(0, len(found["jobs"]) - ran)}

    def drain(self, max_jobs: int | None = None) -> dict:
        """Run passes until a pass finds nothing actionable (the
        one-shot ``rs maint --drain`` semantics).  Paused passes wait
        one interval and retry; blocked work (foreign claims, failing
        targets) does not count as actionable, so a drain over a
        contended root terminates."""
        total = passes = 0
        while not self._stop.is_set():
            out = self.step(max_jobs=None if max_jobs is None
                            else max(0, max_jobs - total))
            passes += 1
            total += out["ran"]
            if out["paused"] or out["deferred"]:
                if self._stop.wait(min(1.0, self.interval_s)):
                    break
                continue
            if out["ran"] == 0:
                break
            if max_jobs is not None and total >= max_jobs:
                break
        found = self.discover()
        return {"jobs": total, "passes": passes,
                "remaining": len(found["jobs"]),
                "skipped_claimed": found["skipped_claimed"],
                "skipped_failing": found["skipped_failing"]}

    def start(self) -> None:
        """Spawn the watch thread (the daemon's always-on mode)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self.started_at = time.time()
        self._thread = threading.Thread(target=self._watch,
                                        name="rs-maint", daemon=True)
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except MaintCrash as e:
                # Injected process death: the thread dies here exactly
                # like a kill -9 would take it, and the ledger carries
                # the recovery state.
                with self._lock:
                    self.last_error = str(e)
                    self.loop_errors += 1
                return
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self.last_error = f"{type(e).__name__}: {e}"
                    self.loop_errors += 1

    def stop(self, wait: bool = True, timeout: float = 30.0) -> None:
        self._stop.set()
        th = self._thread
        if th is not None and wait:
            th.join(timeout=timeout)
        self._thread = None

    # -- introspection -------------------------------------------------------

    def stats(self, include_queue: bool = False) -> dict:
        with self._lock:
            out = {
                "owner": self.owner,
                "tenant": self.tenant,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "paused": self.governor.paused,
                "pause_events": self.governor.pause_events,
                "resume_events": self.governor.resume_events,
                "last_burn": round(self.governor.last_burn, 4),
                "worst_cell": list(self.governor.worst_cell)
                if self.governor.worst_cell else None,
                "burn_pause": self.governor.pause_at,
                "burn_resume": self.governor.resume_at,
                "bytes_per_s": self.bucket.rate,
                "bytes_total": self.bytes_total,
                "lease_s": self.lease_s,
                "interval_s": self.interval_s,
                "passes": self.passes,
                "loop_errors": self.loop_errors,
                "last_error": self.last_error,
                "jobs": {k: dict(v) for k, v in sorted(self.jobs.items())},
                "jobs_total": sum(n for per in self.jobs.values()
                                  for n in per.values()),
                "last_jobs": list(self.last_jobs),
                "governor_events": list(self.governor.events),
            }
        if include_queue:
            try:
                found = self.discover()
                depth = {"repair": 0, "scrub": 0, "compact": 0}
                for job in found["jobs"]:
                    depth[job["kind"]] = depth.get(job["kind"], 0) + 1
                out["queue"] = {
                    **depth,
                    "skipped_claimed": found["skipped_claimed"],
                    "skipped_failing": found["skipped_failing"],
                }
            except Exception as e:  # noqa: BLE001
                out["queue"] = {"error": f"{type(e).__name__}: {e}"}
        return out


# -- the `rs maint` CLI ------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    """The ``rs maint`` subcommand: one-shot ``--drain`` / periodic
    ``--watch`` for CLI-only deployments (no daemon), or the default
    dry-run listing of what a drain would do."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="rs maint",
        description="Background-maintenance control loop: drain the "
        "risk-ranked repair/scrub work queue and compact dead-heavy "
        "store buckets (docs/MAINT.md).",
    )
    ap.add_argument("--ledger", default=None,
                    help="damage-ledger path (default: $RS_RUNLOG)")
    ap.add_argument("--root", action="append", default=[],
                    metavar="DIR",
                    help="store root to scan for compaction work "
                    "(repeatable)")
    ap.add_argument("--drain", action="store_true",
                    help="run jobs until a pass finds nothing actionable")
    ap.add_argument("--watch", nargs="?", type=float, const=None,
                    default=False, metavar="SECS",
                    help="poll forever at SECS intervals (default "
                    "$RS_MAINT_INTERVAL_S)")
    ap.add_argument("--count", type=int, default=0,
                    help="with --watch: stop after N passes (0 = forever)")
    ap.add_argument("--max-jobs", type=int, default=0,
                    help="with --drain: stop after N jobs (0 = no cap)")
    ap.add_argument("--owner", default=None,
                    help="claim-lease owner identity (default "
                    "host:maint-cli:pid)")
    ap.add_argument("--bytes-per-s", type=float, default=None,
                    help="token-bucket byte rate override "
                    "(default $RS_MAINT_BYTES_PER_S)")
    ap.add_argument("--json", action="store_true",
                    help="emit JSON instead of the table")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    ledger = args.ledger or _runlog.path()
    if not ledger and not args.root:
        print("rs maint: no work sources (set RS_RUNLOG or pass "
              "--ledger / --root)", file=sys.stderr)
        return 2
    ctl = MaintController(
        ledger_path=ledger, store_roots=list(args.root),
        owner=args.owner
        or f"{socket.gethostname()}:maint-cli:{os.getpid()}",
        bytes_per_s=args.bytes_per_s)

    if args.watch is not False:
        if args.watch is not None:
            ctl.interval_s = max(0.1, float(args.watch))
        n = 0
        while True:
            out = ctl.step()
            n += 1
            row = {"kind": "rs_maint_pass", **out, **ctl.stats()}
            if args.json:
                print(json.dumps(row), flush=True)
            else:
                print(f"maint pass {n}: ran {out['ran']} job(s), "
                      f"pending {out['pending']}, "
                      f"{'PAUSED' if out['paused'] else 'active'} "
                      f"(burn {ctl.governor.last_burn})", flush=True)
            if args.count and n >= args.count:
                return 0
            try:
                time.sleep(max(0.1, ctl.interval_s))
            except KeyboardInterrupt:
                return 0

    if args.drain:
        out = ctl.drain(max_jobs=args.max_jobs or None)
        doc = {"kind": "rs_maint_drain", **out, "stats": ctl.stats()}
        if args.json:
            print(json.dumps(doc))
        else:
            print(f"maint drain: {out['jobs']} job(s) over "
                  f"{out['passes']} pass(es); remaining {out['remaining']} "
                  f"(claimed elsewhere {out['skipped_claimed']}, "
                  f"failing {out['skipped_failing']})")
        return 0 if out["remaining"] == 0 else 1

    # Default: dry run — list what a drain would do, touch nothing.
    found = ctl.discover()
    if args.json:
        print(json.dumps({"kind": "rs_maint_queue", **found}))
    else:
        jobs = found["jobs"]
        print(f"maint queue: {len(jobs)} job(s) "
              f"(claimed elsewhere {found['skipped_claimed']}, "
              f"failing {found['skipped_failing']})")
        for job in jobs:
            extra = (f"risk {job['risk']:.3f} lost {job['lost']} "
                     f"[{job.get('reason')}]"
                     if job["kind"] != "compact"
                     else f"pending {job['pending']} "
                     f"dead {job['dead_bytes']}B")
            print(f"  {job['kind']:<8} {extra:<36} {job['target']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
