"""``rs maint`` — the always-on background-maintenance plane.

ROADMAP item 3's control loop: repair, scrub and compaction turned from
one-shot CLI batch loops into a continuously running, throttled tenant
(docs/MAINT.md).  The measurement half already exists — every detection
site emits durable ``rs_damage`` events and :func:`obs.health.work_queue`
replays them into the deterministic risk-ranked iterator — this package
is the consumer that closes the loop:

* :mod:`.controller` — the :class:`~.controller.MaintController` state
  machine: drain the three work sources (ledger-driven repair + scrub,
  store-stats-driven compaction) into idempotent jobs, paced by a
  burn-rate governor polling the SLO engine (foreground tenants burning
  error budget pause maintenance, with hysteresis) and a token bucket
  capping device bytes per second.  Progress lives only in the ledger:
  kill the process mid-job and the next pass converges.

Import cost: stdlib only at package level; repair/scrub/compaction jobs
import the jax stack lazily when they actually run.
"""

from __future__ import annotations

__all__ = ["controller"]
