"""Write-behind I/O executor — the missing fifth pipeline stage.

The reference's whole performance story is a 3-way stream overlap — H2D ∥
kernel ∥ D2H per CUDA stream (encode.cu:165-218).  PR 1 rebuilt two thirds
of it for the TPU host runtime (SegmentPrefetcher for reads,
DeviceStagingRing for H2D), but the drain stage stayed serialized: every
``AsyncWindow.consume`` ran ``np.asarray`` (device wait + D2H) and the
``pwrite``/``fp.write`` commit on the dispatch thread, so write I/O stole
wall time from dispatch.  This module completes the 5-stage overlap

    read ∥ H2D ∥ compute ∥ D2H ∥ write

with three pieces:

* :class:`DrainExecutor` — a bounded writer-worker queue the window hands
  its (tag, future) drains to.  ``depth`` bounds queued-but-unwritten
  drains (backpressure: a slow disk eventually blocks dispatch instead of
  growing an unbounded backlog of live device buffers); worker exceptions
  re-raise at the next ``submit``/``flush``; ``ordered=True`` commits
  strictly in submit order (the streaming shared-``fp`` decode path and
  every incremental-CRC drain need it) while ``ordered=False`` lets
  ``workers`` threads race pwrite-at-offset drains out of order.
  ``workers=0`` degrades to the old synchronous inline drain
  (``RS_IO_WRITERS=0``).
* :class:`FleetPipeline` — deferred per-archive commit for multi-file
  operations: each archive's finalize (close + rename promote + checksum
  rewrite) rides the shared writer lane *behind* that archive's writes, so
  archive j+1's reads/dispatches overlap archive j's write drain instead
  of waiting for it.  Registered cleanups run on abort, keeping the
  per-archive atomicity contract.
* :func:`run_rows` — a small shared reader pool that fans the per-chunk
  preads of a segment gather across threads (distinct fds/offsets are
  independently seekable, so this is safe); used by the ``native``
  fallbacks when no C++ toolchain (whose pool, rs_native.cpp ``run_rows``,
  this mirrors) is available.

Knobs: ``RS_IO_WRITERS`` (writer threads; 0 = synchronous drain; default
1), ``RS_IO_WRITE_DEPTH`` (queued drains before dispatch blocks; default
2 x writers), ``RS_IO_READERS`` (fallback read pool; default
min(4, cores)).  Observability (docs/OBSERVABILITY.md): the
``rs_io_*`` counters/gauges and per-lane ``write_drain`` spans recorded
here make the overlap visible in Perfetto.

Import cost: stdlib only (no jax, no numpy) — same contract as ``obs``.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections.abc import Callable

from ..obs import metrics as _metrics, tracing as _tracing
from ..resilience import faults as _faults, retry as _retry


def writer_count(default: int = 1) -> int:
    """``RS_IO_WRITERS``: write-behind worker threads (0 = drain inline on
    the dispatch thread, the pre-write-behind behavior)."""
    try:
        return max(0, int(os.environ.get("RS_IO_WRITERS", default)))
    except ValueError:
        return default


def writer_depth(workers: int) -> int:
    """``RS_IO_WRITE_DEPTH``: queued-but-unwritten drains allowed before
    ``submit`` blocks.  Each queued drain pins a live device future (its
    D2H has not run), so this bounds device memory as well as host backlog.
    """
    fallback = 2 * max(1, workers)
    try:
        return max(1, int(os.environ.get("RS_IO_WRITE_DEPTH", fallback)))
    except ValueError:
        return fallback


def reader_count() -> int:
    """``RS_IO_READERS``: threads for the fallback per-chunk pread fan-out
    (1 = serial).  The native C++ pool (RS_NATIVE_IO_THREADS) is separate —
    it applies when the toolchain-built library handles the gather."""
    try:
        return max(1, int(os.environ.get("RS_IO_READERS", 0) or
                          min(4, os.cpu_count() or 1)))
    except ValueError:
        return min(4, os.cpu_count() or 1)


class DrainExecutor:
    """Bounded background executor for the pipeline's drain stage.

    ``submit(fn, nbytes=...)`` enqueues one drain callable (typically a
    closed-over ``consume(tag, future)``) and returns immediately unless
    ``depth`` drains are already queued — the backpressure that keeps a
    slow writer from accumulating unbounded live device buffers.  Worker
    exceptions are latched and re-raised at the next ``submit`` or
    ``flush`` (the dispatch loop's next touch point); after an error the
    workers discard the remaining queue so ``flush`` cannot deadlock.

    ``ordered=True`` commits strictly in submit order on one worker
    (required by shared-``fp`` streaming writes and incremental CRC
    accumulation); ``ordered=False`` races ``workers`` threads over
    offset-addressed ``pwrite`` drains.  ``workers=0`` runs every submit
    synchronously on the caller — the ``RS_IO_WRITERS=0`` escape hatch and
    the degenerate case the A/B bench compares against.

    Context manager: a clean exit flushes (barrier + error re-raise); an
    exceptional exit cancels queued drains (never half-commits a stream
    that already failed) but still joins the workers, so caller ``finally``
    blocks may safely close the files the drains write to.
    """

    _STOP = object()

    def __init__(
        self,
        workers: int | None = None,
        depth: int | None = None,
        ordered: bool = False,
        name: str = "rs-io-writer",
    ):
        if workers is None:
            workers = writer_count()
        self.ordered = ordered
        self.workers = min(workers, 1) if ordered else workers
        self.depth = depth if depth is not None else writer_depth(self.workers)
        self._q: queue.Queue | None = (
            queue.Queue(maxsize=self.depth) if self.workers else None
        )
        self._error: BaseException | None = None
        self._lock = threading.Lock()
        self._cancelled = False
        self._threads = [
            threading.Thread(
                target=self._run, name=f"{name}-{i}", daemon=True
            )
            for i in range(self.workers)
        ]
        self._started = False

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        lane = threading.current_thread().name.replace("rs-io-", "")
        while True:
            item = self._q.get()
            try:
                if item is self._STOP:
                    return
                fn, nbytes = item
                if self._error is None and not self._cancelled:
                    self._run_task(fn, nbytes, lane)
            except BaseException as e:  # noqa: BLE001 — relayed to submit/flush
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                self._q.task_done()
                self._report_depth()

    def _run_task(self, fn: Callable[[], None], nbytes: int, lane: str) -> None:
        t0 = time.perf_counter()
        # Resilience write boundary (docs/RESILIENCE.md): the fault
        # plane's write hook fires per attempt (injected ioerror/torn/
        # delay), and transient failures — injected or real — retry the
        # whole drain under the default policy.  Drains are idempotent by
        # construction: offset-addressed pwrites, restart-from-scratch
        # copies, and incremental-CRC commits deferred until after the
        # write landed.  The lane's attempted-byte accounting (torn
        # faults) counts a task's bytes once, not per retry.
        first = True

        def attempt() -> None:
            nonlocal first
            # Flag cleared BEFORE the hook: if the hook itself raises on
            # the first attempt, the retry must not re-count the bytes.
            nb = nbytes if first else 0
            first = False
            _faults.on_write(lane, nb)
            fn()

        with _tracing.span("write_drain", lane=lane, nbytes=nbytes):
            _retry.default_policy().call(attempt, op="write_drain")
        dt = time.perf_counter() - t0
        _metrics.counter(
            "rs_io_write_seconds_total",
            "wall seconds spent in drain (D2H wait + write) tasks",
        ).labels(lane=lane).inc(dt)
        _metrics.quantile(
            "rs_io_drain_wall_seconds",
            "writer-lane drain task wall seconds (streaming quantiles)",
        ).labels(lane=lane).observe(dt)

    def _report_depth(self) -> None:
        if self._q is not None:
            n = self._q.qsize()
            _metrics.gauge(
                "rs_io_writer_queue_depth",
                "drain tasks queued behind the write-behind workers",
            ).set(n)
            _tracing.counter("io_writer_queue_depth", queued=n)

    # -- caller side ---------------------------------------------------------

    def _check_error(self) -> None:
        with self._lock:
            err = self._error
        if err is not None:
            # A failed stream is dead: cancel the queue BEFORE re-raising,
            # so no drain still queued behind the failure (in a fleet, an
            # archive's finalize/promote) can run after the caller saw the
            # error.  The latched error keeps re-raising at every later
            # submit/flush.
            self._cancelled = True
            raise err

    def submit(self, fn: Callable[[], None], *, nbytes: int = 0) -> None:
        """Enqueue one drain; blocks when ``depth`` are already queued.
        Re-raises a pending worker exception instead of queueing more work
        behind a failed stream."""
        if self.workers == 0:
            self._run_task(fn, nbytes, "drain-sync")
            return
        if not self._started:
            raise RuntimeError(
                "DrainExecutor must be entered as a context manager before "
                "submit() (worker threads not started)"
            )
        self._check_error()
        self._q.put((fn, nbytes))
        self._report_depth()

    def submit_pwrite(self, fileno: int, data: bytes, offset: int) -> None:
        """Queue one offset-addressed ``os.pwrite`` drain — the
        random-access patch lane of the update/append subsystem
        (update/engine.py): an ``ordered=True`` executor commits patches
        strictly in submit order (the per-chunk ascending-offset
        invariant its incremental CRC accounting depends on), each drain
        crosses the fault plane's write boundary like every other lane,
        and a retried drain re-pwrites the same bytes at the same offset
        (idempotent by construction)."""
        nbytes = len(data)

        def task() -> None:
            done = os.pwrite(fileno, data, offset)
            if done != nbytes:
                raise OSError(
                    f"short pwrite ({done} of {nbytes} bytes at {offset})"
                )
            _metrics.counter(
                "rs_io_write_bytes_total",
                "bytes write by the staging-I/O layer",
            ).labels(call="patch_pwrite").inc(nbytes)

        self.submit(task, nbytes=nbytes)

    def flush(self) -> None:
        """Barrier: block until every submitted drain ran (or was discarded
        after an error), then re-raise the first worker exception."""
        if self._q is not None:
            self._q.join()
        self._check_error()

    def cancel(self) -> None:
        """Discard queued-but-unstarted drains (the in-progress one
        finishes).  Used on the exceptional exit path — a stream that
        already failed must not keep committing segments."""
        self._cancelled = True

    def _shutdown(self) -> None:
        if not self._started:
            return
        for _ in self._threads:
            self._q.put(self._STOP)
        for t in self._threads:
            t.join()
        self._started = False

    def __enter__(self) -> "DrainExecutor":
        for t in self._threads:
            t.start()
        self._started = bool(self._threads)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            try:
                self.flush()
            finally:
                self._shutdown()
        else:
            self.cancel()
            self._shutdown()
        return False


class FleetPipeline:
    """Deferred per-archive commit over a shared :class:`DrainExecutor`.

    Multi-file operations (``repair_fleet``, ``encode_fleet``,
    ``decode_fleet``) stream archives back to back through one writer
    lane.  Each archive's commit — close output files, promote ``.rs_tmp``
    renames, rewrite checksum lines — must run only after *that archive's*
    writes landed, but the dispatch loop must not wait for it; ``defer``
    therefore submits the finalize onto the (ordered) writer lane, where
    FIFO guarantees it runs behind the archive's last write while the main
    thread is already reading/dispatching the next archive.

    Lifecycle per archive: ``register(cleanup)`` *before* streaming starts
    (so an abort at any point can close fds and unlink the archive's temp
    files), then ``commit(key, finalize)`` after the archive's last drain
    was submitted.  A successful finalize unregisters its cleanup; on any
    failure :meth:`abort` runs every still-registered cleanup, keeping the
    same nothing-half-committed contract as a failed single-archive
    operation.  Call ``abort`` only after the executor has fully shut down
    (workers joined), so no in-flight drain races a cleanup's
    closes/unlinks.
    """

    def __init__(self, executor: DrainExecutor):
        if executor.workers and not executor.ordered:
            raise ValueError(
                "FleetPipeline needs an ordered executor: an out-of-order "
                "lane could promote an archive before its writes landed"
            )
        self.executor = executor
        self._cleanups: dict[int, Callable[[], None]] = {}
        self._n = 0
        self._lock = threading.Lock()

    def register(self, cleanup: Callable[[], None]) -> int:
        """Register an archive's failure cleanup; returns the key for
        :meth:`commit`."""
        with self._lock:
            key = self._n
            self._n += 1
            self._cleanups[key] = cleanup
        return key

    def commit(self, key: int, finalize: Callable[[], None]) -> None:
        """Queue the archive's commit behind its writes on the ordered
        writer lane.  Only a *successful* finalize releases the registered
        cleanup — a failed one leaves it for :meth:`abort`."""

        def run() -> None:
            finalize()
            with self._lock:
                self._cleanups.pop(key, None)

        self.executor.submit(run)

    def abort(self) -> None:
        with self._lock:
            pending = list(self._cleanups.values())
            self._cleanups.clear()
        for cb in pending:
            try:
                cb()
            except OSError:
                pass  # best-effort temp cleanup must not bury the cause


# -- shared reader pool ------------------------------------------------------

_POOLS: dict[int, "object"] = {}
_POOL_LOCK = threading.Lock()


def _pool(n: int):
    from concurrent.futures import ThreadPoolExecutor

    with _POOL_LOCK:
        pool = _POOLS.get(n)
        if pool is None:
            pool = _POOLS[n] = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="rs-io-reader"
            )
        return pool


def run_rows(n: int, fn: Callable[[int], None]) -> None:
    """Run ``fn(i)`` for each row ``i`` in ``range(n)``, fanned across the
    shared reader pool (``RS_IO_READERS`` wide; serial when 1 or when the
    row count doesn't warrant threads).  Blocks until every row completed;
    the first row exception re-raises here.

    Deliberately NOT a fault/retry boundary of its own: every caller is a
    segment gather that api.py already wraps in the fault plane's
    per-survivor read hook plus the default retry policy (op=
    encode/decode/repair_stage).  A second layer here would double the
    effective injected-fault rate on toolchain-less builds only (this
    pool is the native gather's fallback), raise unattributable faults
    (no chunk index -> the degraded survivor swap can't engage) and burn
    (attempts+1)^2 nested retries — so the read lane's resilience
    boundary stays one level up, uniform across builds."""
    workers = min(reader_count(), n)
    if workers <= 1:
        for i in range(n):
            fn(i)
        return
    pool = _pool(workers)
    futures = [pool.submit(fn, i) for i in range(n)]
    err = None
    for f in futures:
        try:
            f.result()
        except BaseException as e:  # noqa: BLE001 — re-raised after the join
            if err is None:
                err = e
    if err is not None:
        raise err
