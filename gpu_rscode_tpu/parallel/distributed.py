"""Multi-host initialisation — the distributed communication backend hook.

The reference's "communication backend" is PCIe memcpys + pthread barriers
inside one process (SURVEY §2); it cannot leave one machine.  The TPU build
scales past a host boundary with the standard JAX runtime: every host runs
the same SPMD program, `jax.distributed.initialize` wires the hosts into one
global device mesh, and the identical `shard_map` code from
:mod:`.sharded` then spans ICI within a slice and DCN across slices — the
collectives (the stripe-axis ``psum``) are inserted by XLA either way.

Call :func:`initialize` once per process before building meshes.  On a
single host it is a no-op, so the same entry scripts work everywhere.
"""

from __future__ import annotations

import os


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    auto: bool = False,
) -> None:
    """Initialise multi-host JAX.

    Explicit configuration comes from the arguments or the standard env
    vars (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``); if ANY of the three is present, a full explicit
    init is performed (jax validates completeness).  ``auto=True`` (or env
    ``RS_DISTRIBUTED=auto``) requests the Cloud-TPU metadata auto-detection
    (bare ``jax.distributed.initialize()``).  With neither, this is a
    no-op — safe to call unconditionally in single-process scripts.
    """
    import jax

    from . import _compat

    if _compat.distributed_is_initialized():
        _mark_telemetry_epoch(jax)
        return  # idempotent: callers (library AND cli) may both invoke this

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if auto or os.environ.get("RS_DISTRIBUTED") == "auto":
        _compat.enable_cpu_collectives()
        jax.distributed.initialize()
        _mark_telemetry_epoch(jax)
        return
    if coordinator_address is None and num_processes is None and process_id is None:
        return  # single process, nothing configured
    # CPU-backend multi-process jobs need a collectives layer (gloo)
    # selected before the client initialises; see parallel/_compat.py.
    _compat.enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _mark_telemetry_epoch(jax)


def _mark_telemetry_epoch(jax) -> None:
    """Capture the shared trace-alignment epoch (obs/aggregate.py).

    ``jax.distributed.initialize`` is a barrier every process crosses
    near-simultaneously, so the wall clock HERE is the common time anchor
    that lets per-process Perfetto traces fuse onto one axis.  Marked only
    once (re-init calls keep the first, earliest anchor).
    """
    from ..obs import tracing

    if tracing._EPOCH is None:
        tracing.mark_epoch(process_index=jax.process_index())


def global_mesh(stripe: int = 1):
    """Mesh over ALL devices of the (possibly multi-host) job.

    Lay the stripe axis within hosts where possible so the per-segment
    psum rides ICI; the cols axis (no communication) is the one that may
    span DCN.
    """
    from .mesh import make_mesh

    return make_mesh(stripe=stripe)
