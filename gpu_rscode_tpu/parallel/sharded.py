"""Sharded GF-GEMM over a device mesh — the scale-out compute path.

Capability parity with the reference's multi-GPU fan-out (one pthread per
device over disjoint byte ranges, encode.cu:240-292,357-408), redesigned as
SPMD ``shard_map`` over a ``(stripe, cols)`` mesh:

* **cols sharding** (reference's chunk-split): each device runs the
  identical fused GEMM on its column slice; zero communication, linear
  scaling.  This is the default and matches the reference's model where
  PCIe/pthreads never exchange data.
* **stripe sharding** (wide-stripe k=128 class, BASELINE config 4): the
  contraction axis k itself is sharded.  GF XOR-accumulation across devices
  cannot ride ``psum`` directly (psum adds integers), but the bit-plane
  formulation makes it exact: each device computes integer bit-plane
  partial products over its local k-slice, ``psum`` sums them over ICI
  (XOR == sum mod 2 taken AFTER the reduction), then parity-folds.  One
  collective per segment, bandwidth p*w*m bytes — the partials ride int8
  (mod-256 wrap is parity-exact, 4x less ICI than the int32 form) — the
  TPU-native equivalent the reference never had (it had no cross-device
  reduction at all; this is what unlocks stripes wider than one device's
  memory).

All functions take the GLOBAL (k, m) array; shardings are expressed with
``jax.sharding.PartitionSpec`` so the same code runs on 1 device, a v5e-8
slice, or multi-host DCN meshes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# Resolved through the compat shim: jax >= the shard_map promotion serves
# jax.shard_map (check_vma=False), the 0.4.37 pin serves
# jax.experimental.shard_map.shard_map (check_rep=False) — see
# parallel/_compat.py for why the checker is off in both spellings.
from ._compat import shard_map

if shard_map is None:  # pragma: no cover - no known jax build hits this
    raise ImportError(
        "this jax build has no shard_map implementation "
        "(neither jax.shard_map nor jax.experimental.shard_map)"
    )

from ..obs import metrics as _metrics, tracing as _tracing
from ..ops import gemm as _gemm
from ..ops.gf import get_field
from .mesh import COLS, STRIPE


def sharded_gf_matmul(A, B, *, mesh, w=8, strategy="bitplane",
                      stripe_sharded=False):
    """``C = A . B`` over GF(2^w), B sharded over the mesh.

    ``A``: (p, k) coefficient matrix (replicated; sharded along k when
    ``stripe_sharded``).  ``B``: (k, m) global data.  Returns (p, m) sharded
    along ``cols`` (replicated along ``stripe``).

    This wrapper is the mesh path's accounting boundary (the compute
    lives in the jitted ``_sharded_gf_matmul_jit``): each eager dispatch
    records a ``mesh_dispatch`` span and counts the collective payload in
    ``rs_mesh_collective_bytes_total{op}`` — stripe mode's psum moves
    ``p * w * m`` int8 pre-parity plane bytes per segment (the logical
    reduce volume; the ring transfer is ~2x that on real links), cols
    mode moves nothing — so ``rs analyze`` can attribute mesh-path cost
    next to the staged-byte counters.  Skipped under an outer trace
    (tracers have no concrete byte counts to account).
    """
    if not isinstance(B, jax.core.Tracer):
        m = int(B.shape[1])
        if stripe_sharded:
            p_rows = int(A.shape[0])
            _metrics.counter(
                "rs_mesh_collective_bytes_total",
                "logical bytes through mesh collectives per dispatch",
            ).labels(op="psum_stripe").inc(p_rows * w * m)
        with _tracing.span(
            "mesh_dispatch", lane="dispatch", strategy=str(strategy),
            stripe=bool(stripe_sharded), cols=m,
        ):
            return _sharded_gf_matmul_jit(
                A, B, mesh=mesh, w=w, strategy=strategy,
                stripe_sharded=stripe_sharded,
            )
    return _sharded_gf_matmul_jit(
        A, B, mesh=mesh, w=w, strategy=strategy,
        stripe_sharded=stripe_sharded,
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "w", "strategy", "stripe_sharded")
)
def _sharded_gf_matmul_jit(A, B, *, mesh, w=8, strategy="bitplane",
                           stripe_sharded=False):
    gf = get_field(w)
    out_dtype = jnp.uint8 if gf.dtype == np.uint8 else jnp.uint16

    if not stripe_sharded:
        if strategy == "pallas":
            # This dispatch always runs under the shard_map/jit trace,
            # where refold='autotune' cannot calibrate (the operands are
            # tracers).  Resolve the env knob to a static value HERE —
            # env "sum"/"dot" pass through, "autotune" takes the per-width
            # static default, pack2 expand yields None (its fixed pipeline
            # rejects an explicit refold) — instead of letting the
            # kernel's tracer guard warn 'cannot calibrate under a jit
            # trace' on every mesh trace: that warning is a real
            # regression signal on the eager path and must not cry wolf
            # here (ADVICE r5 finding 3).
            from ..ops.pallas_gemm import gf_matmul_pallas, static_refold

            refold = static_refold(w)

            def body(a_loc, b_loc):
                return gf_matmul_pallas(
                    a_loc, b_loc, w=w, refold=refold
                ).astype(out_dtype)

        else:

            def body(a_loc, b_loc):
                return _gemm.gf_matmul(
                    a_loc, b_loc, w=w, strategy=strategy
                ).astype(out_dtype)

        return shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(None, COLS)),
            out_specs=P(None, COLS),
        )(A, B)

    # Wide stripe: contraction axis sharded.  Integer partials + psum + parity.
    # The partial products MUST stay integer (pre-parity) so psum can carry
    # the XOR as a sum; both the XLA bitplane path and the fused Pallas
    # kernel (fold_parity=False) can emit that form.  The table path folds
    # XOR per element and cannot.
    if strategy not in ("bitplane", "pallas"):
        import warnings

        warnings.warn(
            "stripe-sharded GEMM needs a pre-parity form (bitplane/pallas); "
            f"ignoring strategy={strategy!r}",
            stacklevel=2,
        )
        strategy = "bitplane"

    use_pallas = strategy == "pallas"

    def body(a_loc, b_loc):
        if use_pallas:
            from ..ops.pallas_gemm import gf_matmul_pallas

            # int32 bit-plane partials straight from VMEM (no refold).
            acc = gf_matmul_pallas(a_loc, b_loc, w=w, fold_parity=False)
        else:
            a_bits = _gemm.expand_bitmatrix_jnp(a_loc, w)  # (p*w, k_loc*w)
            b_bits = _gemm.to_bitplanes(b_loc, w)  # (k_loc*w, m_loc)
            acc = _gemm._dot_bits(a_bits, b_bits, jnp.int8)  # int32 partials
        # The collective rides int8, not int32: each accumulator is only
        # ever read mod 2 (XOR == sum mod 2), and both the int32->int8
        # narrowing and the int8 psum wrap mod 256 — an even modulus, so
        # parity is preserved exactly (the same algebra that lets
        # shift_raw drop the plane mask).  This cuts the per-segment ICI
        # payload 4x (STATUS pins it as stripe mode's entire cost:
        # ~107 MB/device per 32 MB segment as int32, ~27 MB as int8); the
        # cast itself is XLA-level, outside the Pallas kernel, so nothing
        # new has to lower through Mosaic.  from_bitplanes upcasts to
        # int32 before its shifts, so the int8 planes fold exactly.
        acc = jax.lax.psum(acc.astype(jnp.int8), STRIPE)
        return _gemm.from_bitplanes(acc, w, dtype=out_dtype)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(None, STRIPE), P(STRIPE, COLS)),
        out_specs=P(None, COLS),
    )(A, B)


def put_sharded(B, mesh, stripe_sharded: bool = False):
    """Place a host (k, m) array on the mesh with the encode sharding.

    Single-process: ``B`` is the GLOBAL array, device_put scatters it.
    Multi-process (mesh spans hosts): ``B`` must be this process's LOCAL
    portion of the global array (each host stages the byte range it owns —
    the natural layout for multi-host file encode); the global array is
    assembled from the per-process pieces.
    """
    spec = P(STRIPE if stripe_sharded else None, COLS)
    sharding = NamedSharding(mesh, spec)
    _metrics.counter(
        "rs_mesh_segments_staged_total",
        "segments placed onto a device mesh (put_sharded)",
    ).labels(stripe=stripe_sharded, procs=jax.process_count()).inc()
    # Byte volume alongside the segment count: per-process in a multi-host
    # job (each host stages only its local portion), so the aggregate sum
    # (obs/aggregate.py) is the fleet's true staged-traffic total.
    _metrics.counter(
        "rs_mesh_staged_bytes_total",
        "bytes placed onto a device mesh (process-local portion)",
    ).labels(stripe=stripe_sharded).inc(int(B.nbytes))
    with _tracing.span(
        "mesh_stage", lane="stage", cols=int(B.shape[1]),
        stripe=bool(stripe_sharded),
    ):
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, B)
        return jax.device_put(B, sharding)
