"""Host<->HBM streaming pipeline — the CUDA-stream analog.

The reference overlaps PCIe copies with kernels by splitting each device's
slice ``streamNum`` ways and issuing H2D -> kernel -> D2H depth-first per
stream (encode.cu:165-218).  On TPU the runtime is already asynchronous:
``device_put`` and jitted dispatch return futures, and compute overlaps
host work automatically.  What still needs managing is *backpressure* — how
many segments may be in flight before the host blocks on results — and
that is exactly what :class:`AsyncWindow` provides (its ``depth`` is the
``-s`` flag).  For mesh runs the sharded placement happens in
``codec._matmul`` via ``put_sharded``, inside the same window, so the H2D
of segment i+1 overlaps compute of segment i.
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any, Generic, TypeVar

T = TypeVar("T")


class AsyncWindow(Generic[T]):
    """Bounded window of in-flight async results.

    ``depth`` = the number of segments allowed in flight: after any ``push``
    returns, at most ``depth`` futures are pending (``-s 2`` overlaps one
    segment's host work with the previous segment's compute).  ``push(tag,
    future)`` enqueues; beyond ``depth`` pending the oldest is drained
    through ``consume(tag, future)`` (which should block on the future —
    e.g. ``np.asarray`` — and commit the result).  ``flush`` drains the rest
    in order.
    """

    def __init__(self, depth: int, consume: Callable[[Any, T], None]):
        self.depth = max(1, depth)
        self.consume = consume
        self._pending: list[tuple[Any, T]] = []

    def push(self, tag: Any, future: T) -> None:
        self._pending.append((tag, future))
        while len(self._pending) > self.depth:
            self.consume(*self._pending.pop(0))

    def flush(self) -> None:
        while self._pending:
            self.consume(*self._pending.pop(0))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.flush()
        else:
            self._pending.clear()
        return False
