"""Host<->HBM streaming pipeline — the CUDA-stream analog.

The reference overlaps PCIe copies with kernels by splitting each device's
slice ``streamNum`` ways and issuing H2D -> kernel -> D2H depth-first per
stream (encode.cu:165-218).  On TPU the runtime is already asynchronous:
``device_put`` and jitted dispatch return futures, and compute overlaps
host work automatically.  What still needs managing is *backpressure* — how
many segments may be in flight before the host blocks on results — and
that is exactly what :class:`AsyncWindow` provides (its ``depth`` is the
``-s`` flag).  For mesh runs the sharded placement happens in
``codec._matmul`` via ``put_sharded``, inside the same window, so the H2D
of segment i+1 overlaps compute of segment i.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Callable, Iterable
from typing import Any, Generic, TypeVar

from ..obs import metrics as _metrics, tracing as _tracing

T = TypeVar("T")

# Process-lifetime staging-ring occupancy watermark (see
# DeviceStagingRing._report_occupancy).  Held in a list so tests can
# reset it without rebinding the module attribute they imported.
_RING_PEAK = [0]


class AsyncWindow(Generic[T]):
    """Bounded window of in-flight async results.

    ``depth`` = the number of segments allowed in flight: after any ``push``
    returns, at most ``depth`` futures are pending (``-s 2`` overlaps one
    segment's host work with the previous segment's compute).  ``push(tag,
    future)`` enqueues; beyond ``depth`` pending the oldest is drained
    through ``consume(tag, future)`` (which should block on the future —
    e.g. ``np.asarray`` — and commit the result).  ``flush`` drains the rest
    in order.

    With an ``executor`` (:class:`.io_executor.DrainExecutor`) the drain
    becomes *write-behind*: instead of running ``consume`` on the dispatch
    thread, the oldest pending (tag, future) is handed to the executor's
    bounded writer queue and ``push`` returns immediately — the fifth
    pipeline stage (write ∥ dispatch).  Backpressure then comes from the
    executor's ``depth``; in-flight device futures are bounded by
    ``window depth + executor depth + workers``.  A worker exception
    re-raises at the next ``push``/``flush`` (via ``executor.submit``);
    note ``flush`` only *hands off* the remaining pending futures — the
    executor's own ``flush`` (its context exit) is the write barrier.
    """

    def __init__(
        self,
        depth: int,
        consume: Callable[[Any, T], None],
        executor=None,
    ):
        self.depth = max(1, depth)
        self.consume = consume
        self.executor = executor
        self._pending: list[tuple[Any, T]] = []

    def _report_depth(self) -> None:
        n = len(self._pending)
        _metrics.gauge(
            "rs_pipeline_inflight",
            "async segments in flight (AsyncWindow pending futures)",
        ).set(n)
        _tracing.counter("pipeline_inflight", inflight=n)

    def _drain_oldest(self) -> None:
        tag, future = self._pending.pop(0)
        if self.executor is not None:
            # Device futures know their size; the byte count feeds the
            # write_drain span args and per-lane accounting (docs/IO.md).
            nbytes = getattr(future, "nbytes", 0) or 0
            self.executor.submit(
                lambda: self.consume(tag, future), nbytes=int(nbytes)
            )
        else:
            self.consume(tag, future)

    def push(self, tag: Any, future: T) -> None:
        self._pending.append((tag, future))
        self._report_depth()
        while len(self._pending) > self.depth:
            self._drain_oldest()
            self._report_depth()

    def flush(self) -> None:
        while self._pending:
            self._drain_oldest()
            self._report_depth()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.flush()
        else:
            # Abort: drop the pending futures unconsumed — but leave the
            # inflight gauge/counter track reset to zero, not frozen at its
            # last nonzero sample (a stale gauge would read as a live
            # pipeline long after the window died).
            dropped = len(self._pending)
            self._pending.clear()
            if dropped:
                self._report_depth()
                _tracing.instant(
                    "pipeline_aborted", lane="dispatch", dropped=dropped
                )
        return False


class DeviceStagingRing:
    """Double-buffered device staging: the H2D transfer of segment i+1 is
    issued while segment i computes and segment i-1 drains.

    Completes the 3-stage H2D -> compute -> D2H pipeline of the reference's
    stream loop (encode.cu:165-218) on the device side: the
    :class:`SegmentPrefetcher` overlaps *read IO* with everything, the
    :class:`AsyncWindow` overlaps *D2H + write IO* with compute — but the
    H2D placement itself used to happen inside the dispatch call, so the
    transfer of segment i+1 only started after segment i's dispatch
    returned.  This ring pulls ``depth`` segments ahead of the consumer and
    calls ``stage(tag, host_seg)`` on each (typically
    ``codec.stage_segment`` — an async ``jax.device_put`` of the
    bucket-padded segment), so the DMA is in flight before the consumer
    asks for the data.

    ``source`` yields ``(tag, host_segment)`` (a SegmentPrefetcher is one);
    iteration yields ``(tag, staged)`` in source order.  ``stage`` runs on
    the consumer thread (device_put returns immediately; nothing here
    blocks), and its exceptions propagate at the consuming ``__next__``.
    ``depth=2`` is the double-buffer: one segment staged ahead of the one
    being handed out.
    """

    def __init__(self, source, stage, depth: int = 2):
        self._source = iter(source)
        self._stage = stage
        self._depth = max(1, depth)
        self._staged: list = []
        self._exhausted = False

    def _report_occupancy(self) -> None:
        n = len(self._staged)
        _metrics.gauge(
            "rs_staging_ring_occupancy",
            "segments staged on-device ahead of the consumer",
        ).set(n)
        # High-watermark across EVERY ring of the process (one ring per
        # file op — a per-ring peak would let a later small op overwrite
        # the fleet answer "did any ring ever fill" downward).  The
        # module global is gated on enabled() so a climb during a
        # disabled run cannot suppress the gauge of a later enabled one.
        if _metrics.enabled() and n > _RING_PEAK[0]:
            _RING_PEAK[0] = n
            _metrics.gauge(
                "rs_staging_ring_occupancy_peak",
                "process-wide high watermark of staged segments ahead "
                "of the consumer",
            ).set(n)
        _tracing.counter("staging_ring_occupancy", staged=n)

    def _fill(self) -> None:
        while not self._exhausted and len(self._staged) < self._depth:
            try:
                tag, host = next(self._source)
            except StopIteration:
                self._exhausted = True
                return
            self._staged.append((tag, self._stage(tag, host)))

    def __iter__(self):
        return self

    def __next__(self):
        self._fill()
        if not self._staged:
            raise StopIteration
        tag, staged = self._staged.pop(0)
        self._fill()  # issue the next H2D before handing this segment out
        # ONE sample per handed-out segment, after pop+refill: steady state
        # reads depth, the tail drain (source exhausted, ring emptying)
        # shows the occupancy actually falling to zero.
        self._report_occupancy()
        return tag, staged


class SegmentPrefetcher:
    """Stage segments on a worker thread into a bounded queue.

    Completes the three-way overlap of the reference's stream loop
    (encode.cu:165-218: H2D || kernel || D2H): JAX's async dispatch already
    overlaps device compute with the drain's D2H+write, but in a
    single-threaded loop the *read* of segment i+depth only starts after the
    drain of segment i returns — read IO and write IO serialize.  With the
    pread gather on its own thread, steady-state encode wall approaches
    max(read, compute, write) instead of read + max(compute, write).

    ``segments``: (off, cols) tags, staged in order.  ``produce(off, cols)``
    runs on the worker thread (it must be thread-safe against the consumer's
    work — the pread/memmap gathers are: distinct fds/offsets).  ``depth``
    bounds staged-but-unconsumed segments, so host memory holds at most
    ``depth + 1`` staged segments beyond the AsyncWindow's in-flight ones.

    Iterating yields ``((off, cols), staged)`` in order.  A ``produce``
    exception re-raises at the consuming ``__next__``.  Exiting the context
    early (consumer exception) cancels the worker promptly: the worker
    checks a stop flag before each stage and uses timeouts around queue
    puts.
    """

    _STOP = object()

    def __init__(
        self,
        segments: Iterable[tuple[int, int]],
        produce: Callable[[int, int], Any],
        depth: int = 2,
    ):
        self._segments = list(segments)
        self._produce = produce
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._started = False
        self._thread = threading.Thread(
            target=self._run, name="rs-segment-prefetch", daemon=True
        )

    def _run(self) -> None:
        try:
            for off, cols in self._segments:
                if self._stop.is_set():
                    return
                item = ((off, cols), self._produce(off, cols))
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
            self._put_forever((self._STOP, None))
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            self._put_forever((self._STOP, e))

    def _put_forever(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        if not self._started:
            # Outside the context manager the worker thread never started,
            # so q.get() below would block forever — fail loudly instead.
            raise RuntimeError(
                "SegmentPrefetcher must be used as a context manager "
                "(worker thread not started; iterate inside 'with')"
            )
        tag, item = self._q.get()
        if tag is self._STOP:
            self._stop.set()  # idempotent; lets join() return fast
            if item is not None:
                raise item
            raise StopIteration
        return tag, item

    def __enter__(self):
        self._thread.start()
        self._started = True
        return self

    def __exit__(self, exc_type, exc, tb):
        self._stop.set()
        # Unblock a worker waiting on put() by draining whatever is queued.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=30)
        return False
