"""Device-mesh construction helpers.

The framework's two parallel axes (SURVEY §2 parallelism items 1-2,
re-expressed for a TPU slice):

* ``cols`` — the chunk-column axis.  Embarrassingly parallel (the
  reference's per-GPU byte-range split, encode.cu:368-380): every device
  holds a column slice of ALL stripe rows; no communication ever.
* ``stripe`` — the k (stripe-row) axis, used for wide stripes (k=128 class
  configs) where one device shouldn't hold all k rows.  The XOR-accumulation
  across devices becomes an integer ``psum`` over bit-plane partials riding
  ICI (see :mod:`.sharded`).

A 1-D mesh uses ``cols`` only; a 2-D mesh ``(stripe, cols)`` composes both.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

COLS, STRIPE = "cols", "stripe"


def make_mesh(n_devices: int | None = None, stripe: int = 1) -> Mesh:
    """Mesh over the first ``n_devices`` devices, shaped
    ``(stripe, n_devices // stripe)`` with axes ``(STRIPE, COLS)``."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    if n % stripe:
        raise ValueError(f"{n} devices not divisible by stripe={stripe}")
    arr = np.array(devs[:n]).reshape(stripe, n // stripe)
    return Mesh(arr, (STRIPE, COLS))
