"""jax version compatibility shims for the parallel layer.

The one shim that matters today: ``shard_map``.  The jax 0.4.37 pin this
environment carries predates the promotion of ``shard_map`` to the
top-level namespace — there it lives at
``jax.experimental.shard_map.shard_map`` with the older ``check_rep``
knob instead of ``check_vma``.  Resolving the symbol here (instead of at
``parallel/sharded.py`` import time) is what burned down the carried
14-test mesh failure set (docs/STATUS.md, ROADMAP item 4): every one of
those failures was this single attribute lookup.

Both spellings are wrapped with their varying-axes checker disabled
(``check_vma=False`` new / ``check_rep=False`` old) for the same reason
documented at the original call site: the checker cannot type
``pallas_call`` outputs or scan carries initialised inside the body;
correctness is covered by the oracle-equality tests on the virtual mesh.
"""

from __future__ import annotations

import functools


def resolve_shard_map():
    """The callable ``parallel.sharded`` builds its collectives with, or
    ``None`` when this jax build has no shard_map at all (callers degrade
    to a clear error at mesh-dispatch time, not at import)."""
    import jax

    if hasattr(jax, "shard_map"):
        return functools.partial(jax.shard_map, check_vma=False)
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:
        return None
    return functools.partial(_shard_map, check_rep=False)


shard_map = resolve_shard_map()


def shard_map_available() -> bool:
    """Whether a shard_map implementation resolved (either spelling) —
    the `rs doctor` mesh-section probe."""
    return shard_map is not None


def enable_cpu_collectives() -> None:
    """Select the gloo CPU collectives implementation when the option
    exists and is still at its 'none' default.

    Multi-process jobs on the CPU backend (the 2-process integration
    tests, CPU-only fleet tooling) need a cross-process collectives
    layer or XLA refuses with "Multiprocess computations aren't
    implemented on the CPU backend".  Must run before the CPU client
    initialises; harmless on TPU/GPU backends (the knob only steers CPU
    client construction) and a no-op on jax builds without the option."""
    import jax

    try:
        if jax.config._read("jax_cpu_collectives_implementation") == "none":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # option absent (old/new jax) or backend already up


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists (newer jax);
    the 0.4.37 pin predates it, so fall back to the runtime's global
    client state (set iff initialize() completed), and to False when
    even that internal moved."""
    import jax

    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:
        return False
