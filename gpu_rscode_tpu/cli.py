"""``rs`` command-line driver — flag-compatible with the reference CLI.

Reference surface (main.c:32-164): encode ``-k <k> -n <n> -e <file>``;
decode ``-d -i <file> -c <conf> [-o <out>]``; tuning ``-p`` (device grid
cap -> here: per-dispatch SEGMENT sizing — the loose analog; the kernel's
actual column tile is set from committed sweeps and overridable via env
``RS_PALLAS_TILE``, the true gridDim.x-cap counterpart) and ``-s``
(stream count -> here: pipeline depth, number of segments in flight);
``-h`` help; upper- and lower-case flags both accepted.  ``-i/-c/-o`` are
rejected unless a decode was selected first, matching the reference's
ordering rule.

Extensions (flagged long options, no reference equivalent):
``--generator {vandermonde,cauchy}``,
``--strategy {auto,bitplane,table,pallas,cpu}`` (default auto: the fused
pallas kernel on TPU hardware, meshes included — every fused dispatch is
guarded with a bitplane fallback — bitplane elsewhere), ``--devices N`` /
``--stripe S``
(mesh sharding), ``--quiet`` (suppress the timing report),
``--profile-dir DIR`` (jax.profiler trace output).
"""

from __future__ import annotations

import getopt
import os
import sys

from .utils.timing import PhaseTimer

_USAGE = """Usage:
[-h]: show usage information
Encode: [-k|-K nativeBlockNum] [-n|-N totalBlockNum] [-e|-E fileName]
        (extra positional files after the flags encode a whole batch
        through one shared write-behind lane: file j+1 reads/dispatches
        while file j's writes drain)
Decode: [-d|-D] [-i|-I originalFileName] [-c|-C config] [-o|-O output]
For encoding, the -k, -n, and -e options are all necessary.
For decoding, the -d, -i, and -c options are all necessary.
If -o is not set, the original file name is used as the output file name.
Performance-tuning options:
[-p|-P]: per-dispatch segment-size hint (p * 128 KiB per segment); the
         kernel's internal column tile comes from committed sweeps and is
         overridable via env RS_PALLAS_TILE
[-s|-S]: pipeline depth (segments in flight, default 2)
Extensions: [--generator vandermonde|cauchy]
            [--strategy auto|bitplane|table|pallas|cpu]  (default auto:
            pallas kernel on TPU incl. meshes, bitplane elsewhere;
            cpu = host codec)
            [--segment-bytes N] [--quiet] [--profile-dir DIR]
            [--devices N] [--stripe S]  (shard over a device mesh;
            S > 1 additionally shards the stripe/k axis)
            [--checksum]  (encode: record per-chunk CRC32 in .METADATA)
            [--no-verify] (decode: skip checksum verification)
            [--width 8|16] (encode: GF symbol width; 16 = wide-symbol
            extension recorded in .METADATA, decode auto-detects)
            [--auto] (decode without -c: discover healthy chunks, skip
            corrupt ones via CRC32, pick a decodable subset.  Extra
            positional archives after the flags decode a whole batch
            through one shared write-behind lane)
            [--repair] (with -i: rebuild every lost/corrupt chunk in place,
            parity included; refreshes CRC lines.  Extra positional files
            after the flags repair a whole fleet: all survivor-matrix
            inversions run in one batched device dispatch)
            [--scrub]  (with -i: read-only health report as one JSON line)
Observability (docs/OBSERVABILITY.md):
            [--metrics-json PATH] (encode/decode/repair: collect the
            RS_METRICS registry during the run — enabled automatically —
            and dump the unified snapshot, plan cache included, as JSON)
            [--trace PATH] (encode/decode/repair: write a per-segment
            Chrome-trace/Perfetto timeline; equivalent to RS_TRACE=PATH)
Subcommand:  rs stats [--text] [--workload]
            (dump the unified observability snapshot of this process;
            --text = Prometheus exposition, --workload = run a synthetic
            multi-tail encode first)
"""


def _stats_main(argv: list[str]) -> int:
    """The ``rs stats`` subcommand: dump the unified observability
    snapshot (metrics registry + plan-cache stats + autotune decisions)."""
    import argparse
    import json

    from .obs import metrics as obs_metrics

    ap = argparse.ArgumentParser(
        prog="rs stats",
        description="Dump the unified observability snapshot "
        "(RS_METRICS registry + plan cache + autotune decisions).",
    )
    ap.add_argument(
        "--text", action="store_true",
        help="Prometheus text exposition instead of one-line JSON",
    )
    ap.add_argument(
        "--workload", action="store_true",
        help="run the synthetic multi-tail encode workload first "
        "(a fresh process otherwise has little to report)",
    )
    # No --reset flag: a CLI invocation exits right after dumping, so a
    # registry clear could never be observed; in-process embedders use
    # obs.metrics.REGISTRY.reset() directly.
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        # Same int-return contract as every other usage-error path (_fail
        # returns 2); argparse must not raise through a programmatic
        # main() caller.
        return int(e.code or 0)
    if args.workload:
        obs_metrics.force_enable()
        from .tools.plan_stats import run_workload

        run_workload()
    if args.text:
        print(obs_metrics.REGISTRY.render_text(), end="")
    else:
        print(json.dumps(obs_metrics.unified_snapshot()))
    return 0


def _fail(msg: str) -> "int":
    print(msg, file=sys.stderr)
    print(_USAGE, file=sys.stderr)
    return 2


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "stats":
        return _stats_main(argv[1:])
    try:
        # gnu_getopt: flags may follow the fleet-repair positional archives
        # (the reference surface has no positionals, so ordering semantics
        # for its flags are unchanged — opts keeps argv order).
        opts, extra = getopt.gnu_getopt(
            argv,
            "S:s:P:p:K:k:N:n:E:e:I:i:C:c:O:o:DdHh",
            [
                "generator=",
                "strategy=",
                "segment-bytes=",
                "quiet",
                "profile-dir=",
                "devices=",
                "stripe=",
                "checksum",
                "no-verify",
                "width=",
                "auto",
                "repair",
                "scrub",
                "metrics-json=",
                "trace=",
            ],
        )
    except getopt.GetoptError as e:
        return _fail(f"rs: {e}")
    flags_seen = {fl.lower() for fl, _ in opts}
    # Batch (fleet) surfaces take positional files after the flags:
    # --repair (fleet repair), -e (batch encode), -d --auto (batch decode).
    if extra and not (
        "--repair" in flags_seen
        or "-e" in flags_seen
        or ("-d" in flags_seen and "--auto" in flags_seen)
    ):
        return _fail(f"rs: unexpected arguments {extra}")

    native_num = total_num = 0
    pipeline_depth = 2
    tile_hint = 0
    in_file = conf_file = out_file = None
    op = None
    generator, strategy = "vandermonde", "auto"
    segment_bytes = None
    quiet = False
    profile_dir = None
    n_devices = 0
    stripe = 1
    checksum = False
    no_verify = False
    width = 8
    auto = False
    repair = False
    scrub = False
    metrics_json = None
    trace_path = None

    repair_requested = any(fl in ("--repair", "--scrub") for fl, _ in opts)
    for flag, val in opts:
        f = flag.lower()
        if f in ("-s",):
            pipeline_depth = int(val)
        elif f in ("-p",):
            tile_hint = int(val)
        elif f in ("-k",):
            native_num = int(val)
        elif f in ("-n",):
            total_num = int(val)
        elif f in ("-e",):
            in_file, op = val, "encode"
        elif f in ("-d",):
            op = "decode"
        elif f in ("-i", "-c", "-o"):
            # -i is also the --repair target; the reference ordering rule
            # (-i/-c/-o only after -d) applies to the reference surface.
            if op != "decode" and not (f == "-i" and repair_requested):
                return _fail(f"rs: {flag} is only valid after -d (decode)")
            if f == "-i":
                in_file = val
            elif f == "-c":
                conf_file = val
            else:
                out_file = val
        elif f == "-h":  # getopt folds -H here via f.lower()
            print(_USAGE)
            return 0
        elif f == "--generator":
            generator = val
        elif f == "--strategy":
            strategy = val
        elif f == "--segment-bytes":
            segment_bytes = int(val)
        elif f == "--quiet":
            quiet = True
        elif f == "--profile-dir":
            profile_dir = val
        elif f == "--devices":
            n_devices = int(val)
        elif f == "--stripe":
            stripe = int(val)
        elif f == "--checksum":
            checksum = True
        elif f == "--no-verify":
            no_verify = True
        elif f == "--width":
            width = int(val)
        elif f == "--auto":
            auto = True
        elif f == "--repair":
            repair = True
        elif f == "--scrub":
            scrub = True
        elif f == "--metrics-json":
            metrics_json = val
        elif f == "--trace":
            trace_path = val

    if repair and scrub:
        return _fail("rs: --repair and --scrub conflict")
    if repair:
        if op == "encode" or auto or conf_file or out_file:
            return _fail("rs: --repair takes only -i (plus tuning flags)")
        op = "repair"
        if extra and n_devices:
            # Rejected HERE, before distributed.initialize()/make_mesh can
            # block or raise: the batched fleet path is single-host.
            return _fail("rs: fleet --repair does not take --devices")
    if scrub:
        if op == "encode" or auto or conf_file or out_file:
            return _fail("rs: --scrub takes only -i")
        if n_devices:
            return _fail(
                "rs: --scrub is host-only (CRC reads, no device compute); "
                "--devices does not apply"
            )
        op = "scrub"
    if op is None:
        return _fail("rs: choose encode (-e), decode (-d), or --repair -i <file>")
    if op in ("repair", "scrub") and not in_file:
        return _fail(f"rs: --{op} requires -i")
    if checksum and op != "encode":
        return _fail("rs: --checksum is encode-only (decode verifies automatically)")
    if no_verify and op != "decode":
        return _fail("rs: --no-verify is decode-only")
    if width != 8 and op != "encode":
        return _fail("rs: --width is encode-only (decode reads it from .METADATA)")
    if width not in (8, 16):
        return _fail(f"rs: --width must be 8 or 16, got {width}")
    if auto and op != "decode":
        return _fail("rs: --auto is decode-only")
    if auto and conf_file:
        return _fail("rs: -c and --auto conflict; pick one survivor source")
    if op == "scrub" and (metrics_json or trace_path):
        return _fail(
            "rs: --metrics-json/--trace apply to encode/decode/repair "
            "(scrub is a host-only CRC pass)"
        )
    if stripe > 1 and not n_devices:
        return _fail("rs: --stripe requires --devices")
    if extra and op in ("encode", "decode"):
        # Batch encode/decode stream through the single-host fleet lane.
        if n_devices:
            return _fail(f"rs: batch {op} does not take --devices")
        if op == "decode" and out_file:
            return _fail(
                "rs: batch --auto decode does not take -o "
                "(outputs are written in place, one per archive)"
            )

    if metrics_json:
        # Fail fast on an unwritable snapshot path — AFTER every pure
        # usage validation above (no probe file on a usage error), BEFORE
        # the slow jax import / mesh init below and long before the run
        # whose metrics the user would otherwise lose.  A newly created
        # (empty) probe file gets a "{}" placeholder so every later exit
        # — even an uncaught mesh-init crash before the try/finally —
        # leaves valid JSON, never a zero-byte file; dump_metrics()
        # overwrites it with the real snapshot.
        try:
            with open(metrics_json, "a") as fp:
                if fp.tell() == 0:
                    fp.write("{}\n")
        except OSError as e:
            return _fail(f"rs: cannot write --metrics-json path: {e}")

    # Import lazily: jax init is slow and -h must be instant.
    from . import api

    kwargs = dict(strategy=strategy, pipeline_depth=max(1, pipeline_depth))
    if n_devices:
        from .parallel import distributed
        from .parallel.mesh import make_mesh

        # Env-driven no-op single-process; under JAX_COORDINATOR_ADDRESS /
        # JAX_NUM_PROCESSES / JAX_PROCESS_ID it joins the multi-host job so
        # --devices can span processes (the file ops become collectives).
        distributed.initialize()
        kwargs["mesh"] = make_mesh(n_devices, stripe=stripe)
        kwargs["stripe_sharded"] = stripe > 1
    if segment_bytes:
        kwargs["segment_bytes"] = segment_bytes
    elif tile_hint:
        # -p caps the per-dispatch column extent, the closest analog of the
        # reference's gridDim.x cap (encode.cu:348-355).
        kwargs["segment_bytes"] = max(1, tile_hint) * 128 * 1024

    if metrics_json:
        # Collection must be on DURING the run; --metrics-json implies it
        # (the in-process equivalent of exporting RS_METRICS=1).
        from .obs import metrics as obs_metrics

        obs_metrics.force_enable()
    if trace_path:
        kwargs["trace_path"] = trace_path  # == RS_TRACE=PATH for this op

    def dump_metrics() -> None:
        # Called on success AND failure: the snapshot is most valuable
        # when a long run died near the end, and a zero-byte probe file
        # left behind would crash downstream json.load's.
        if not metrics_json:
            return
        import json

        from .obs import metrics as obs_metrics

        try:
            with open(metrics_json, "w") as fp:
                json.dump(obs_metrics.unified_snapshot(), fp)
                fp.write("\n")
        except OSError as e:  # writability probed up front; disk-full etc.
            print(f"rs: metrics snapshot write failed: {e}", file=sys.stderr)

    timer = PhaseTimer(enabled=True)
    ctx = None
    if profile_dir:
        import jax

        ctx = jax.profiler.trace(profile_dir)
        ctx.__enter__()
    try:
        if op == "encode":
            if native_num <= 0 or total_num <= 0 or not in_file:
                return _fail("rs: encoding requires -k, -n and -e")
            if total_num <= native_num:
                return _fail(f"rs: need n > k (got n={total_num}, k={native_num})")
            if extra:
                # Batch encode: -e <first> plus positional files, one
                # shared write-behind lane (--devices rejected above, so
                # kwargs carries no mesh here).
                fleet = [in_file] + list(extra)
                api.encode_fleet(
                    fleet,
                    native_num,
                    total_num - native_num,
                    generator=generator,
                    checksums=checksum,
                    w=width,
                    timer=timer,
                    **kwargs,
                )
                nbytes = sum(os.path.getsize(f) for f in fleet)
            else:
                api.encode_file(
                    in_file,
                    native_num,
                    total_num - native_num,
                    generator=generator,
                    checksums=checksum,
                    w=width,
                    timer=timer,
                    **kwargs,
                )
                nbytes = os.path.getsize(in_file)
        elif op == "scrub":
            import json

            report = api.scan_file(
                in_file,
                **(
                    {"segment_bytes": kwargs["segment_bytes"]}
                    if "segment_bytes" in kwargs
                    else {}
                ),
            )
            print(json.dumps(report))
            # "unknown" (subset search capped) is not proven healthy -> 1.
            return 0 if report["decodable"] is True else 1
        elif op == "repair":
            if extra:
                # Fleet mode: -i <first> plus positional archives (the
                # --devices combination was rejected at validation, so
                # kwargs carries no mesh here).
                fleet = [in_file] + list(extra)
                results = api.repair_fleet(fleet, timer=timer, **kwargs)
                for f in fleet:
                    reb = results[f]
                    print(f"{f}: rebuilt {reb}" if reb else f"{f}: healthy")
                nbytes = sum(
                    os.path.getsize(f) for f in fleet if os.path.exists(f)
                )
            else:
                rebuilt = api.repair_file(in_file, timer=timer, **kwargs)
                print(
                    f"rebuilt chunks: {rebuilt}"
                    if rebuilt else "archive healthy"
                )
                nbytes = (
                    os.path.getsize(in_file) if os.path.exists(in_file) else 0
                )
        else:
            if not in_file or (not conf_file and not auto):
                return _fail("rs: decoding requires -i and -c (or --auto)")
            if auto and extra:
                # Batch decode: -i <first> plus positional archives, one
                # shared write-behind lane (--devices/-o rejected above).
                fleet = [in_file] + list(extra)
                results = api.decode_fleet(
                    fleet,
                    verify_checksums=False if no_verify else None,
                    timer=timer, **kwargs,
                )
                for f in fleet:
                    print(f"{f}: decoded -> {results[f]}")
                nbytes = sum(os.path.getsize(results[f]) for f in fleet)
            elif auto:
                out = api.auto_decode_file(
                    in_file, out_file,
                    verify_checksums=False if no_verify else None,
                    timer=timer, **kwargs,
                )
            else:
                out = api.decode_file(
                    in_file, conf_file, out_file,
                    verify_checksums=False if no_verify else None,
                    timer=timer, **kwargs,
                )
            if not (auto and extra):
                nbytes = os.path.getsize(out)
    except (ValueError, FileNotFoundError, OSError) as e:
        print(f"rs: error: {e}", file=sys.stderr)
        return 1
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
        # In the finally: the snapshot must land on EVERY exit from the
        # run — success, handled error, unhandled exception (device
        # runtime errors, KeyboardInterrupt on a long encode) or a
        # post-probe validation _fail — never leaving the zero-byte
        # writability-probe file behind.
        dump_metrics()

    if not quiet:
        print(f"== {op} {in_file} ==")
        print(timer.summary(data_bytes=nbytes))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
