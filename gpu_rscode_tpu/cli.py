"""``rs`` command-line driver — flag-compatible with the reference CLI.

Reference surface (main.c:32-164): encode ``-k <k> -n <n> -e <file>``;
decode ``-d -i <file> -c <conf> [-o <out>]``; tuning ``-p`` (device grid
cap -> here: per-dispatch SEGMENT sizing — the loose analog; the kernel's
actual column tile is set from committed sweeps and overridable via env
``RS_PALLAS_TILE``, the true gridDim.x-cap counterpart) and ``-s``
(stream count -> here: pipeline depth, number of segments in flight);
``-h`` help; upper- and lower-case flags both accepted.  ``-i/-c/-o`` are
rejected unless a decode was selected first, matching the reference's
ordering rule.

Extensions (flagged long options, no reference equivalent):
``--generator {vandermonde,cauchy}``,
``--strategy {auto,bitplane,table,pallas,xor,ring,cpu}`` (default auto,
resolved per backend by the strategy autotuner: the fused pallas kernel
on TPU hardware, meshes included — every fused dispatch is guarded with
a bitplane fallback — bitplane elsewhere; RS_STRATEGY_AUTOTUNE=measure
lets xor/native compete on real timings), ``--devices N`` /
``--stripe S``
(mesh sharding), ``--quiet`` (suppress the timing report),
``--profile-dir DIR`` (jax.profiler trace output).
"""

from __future__ import annotations

import getopt
import os
import sys

from .utils.timing import PhaseTimer

_USAGE = """Usage:
[-h]: show usage information
Encode: [-k|-K nativeBlockNum] [-n|-N totalBlockNum] [-e|-E fileName]
        (extra positional files after the flags encode a whole batch
        through one shared write-behind lane: file j+1 reads/dispatches
        while file j's writes drain)
Decode: [-d|-D] [-i|-I originalFileName] [-c|-C config] [-o|-O output]
For encoding, the -k, -n, and -e options are all necessary.
For decoding, the -d, -i, and -c options are all necessary.
If -o is not set, the original file name is used as the output file name.
Performance-tuning options:
[-p|-P]: per-dispatch segment-size hint (p * 128 KiB per segment); the
         kernel's internal column tile comes from committed sweeps and is
         overridable via env RS_PALLAS_TILE
[-s|-S]: pipeline depth (segments in flight, default 2)
Extensions: [--generator vandermonde|cauchy]
            [--strategy auto|bitplane|table|pallas|xor|ring|cpu]
            (default auto: resolved by the per-backend strategy
            autotuner — pallas kernel on TPU incl. meshes, bitplane
            elsewhere, RS_STRATEGY_AUTOTUNE=measure to compete on
            timings; xor = bitsliced XOR lowering, docs/XOR.md;
            ring = polynomial-ring lowering; cpu = host codec)
            [--segment-bytes N] [--quiet] [--profile-dir DIR]
            [--devices N] [--stripe S]  (shard over a device mesh;
            S > 1 additionally shards the stripe/k axis)
            [--checksum]  (encode: record per-chunk CRC32 in .METADATA)
            [--no-verify] (decode: skip checksum verification)
            [--width 8|16] (encode: GF symbol width; 16 = wide-symbol
            extension recorded in .METADATA, decode auto-detects)
            [--layout row|interleaved] (encode: chunk layout; interleaved
            = append-mode extension — file symbol s lives in row s%k,
            so rs append only touches the tail column block; recorded
            in .METADATA, decode auto-detects; docs/UPDATE.md)
            [--auto] (decode without -c: discover healthy chunks, skip
            corrupt ones via CRC32, pick a decodable subset.  Extra
            positional archives after the flags decode a whole batch
            through one shared write-behind lane)
            [--locate] (decode without -c OR CRCs: error-LOCATING decode
            — parity-check syndromes find silent bitrot in up to
            floor((p - missing)/2) chunks per symbol column, patch it,
            then reconstruct; damage past that bound fails loudly
            instead of fabricating bytes.  RS_LOCATE=auto|off|force
            tunes the --auto escalation ladder; docs/RESILIENCE.md)
            [--repair] (with -i: rebuild every lost/corrupt chunk in place,
            parity included; refreshes CRC lines.  Extra positional files
            after the flags repair a whole fleet: all survivor-matrix
            inversions run in one batched device dispatch)
            [--scrub]  (with -i: read-only health report as one JSON line)
            [--syndrome] (with --scrub: add the error-locating pre-check
            — syndromes attribute silent bitrot to its chunk index with
            no CRCs, reported as state "silent_bitrot")
Observability (docs/OBSERVABILITY.md):
            [--metrics-json PATH] (any operation, --scrub included:
            collect the RS_METRICS registry during the run — enabled
            automatically — and dump the unified snapshot, plan cache
            included, as JSON; multi-process jobs write PATH.p<i> per
            process, merged by `rs aggregate`)
            [--trace PATH] (write a per-segment Chrome-trace/Perfetto
            timeline; equivalent to RS_TRACE=PATH; PATH.p<i> per process
            on multi-process jobs)
            RS_RUNLOG=PATH appends one ledger record per operation;
            RS_METRICS_PORT=P serves /metrics live during the run
Resilience (docs/RESILIENCE.md):
            [--faults SPEC] (deterministic fault injection at the I/O
            boundaries, e.g. "read:ioerror@p=0.02;write:torn@after=1MiB";
            equivalent to RS_FAULTS=SPEC, seeded by RS_FAULTS_SEED;
            RS_RETRY_* env knobs tune the retry/backoff policy)
Subcommands: rs update ARCHIVE --at OFF --in DELTA [--recover] [--json]
            (delta-parity partial-stripe update: overwrite a byte range
            of the archived file in place — parity' = parity XOR E*delta,
            only the touched segment columns move; crash-atomic via the
            undo journal + metadata generation; per-chunk CRCs fixed by
            seekable crc32-combine.  --recover resolves a torn op's
            journal and exits; --edits FILE coalesces a batch of
            OFFSET:PAYLOADFILE / append:PAYLOADFILE records into
            group-committed window groups of up to RS_UPDATE_GROUP_WINDOW
            edits — one journal fsync chain + one metadata commit per
            group, each group all-or-nothing; docs/UPDATE.md)
            rs append ARCHIVE --in DATA [--json]
            (append-mode encoding: grow the archive without touching
            cold segments — unbounded on interleaved-layout archives,
            slack-bounded on row-layout ones; torn appends roll back at
            the next open)
            rs stats [--text] [--workload]
            (dump the unified observability snapshot of this process;
            --text = Prometheus exposition, --workload = run a synthetic
            multi-tail encode first)
            rs history [--op OP] [--k K] [--n N] [--w W] [--strategy S]
            [--last N] [--json] [--save-baseline NAME]
            [--regress NAME [--threshold F] [--window N]]
            (trend the RS_RUNLOG run ledger; --regress exits 3 when the
            recent window's mean GB/s drops below the named baseline)
            rs serve-metrics [--port P] [--addr A] [--runlog PATH]
            (foreground HTTP endpoint: /metrics, /healthz, /runs)
            rs aggregate INPUT... [--snapshot-out F] [--trace-out F] [--text]
            (merge per-process {path}.p<i> snapshots/traces from a
            multi-host run into one snapshot / one Perfetto file)
            rs chaos [--seed S] [--iters N] [--only I] [--repro JSON]
            [--silent]
            (seeded encode -> corrupt -> scrub/decode/repair loop,
            differential-checked against the native oracle; failures
            shrink to a one-line reproducer.  --silent runs the CRC-less
            bitrot class recovered by the error-locating decoder)
            rs analyze [--json] [--strategies S,S] [--k K] [--p P]
            [--size-kb N] [--refresh-roofline]
            (roofline attribution: per-strategy achieved GB/s, GFLOP/s,
            arithmetic intensity and a memory/compute/dispatch bound
            verdict against the calibrated host roofline)
            rs doctor [--json]
            (one-shot environment diagnostic: backend/devices, native
            lib, mesh sanity, RS_* knobs, ledger/endpoint reachability,
            serve-daemon health, roofline freshness, fleet health)
            rs health [--json] [--top N] [--watch [SECS] [--count N]]
            [--ledger PATH] [--snapshot]
            (risk-ranked fleet durability report replayed from the
            RS_RUNLOG damage ledger: per-archive distance-to-data-loss
            margin weighted by bitrot recurrence, scrub staleness and
            repair-failure history; --snapshot checkpoints the state
            back to the ledger; the same ranking feeds the daemon's
            GET /health, rs_durability_* gauges and the repair
            work queue; docs/HEALTH.md)
            rs maint [--ledger PATH] [--root DIR ...] [--drain]
            [--watch [SECS] [--count N]] [--max-jobs N] [--json]
            (background-maintenance controller: drains the repair work
            queue, age/update-driven scrubs and dead-heavy bucket
            compactions as idempotent lease-claimed jobs, throttled by
            an SLO burn-rate governor and an RS_MAINT_BYTES_PER_S token
            bucket; default lists the pending queue, --drain runs until
            converged, --watch loops like the daemon's resident tenant;
            docs/MAINT.md)
            rs perf [--runlog PATH] [--captures DIR] [--record]
            [--check] [--drift-frac F] [--host H] [--backend B] [--json]
            (per-(host,backend,strategy,op,shape-bucket) throughput
            baselines folded from RS_PROF rs_perf dispatch events, op
            records and bench captures; --record blesses the current
            medians as kind=rs_perf_baseline, --check exits 4 when the
            worst cell drifts below RS_PERF_DRIFT_FRAC (default 0.85)
            of baseline and 2 with no evidence; docs/OBSERVABILITY.md)
            rs serve [--root DIR] [--port P] [--addr A] [--depth N]
            [--batch-ms MS] [--max-batch N] [--workers N]
            [--warm K,N[,W]] [--faults SPEC] [--slo SPEC]
            (resident multi-tenant encode/decode daemon: POST /encode
            /decode /scrub with streaming bodies, X-RS-Tenant fairness,
            429 past RS_SERVE_DEPTH, cross-request batching into the
            warm plan cache, graceful drain on SIGTERM; every response
            echoes X-RS-Request-Id, GET /slo + /debug/requests expose
            the request lifecycle plane; docs/SERVE.md)
            rs loadgen [--url U | --spawn] [--duration S] [--rate R]
            [--tenants a:3,b:1] [--size-kb N] [--decode-frac F]
            [--update-frac F] [--k K] [--n N] [--seed S] [--ab --files N]
            [--faults SPEC] [--slo SPEC] [--capture PATH] [--json]
            (open-loop Poisson load harness for rs serve: offered vs
            achieved throughput, per-tenant latency percentiles, bench
            capture with per-request ids + stage breakdowns; --slo
            configures objectives on the spawned daemon and exits 4 on
            a missed window — open-loop runs double as SLO gates; --ab
            times resident-daemon vs CLI-subprocess-per-file)
            rs slo [--url U | --runlog PATH [--slo SPEC]] [--check]
            [--json]
            (per-tenant SLO attainment + burn rates over rolling
            windows: scrape a live daemon's GET /slo, or replay
            kind=rs_request ledger records offline; --check exits 4
            on any missed objective; docs/SERVE.md)
            rs object put|get|rm|ls|stat|compact BUCKET [KEY] [--root D]
            (object-store façade: millions of small objects packed into
            shared erasure-coded stripe archives — durable object
            index, group-committed PUT batches, range-window GET,
            tombstone+zeroing DELETE, all-or-nothing compaction;
            docs/STORE.md)
            RS_PROFILE=DIR wraps every file operation (scrub/fleet/chaos
            included) in a jax.profiler capture; --profile-dir is the
            per-run alias
"""


def _stats_main(argv: list[str]) -> int:
    """The ``rs stats`` subcommand: dump the unified observability
    snapshot (metrics registry + plan-cache stats + autotune decisions)."""
    import argparse
    import json

    from .obs import metrics as obs_metrics

    ap = argparse.ArgumentParser(
        prog="rs stats",
        description="Dump the unified observability snapshot "
        "(RS_METRICS registry + plan cache + autotune decisions).",
    )
    ap.add_argument(
        "--text", action="store_true",
        help="Prometheus text exposition instead of one-line JSON",
    )
    ap.add_argument(
        "--workload", action="store_true",
        help="run the synthetic multi-tail encode workload first "
        "(a fresh process otherwise has little to report)",
    )
    # No --reset flag: a CLI invocation exits right after dumping, so a
    # registry clear could never be observed; in-process embedders use
    # obs.metrics.REGISTRY.reset() directly.
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        # Same int-return contract as every other usage-error path (_fail
        # returns 2); argparse must not raise through a programmatic
        # main() caller.
        return int(e.code or 0)
    if args.workload:
        obs_metrics.force_enable()
        from .tools.plan_stats import run_workload

        run_workload()
    if args.text:
        print(obs_metrics.REGISTRY.render_text(), end="")
    else:
        print(json.dumps(obs_metrics.unified_snapshot()))
    return 0


def _history_main(argv: list[str]) -> int:
    """The ``rs history`` subcommand: filter/trend the persistent run
    ledger (obs/runlog.py) by op + config, with ``--regress`` comparing
    the recent window against a named baseline (the measurement-driven
    regression watch — exit 3 past the threshold, so a cron job or CI
    step can gate on it)."""
    import argparse
    import json
    import statistics
    import time as _time

    from .obs import runlog as obs_runlog

    ap = argparse.ArgumentParser(
        prog="rs history",
        description="Trend the RS_RUNLOG run ledger (and capture_header-"
        "style bench captures) by op + config; --regress gates on a "
        "named throughput baseline.",
    )
    ap.add_argument("--runlog", default=None,
                    help="ledger path (default: $RS_RUNLOG)")
    ap.add_argument("--op", help="filter: op (or bench tool) name")
    ap.add_argument("--k", type=int, help="filter: native chunk count")
    ap.add_argument("--n", type=int, help="filter: total chunk count")
    ap.add_argument("--w", type=int, help="filter: GF symbol width")
    ap.add_argument("--strategy", help="filter: GEMM strategy")
    ap.add_argument("--host", help="filter: origin hostname")
    ap.add_argument("--last", type=int, default=0,
                    help="list only the last N filtered records")
    ap.add_argument("--json", action="store_true",
                    help="emit filtered records as JSONL instead of text")
    ap.add_argument("--window", type=int, default=20,
                    help="records in the trend/baseline window (last N)")
    ap.add_argument("--save-baseline", metavar="NAME",
                    help="store the current window's throughput under NAME")
    ap.add_argument("--regress", metavar="NAME",
                    help="compare the current window against baseline NAME; "
                    "exit 3 when mean GB/s drops past --threshold")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="--regress tolerance as a fraction (default 0.25 = "
                    "fail when >25%% below the baseline mean)")
    ap.add_argument("--baselines", default=None,
                    help="baseline store (default: <runlog>.baselines.json)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    ledger = args.runlog or os.environ.get("RS_RUNLOG")
    if not ledger:
        print("rs history: no ledger — pass --runlog or set RS_RUNLOG",
              file=sys.stderr)
        return 2
    if not (os.path.exists(ledger) or os.path.exists(ledger + ".1")):
        print(f"rs history: ledger not found: {ledger}", file=sys.stderr)
        return 1
    recs = obs_runlog.filter_records(
        obs_runlog.read_records(ledger),
        op=args.op, k=args.k, n=args.n, w=args.w,
        strategy=args.strategy, host=args.host,
    )
    shown = recs[-args.last:] if args.last else recs
    window = recs[-args.window:] if args.window else recs
    gbps = [g for g in map(obs_runlog.throughput_gbps, window)
            if g is not None]
    errors = sum(1 for r in recs if r.get("outcome") == "error")

    if args.json:
        for r in shown:
            print(json.dumps(r))
    elif not (args.save_baseline or args.regress):
        for r in shown:
            cfg = r.get("config") or {}
            when = _time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", _time.gmtime(r.get("ts", 0))
            )
            g = obs_runlog.throughput_gbps(r)
            print(
                f"{when} {r.get('op') or r.get('tool') or '?':<13}"
                f" k={cfg.get('k', '-')} n={cfg.get('n', '-')}"
                f" w={cfg.get('w', '-')} {cfg.get('strategy', '-'):<9}"
                f" {r.get('bytes') or 0:>12}B {r.get('wall_s') or 0:>9.3f}s"
                f" {f'{g:.3f}GB/s' if g is not None else '-':>11}"
                f" {r.get('outcome', '?')}"
            )
        from .obs.percentile import quantile_of

        walls = [r.get("wall_s") for r in window
                 if r.get("outcome", "ok") == "ok"
                 and isinstance(r.get("wall_s"), (int, float))]
        print(
            f"# {len(recs)} records ({errors} errors); window of "
            f"{len(window)}: "
            + (
                f"mean {statistics.fmean(gbps):.3f} GB/s "
                f"(p50 {quantile_of(gbps, 0.5):.3f}, "
                f"p99 {quantile_of(gbps, 0.99):.3f}), "
                f"best {max(gbps):.3f} GB/s over {len(gbps)} measured"
                if gbps else "no throughput-measurable records"
            )
            + (
                f"; wall p50 {quantile_of(walls, 0.5):.3f}s "
                f"p99 {quantile_of(walls, 0.99):.3f}s"
                if walls else ""
            ),
            file=sys.stderr,
        )

    if not (args.save_baseline or args.regress):
        return 0
    if not gbps:
        print("rs history: no successful records with bytes+wall in the "
              "window — nothing to baseline or compare", file=sys.stderr)
        return 1
    mean = statistics.fmean(gbps)
    store = args.baselines or ledger + ".baselines.json"
    baselines: dict = {}
    if os.path.exists(store):
        try:
            with open(store) as fp:
                baselines = json.load(fp)
        except (OSError, ValueError) as e:
            print(f"rs history: unreadable baseline store {store}: {e}",
                  file=sys.stderr)
            return 1
    if args.save_baseline:
        baselines[args.save_baseline] = {
            "gbps_mean": round(mean, 6),
            "gbps_best": round(max(gbps), 6),
            "count": len(gbps),
            "saved_ts": _time.time(),
            "filter": {
                key: val for key, val in (
                    ("op", args.op), ("k", args.k), ("n", args.n),
                    ("w", args.w), ("strategy", args.strategy),
                    ("host", args.host),
                ) if val is not None
            },
        }
        with open(store, "w") as fp:
            json.dump(baselines, fp, indent=2)
            fp.write("\n")
        print(f"saved baseline {args.save_baseline!r}: mean {mean:.3f} GB/s "
              f"over {len(gbps)} records -> {store}", file=sys.stderr)
    if args.regress:
        base = baselines.get(args.regress)
        if base is None:
            print(f"rs history: no baseline {args.regress!r} in {store} "
                  f"(have: {sorted(baselines) or 'none'})", file=sys.stderr)
            return 1
        floor = base["gbps_mean"] * (1.0 - args.threshold)
        verdict = (
            f"window mean {mean:.3f} GB/s vs baseline "
            f"{args.regress!r} {base['gbps_mean']:.3f} GB/s "
            f"(floor {floor:.3f} at threshold {args.threshold:.0%})"
        )
        if mean < floor:
            print(f"REGRESSION: {verdict}", file=sys.stderr)
            return 3
        print(f"ok: {verdict}", file=sys.stderr)
    return 0


def _serve_main(argv: list[str]) -> int:
    """The ``rs serve-metrics`` subcommand: a foreground telemetry
    endpoint (/metrics, /healthz, /runs) for this process — see
    obs/serve.py.  ``RS_METRICS_PORT`` on a normal file operation starts
    the same server for just that run's duration."""
    import argparse

    from .obs import metrics as obs_metrics, serve as obs_serve

    ap = argparse.ArgumentParser(
        prog="rs serve-metrics",
        description="Serve /metrics (Prometheus text), /healthz and /runs "
        "(run-ledger tail) over HTTP.",
    )
    ap.add_argument("--port", type=int, default=None,
                    help="bind port (default $RS_METRICS_PORT or 9464)")
    ap.add_argument("--addr", default=None,
                    help="bind address (default $RS_METRICS_ADDR or 0.0.0.0)")
    ap.add_argument("--runlog", default=None,
                    help="ledger served at /runs (default: $RS_RUNLOG)")
    ap.add_argument("--workload", action="store_true",
                    help="run the synthetic encode workload first so a "
                    "fresh process has series to scrape")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    if args.port is None:
        try:
            args.port = int(os.environ.get("RS_METRICS_PORT", "9464"))
        except ValueError:
            print(
                f"rs serve-metrics: RS_METRICS_PORT="
                f"{os.environ['RS_METRICS_PORT']!r} is not a port",
                file=sys.stderr,
            )
            return 2
    obs_metrics.force_enable()
    if args.workload:
        from .tools.plan_stats import run_workload

        run_workload()
    try:
        server = obs_serve.make_server(args.port, args.runlog, args.addr)
    except OSError as e:
        print(f"rs serve-metrics: cannot bind: {e}", file=sys.stderr)
        return 1
    host, port = server.server_address[:2]
    print(f"serving /metrics /healthz /runs on http://{host}:{port}",
          file=sys.stderr)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return 0


def _update_main(argv: list[str], op: str) -> int:
    """The ``rs update`` / ``rs append`` subcommands (docs/UPDATE.md):
    delta-parity partial-stripe updates and append-mode encoding —
    parity' = parity ⊕ E·Δ, only the touched segment columns move."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog=f"rs {op}",
        description=(
            "Patch a byte range of an encoded archive in place: only the "
            "touched segment columns are read, the parity delta E*delta "
            "is XOR-patched, and per-chunk CRCs are fixed incrementally "
            "(crash-atomic via the undo journal)."
            if op == "update" else
            "Grow an encoded archive: interleaved-layout archives extend "
            "every chunk by just the tail column block (cold columns "
            "untouched); row-layout archives accept appends bounded by "
            "their tail-padding slack.  Torn appends roll back at the "
            "next open."
        ),
    )
    ap.add_argument("archive", help="the encoded file (chunk files and "
                    ".METADATA live next to it)")
    if op == "update":
        ap.add_argument("--at", type=int, default=None,
                        help="byte offset of the edit in the original file")
        ap.add_argument("--recover", action="store_true",
                        help="only resolve a pending torn update/append "
                        "journal (rollback), then exit")
        ap.add_argument("--edits", metavar="FILE", default=None,
                        help="group-commit batch mode: coalesce the edits "
                        "listed in FILE into window groups of up to "
                        "RS_UPDATE_GROUP_WINDOW edits, each group "
                        "independently all-or-nothing (one journal fsync "
                        "chain + one metadata commit per group).  One "
                        "edit per line, OFFSET:PAYLOADFILE for an update "
                        "or append:PAYLOADFILE for an append; '#' "
                        "comments and blank lines are skipped; payload "
                        "paths resolve relative to FILE's directory")
    ap.add_argument("--in", dest="in_path", metavar="FILE", default=None,
                    help=("the replacement bytes" if op == "update"
                          else "the bytes to append"))
    ap.add_argument("--strategy", default="auto",
                    choices=("auto", "bitplane", "table", "pallas", "xor",
                             "ring", "cpu"))
    ap.add_argument("--segment-bytes", type=int, default=None,
                    help="column block sizing (default 64 MiB of natives)")
    ap.add_argument("--json", action="store_true",
                    help="emit the op summary as one JSON line")
    ap.add_argument("--quiet", action="store_true")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    from . import api

    try:
        if op == "update" and args.recover:
            verdict = api.recover_archive(args.archive)
            print(json.dumps({"archive": args.archive,
                              "recovered": verdict}))
            return 0
        timer = PhaseTimer(enabled=not args.quiet)
        if op == "update" and args.edits is not None:
            if args.in_path is not None or args.at is not None:
                print("rs update: --edits replaces --at/--in (the batch "
                      "file lists every edit)", file=sys.stderr)
                return 2
            try:
                edits = _parse_edit_lines(args.edits)
            except (OSError, ValueError) as e:
                print(f"rs update: bad --edits file: {e}", file=sys.stderr)
                return 2
            kwargs = dict(strategy=args.strategy, timer=timer)
            if args.segment_bytes:
                kwargs["segment_bytes"] = args.segment_bytes
            summary = api.update_file_many(args.archive, edits, **kwargs)
        else:
            if args.in_path is None:
                print(f"rs {op}: --in FILE is required", file=sys.stderr)
                return 2
            if op == "update" and args.at is None:
                print("rs update: --at OFFSET is required", file=sys.stderr)
                return 2
            kwargs = dict(src=args.in_path, strategy=args.strategy)
            if args.segment_bytes:
                kwargs["segment_bytes"] = args.segment_bytes
            kwargs["timer"] = timer
            if op == "update":
                summary = api.update_file(args.archive, args.at, **kwargs)
            else:
                summary = api.append_file(args.archive, **kwargs)
    except (ValueError, FileNotFoundError, OSError) as e:
        print(f"rs {op}: error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(summary))
    elif not args.quiet:
        print(f"== {op} {args.archive} ==")
        if summary.get("op") == "group":
            print(
                f"{summary['edits']} edit(s) in {summary['groups']} "
                f"group(s) -> {summary['bytes']} payload bytes, "
                f"{summary['windows']} window(s), {summary['segments']} "
                f"segment block(s), chunks {summary['chunks_touched']}, "
                f"generation {summary['generation']}, "
                f"total {summary['total_size']}"
            )
        else:
            print(
                f"{summary['bytes']} payload bytes -> {summary['segments']} "
                f"segment block(s), chunks {summary['chunks_touched']}, "
                f"generation {summary['generation']}, "
                f"total {summary['total_size']}"
            )
        print(timer.summary(data_bytes=summary["bytes"]))
    return 0


def _parse_edit_lines(path: str) -> list[dict]:
    """``--edits`` batch file: one ``OFFSET:PAYLOADFILE`` or
    ``append:PAYLOADFILE`` record per line (docs/UPDATE.md "Group
    commit"); payload paths resolve relative to the batch file."""
    base = os.path.dirname(os.path.abspath(path))
    edits: list[dict] = []
    with open(path) as fp:
        for ln, line in enumerate(fp, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            head, sep, payload = line.partition(":")
            if not sep or not payload:
                raise ValueError(
                    f"line {ln}: want OFFSET:PAYLOADFILE or "
                    f"append:PAYLOADFILE, got {line!r}"
                )
            src = os.path.join(base, payload.strip())
            if head.strip() == "append":
                edits.append({"op": "append", "src": src})
            else:
                try:
                    at = int(head)
                except ValueError:
                    raise ValueError(
                        f"line {ln}: offset {head!r} is not an integer "
                        "(or the keyword 'append')"
                    ) from None
                edits.append({"op": "update", "at": at, "src": src})
    if not edits:
        raise ValueError("no edit records (every line blank or comment)")
    return edits


def _fail(msg: str) -> "int":
    print(msg, file=sys.stderr)
    print(_USAGE, file=sys.stderr)
    return 2


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "stats":
        return _stats_main(argv[1:])
    if argv and argv[0] == "history":
        return _history_main(argv[1:])
    if argv and argv[0] == "serve-metrics":
        return _serve_main(argv[1:])
    if argv and argv[0] == "aggregate":
        from .obs.aggregate import main as _aggregate_main

        return _aggregate_main(argv[1:])
    if argv and argv[0] == "chaos":
        from .resilience.chaos import main as _chaos_main

        return _chaos_main(argv[1:])
    if argv and argv[0] == "analyze":
        from .obs.attrib import main as _analyze_main

        return _analyze_main(argv[1:])
    if argv and argv[0] == "doctor":
        from .obs.doctor import main as _doctor_main

        return _doctor_main(argv[1:])
    if argv and argv[0] == "health":
        from .obs.health import main as _health_main

        return _health_main(argv[1:])
    if argv and argv[0] == "maint":
        from .maint.controller import main as _maint_main

        return _maint_main(argv[1:])
    if argv and argv[0] == "perf":
        from .obs.perfbase import main as _perf_main

        return _perf_main(argv[1:])
    if argv and argv[0] == "serve":
        from .serve.daemon import main as _serve_daemon_main

        return _serve_daemon_main(argv[1:])
    if argv and argv[0] == "loadgen":
        from .serve.loadgen import main as _loadgen_main

        return _loadgen_main(argv[1:])
    if argv and argv[0] == "slo":
        from .obs.slo import main as _slo_main

        return _slo_main(argv[1:])
    if argv and argv[0] == "object":
        from .store.cli import main as _object_main

        return _object_main(argv[1:])
    if argv and argv[0] in ("update", "append"):
        return _update_main(argv[1:], argv[0])
    try:
        # gnu_getopt: flags may follow the fleet-repair positional archives
        # (the reference surface has no positionals, so ordering semantics
        # for its flags are unchanged — opts keeps argv order).
        opts, extra = getopt.gnu_getopt(
            argv,
            "S:s:P:p:K:k:N:n:E:e:I:i:C:c:O:o:DdHh",
            [
                "generator=",
                "strategy=",
                "segment-bytes=",
                "quiet",
                "profile-dir=",
                "devices=",
                "stripe=",
                "checksum",
                "no-verify",
                "width=",
                "layout=",
                "auto",
                "locate",
                "repair",
                "scrub",
                "syndrome",
                "metrics-json=",
                "trace=",
                "faults=",
            ],
        )
    except getopt.GetoptError as e:
        return _fail(f"rs: {e}")
    flags_seen = {fl.lower() for fl, _ in opts}
    # Batch (fleet) surfaces take positional files after the flags:
    # --repair (fleet repair), -e (batch encode), -d --auto (batch decode).
    if extra and not (
        "--repair" in flags_seen
        or "-e" in flags_seen
        or ("-d" in flags_seen and "--auto" in flags_seen)
    ):
        return _fail(f"rs: unexpected arguments {extra}")

    native_num = total_num = 0
    pipeline_depth = 2
    tile_hint = 0
    in_file = conf_file = out_file = None
    op = None
    generator, strategy = "vandermonde", "auto"
    segment_bytes = None
    quiet = False
    profile_dir = None
    n_devices = 0
    stripe = 1
    checksum = False
    no_verify = False
    width = 8
    layout = "row"
    auto = False
    locate = False
    repair = False
    scrub = False
    syndrome = False
    metrics_json = None
    trace_path = None
    faults_spec = None

    repair_requested = any(fl in ("--repair", "--scrub") for fl, _ in opts)
    for flag, val in opts:
        f = flag.lower()
        if f in ("-s",):
            pipeline_depth = int(val)
        elif f in ("-p",):
            tile_hint = int(val)
        elif f in ("-k",):
            native_num = int(val)
        elif f in ("-n",):
            total_num = int(val)
        elif f in ("-e",):
            in_file, op = val, "encode"
        elif f in ("-d",):
            op = "decode"
        elif f in ("-i", "-c", "-o"):
            # -i is also the --repair target; the reference ordering rule
            # (-i/-c/-o only after -d) applies to the reference surface.
            if op != "decode" and not (f == "-i" and repair_requested):
                return _fail(f"rs: {flag} is only valid after -d (decode)")
            if f == "-i":
                in_file = val
            elif f == "-c":
                conf_file = val
            else:
                out_file = val
        elif f == "-h":  # getopt folds -H here via f.lower()
            print(_USAGE)
            return 0
        elif f == "--generator":
            generator = val
        elif f == "--strategy":
            strategy = val
        elif f == "--segment-bytes":
            segment_bytes = int(val)
        elif f == "--quiet":
            quiet = True
        elif f == "--profile-dir":
            profile_dir = val
        elif f == "--devices":
            n_devices = int(val)
        elif f == "--stripe":
            stripe = int(val)
        elif f == "--checksum":
            checksum = True
        elif f == "--no-verify":
            no_verify = True
        elif f == "--width":
            width = int(val)
        elif f == "--layout":
            layout = val
        elif f == "--auto":
            auto = True
        elif f == "--locate":
            locate = True
        elif f == "--repair":
            repair = True
        elif f == "--scrub":
            scrub = True
        elif f == "--syndrome":
            syndrome = True
        elif f == "--metrics-json":
            metrics_json = val
        elif f == "--trace":
            trace_path = val
        elif f == "--faults":
            faults_spec = val

    # One validation for every surface that takes --strategy (encode,
    # decode, repair, batch fleets): the same enumerated usage error the
    # update/append argparse choices produce, HERE as a usage failure
    # instead of a mid-run codec ValueError after files were opened.
    from .tune import VALID_STRATEGIES

    if strategy not in VALID_STRATEGIES:
        return _fail(
            f"rs: unknown --strategy {strategy!r}; valid strategies are "
            + "|".join(VALID_STRATEGIES)
        )

    fault_plan = None
    if faults_spec is not None:
        # Validate the grammar HERE (usage error, not a mid-run surprise);
        # the plan is activated around the operation below — identical
        # semantics to RS_FAULTS=SPEC (seeded by RS_FAULTS_SEED) without
        # mutating the process env, which would leak the fault plane into
        # later in-process main() calls (tests, embedders).
        from .resilience import faults as _res_faults

        try:
            fault_plan = _res_faults.parse_plan(
                faults_spec, seed=_res_faults.env_seed()
            )
        except ValueError as e:
            return _fail(f"rs: bad --faults spec: {e}")

    if repair and scrub:
        return _fail("rs: --repair and --scrub conflict")
    if repair:
        if op == "encode" or auto or conf_file or out_file:
            return _fail("rs: --repair takes only -i (plus tuning flags)")
        op = "repair"
        if extra and n_devices:
            # Rejected HERE, before distributed.initialize()/make_mesh can
            # block or raise: the batched fleet path is single-host.
            return _fail("rs: fleet --repair does not take --devices")
    if scrub:
        if op == "encode" or auto or conf_file or out_file:
            return _fail("rs: --scrub takes only -i")
        if n_devices:
            return _fail(
                "rs: --scrub is host-only (CRC reads, no device compute); "
                "--devices does not apply"
            )
        op = "scrub"
    if op is None:
        return _fail("rs: choose encode (-e), decode (-d), or --repair -i <file>")
    if op in ("repair", "scrub") and not in_file:
        return _fail(f"rs: --{op} requires -i")
    if checksum and op != "encode":
        return _fail("rs: --checksum is encode-only (decode verifies automatically)")
    if no_verify and op != "decode":
        return _fail("rs: --no-verify is decode-only")
    if width != 8 and op != "encode":
        return _fail("rs: --width is encode-only (decode reads it from .METADATA)")
    if width not in (8, 16):
        return _fail(f"rs: --width must be 8 or 16, got {width}")
    if layout != "row":
        if op != "encode":
            return _fail(
                "rs: --layout is encode-only (decode reads it from .METADATA)"
            )
        if layout != "interleaved":
            return _fail(
                f"rs: --layout must be row or interleaved, got {layout}"
            )
        if n_devices:
            return _fail("rs: --layout interleaved is single-host")
    if auto and op != "decode":
        return _fail("rs: --auto is decode-only")
    if auto and conf_file:
        return _fail("rs: -c and --auto conflict; pick one survivor source")
    if locate:
        if op != "decode":
            return _fail("rs: --locate is decode-only")
        if conf_file:
            return _fail(
                "rs: -c and --locate conflict (locate reads every present "
                "chunk, no conf needed)"
            )
        if auto:
            return _fail(
                "rs: --auto and --locate conflict; --auto already "
                "escalates to locate (RS_LOCATE tunes it)"
            )
        if n_devices:
            return _fail("rs: --locate is single-host; --devices does not apply")
        if extra:
            return _fail("rs: --locate decodes one archive at a time")
    if syndrome and not scrub:
        return _fail("rs: --syndrome only applies to --scrub")
    if stripe > 1 and not n_devices:
        return _fail("rs: --stripe requires --devices")
    if extra and op in ("encode", "decode"):
        # Batch encode/decode stream through the single-host fleet lane.
        if n_devices:
            return _fail(f"rs: batch {op} does not take --devices")
        if op == "decode" and out_file:
            return _fail(
                "rs: batch --auto decode does not take -o "
                "(outputs are written in place, one per archive)"
            )

    if n_devices and (metrics_json or trace_path):
        # Multi-process jobs (JAX_NUM_PROCESSES workers running this same
        # CLI with --devices): each process dumps its own telemetry part —
        # {path}.p{i}, merged by obs/aggregate.py — resolved from the env
        # HERE so the writability probe below exercises the real part
        # path.  Gated on --devices: only that flag makes this run join
        # the distributed job, so a stale JAX_NUM_PROCESSES in the shell
        # must not redirect a single-process run's dump.  (The
        # RS_DISTRIBUTED=auto detection path cannot know its index before
        # the slow jax init; explicit-env jobs, the tested surface, can.)
        from .obs.aggregate import part_path

        try:
            nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
            pidx = int(os.environ.get("JAX_PROCESS_ID", "0"))
        except ValueError:
            nproc, pidx = 1, 0
        if metrics_json:
            metrics_json = part_path(metrics_json, pidx, nproc)
        if trace_path:
            trace_path = part_path(trace_path, pidx, nproc)
        elif os.environ.get("RS_TRACE") and nproc > 1:
            # The env spelling must suffix like the flag: otherwise every
            # process of the job exports through the SAME file (and the
            # same .rs_tmp), last-writer-wins clobbering the others.
            trace_path = part_path(os.environ["RS_TRACE"], pidx, nproc)

    if metrics_json:
        # Fail fast on an unwritable snapshot path — AFTER every pure
        # usage validation above (no probe file on a usage error), BEFORE
        # the slow jax import / mesh init below and long before the run
        # whose metrics the user would otherwise lose.  A newly created
        # (empty) probe file gets a "{}" placeholder so every later exit
        # — even an uncaught mesh-init crash before the try/finally —
        # leaves valid JSON, never a zero-byte file; dump_metrics()
        # overwrites it with the real snapshot.
        try:
            with open(metrics_json, "a") as fp:
                if fp.tell() == 0:
                    fp.write("{}\n")
        except OSError as e:
            return _fail(f"rs: cannot write --metrics-json path: {e}")

    # Import lazily: jax init is slow and -h must be instant.
    from . import api

    kwargs = dict(strategy=strategy, pipeline_depth=max(1, pipeline_depth))
    if n_devices:
        from .parallel import distributed
        from .parallel.mesh import make_mesh

        # Env-driven no-op single-process; under JAX_COORDINATOR_ADDRESS /
        # JAX_NUM_PROCESSES / JAX_PROCESS_ID it joins the multi-host job so
        # --devices can span processes (the file ops become collectives).
        distributed.initialize()
        kwargs["mesh"] = make_mesh(n_devices, stripe=stripe)
        kwargs["stripe_sharded"] = stripe > 1
    if segment_bytes:
        kwargs["segment_bytes"] = segment_bytes
    elif tile_hint:
        # -p caps the per-dispatch column extent, the closest analog of the
        # reference's gridDim.x cap (encode.cu:348-355).
        kwargs["segment_bytes"] = max(1, tile_hint) * 128 * 1024

    if metrics_json:
        # Collection must be on DURING the run; --metrics-json implies it
        # (the in-process equivalent of exporting RS_METRICS=1).
        from .obs import metrics as obs_metrics

        obs_metrics.force_enable()
    if trace_path:
        kwargs["trace_path"] = trace_path  # == RS_TRACE=PATH for this op

    def dump_metrics() -> None:
        # Called on success AND failure: the snapshot is most valuable
        # when a long run died near the end, and a zero-byte probe file
        # left behind would crash downstream json.load's.
        if not metrics_json:
            return
        import json

        from .obs import metrics as obs_metrics

        try:
            with open(metrics_json, "w") as fp:
                json.dump(obs_metrics.unified_snapshot(), fp)
                fp.write("\n")
        except OSError as e:  # writability probed up front; disk-full etc.
            print(f"rs: metrics snapshot write failed: {e}", file=sys.stderr)

    # Live exposition for the run's duration: RS_METRICS_PORT starts the
    # /metrics endpoint (obs/serve.py) on a daemon thread so a scraper can
    # watch a long fleet job between launch and final snapshot.
    from .obs import serve as obs_serve

    obs_serve.maybe_start_from_env()

    timer = PhaseTimer(enabled=True)
    if profile_dir:
        # Deprecated alias for RS_PROFILE=<dir>: the capture itself now
        # lives in api._observed_file_op (so scrub/fleet/chaos paths and
        # library callers profile too); the flag just latches the same
        # override for this run, cleared in the finally below so later
        # in-process main() calls (tests, embedders) don't inherit it.
        api.profile_dir_override(profile_dir)
    fault_ctx = None
    if fault_plan is not None:
        from .resilience import faults as _res_faults

        fault_ctx = _res_faults.activate(fault_plan)
        fault_ctx.__enter__()
    try:
        if op == "encode":
            if native_num <= 0 or total_num <= 0 or not in_file:
                return _fail("rs: encoding requires -k, -n and -e")
            if total_num <= native_num:
                return _fail(f"rs: need n > k (got n={total_num}, k={native_num})")
            if extra:
                # Batch encode: -e <first> plus positional files, one
                # shared write-behind lane (--devices rejected above, so
                # kwargs carries no mesh here).
                fleet = [in_file] + list(extra)
                api.encode_fleet(
                    fleet,
                    native_num,
                    total_num - native_num,
                    generator=generator,
                    checksums=checksum,
                    w=width,
                    layout=layout,
                    timer=timer,
                    **kwargs,
                )
                nbytes = sum(os.path.getsize(f) for f in fleet)
            else:
                api.encode_file(
                    in_file,
                    native_num,
                    total_num - native_num,
                    generator=generator,
                    checksums=checksum,
                    w=width,
                    layout=layout,
                    timer=timer,
                    **kwargs,
                )
                nbytes = os.path.getsize(in_file)
        elif op == "scrub":
            import json

            report = api.scan_file(
                in_file,
                syndrome=syndrome,
                **(
                    {"segment_bytes": kwargs["segment_bytes"]}
                    if "segment_bytes" in kwargs
                    else {}
                ),
                # Scrub rides the same observability surfaces as the data
                # ops: --trace exports the scan spans, and the snapshot
                # dump in the finally below carries the scrub counters.
                **({"trace_path": trace_path} if trace_path else {}),
            )
            print(json.dumps(report))
            # "unknown" (subset search capped) is not proven healthy -> 1.
            return 0 if report["decodable"] is True else 1
        elif op == "repair":
            if extra:
                # Fleet mode: -i <first> plus positional archives (the
                # --devices combination was rejected at validation, so
                # kwargs carries no mesh here).
                fleet = [in_file] + list(extra)
                results = api.repair_fleet(fleet, timer=timer, **kwargs)
                for f in fleet:
                    reb = results[f]
                    print(f"{f}: rebuilt {reb}" if reb else f"{f}: healthy")
                nbytes = sum(
                    os.path.getsize(f) for f in fleet if os.path.exists(f)
                )
            else:
                rebuilt = api.repair_file(in_file, timer=timer, **kwargs)
                print(
                    f"rebuilt chunks: {rebuilt}"
                    if rebuilt else "archive healthy"
                )
                nbytes = (
                    os.path.getsize(in_file) if os.path.exists(in_file) else 0
                )
        else:
            if not in_file or (not conf_file and not auto and not locate):
                return _fail(
                    "rs: decoding requires -i and -c (or --auto/--locate)"
                )
            if locate:
                out = api.locate_decode_file(
                    in_file, out_file, timer=timer,
                    **{key: kwargs[key] for key in
                       ("strategy", "pipeline_depth", "segment_bytes")
                       if key in kwargs},
                )
                nbytes = os.path.getsize(out)
            elif auto and extra:
                # Batch decode: -i <first> plus positional archives, one
                # shared write-behind lane (--devices/-o rejected above).
                fleet = [in_file] + list(extra)
                results = api.decode_fleet(
                    fleet,
                    verify_checksums=False if no_verify else None,
                    timer=timer, **kwargs,
                )
                for f in fleet:
                    print(f"{f}: decoded -> {results[f]}")
                nbytes = sum(os.path.getsize(results[f]) for f in fleet)
            elif auto:
                out = api.auto_decode_file(
                    in_file, out_file,
                    verify_checksums=False if no_verify else None,
                    timer=timer, **kwargs,
                )
            else:
                out = api.decode_file(
                    in_file, conf_file, out_file,
                    verify_checksums=False if no_verify else None,
                    timer=timer, **kwargs,
                )
            if not (auto and extra):
                nbytes = os.path.getsize(out)
    except (ValueError, FileNotFoundError, OSError) as e:
        print(f"rs: error: {e}", file=sys.stderr)
        return 1
    finally:
        if fault_ctx is not None:
            fault_ctx.__exit__(None, None, None)
        if profile_dir:
            api.profile_dir_override(None)
        # In the finally: the snapshot must land on EVERY exit from the
        # run — success, handled error, unhandled exception (device
        # runtime errors, KeyboardInterrupt on a long encode) or a
        # post-probe validation _fail — never leaving the zero-byte
        # writability-probe file behind.
        dump_metrics()

    if not quiet:
        print(f"== {op} {in_file} ==")
        print(timer.summary(data_bytes=nbytes))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
