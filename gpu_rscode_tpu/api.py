"""File-level encode/decode — the L4 orchestration layer.

Capability parity with the reference's ``encode_file`` (encode.cu:300-473)
and ``decode_file`` (decode.cu:235-434), redesigned for a TPU host runtime:

* The file is striped into k contiguous ranges (``chunk_size =
  ceil(total/k)``, same layout as encode.cu:317,332-346) and processed in
  column *segments* so arbitrarily large files stream through bounded
  memory.  Segments are dispatched asynchronously: JAX's async dispatch
  overlaps the host striping/IO of segment i+1 with device compute of
  segment i — the TPU-native analog of the reference's CUDA-stream
  depth-first pipeline (encode.cu:165-218).  ``pipeline_depth`` caps how many
  segments may be in flight (the ``-s`` stream-count knob).
* Tail padding is explicit zeros (deterministic parity — fixes the
  reference's uninitialised-heap padding divergence, encode.cu:325-330).
* Natives are written straight from the source file; only parity rides the
  device (the reference writes natives from the original buffer too).
* Decode trusts the .METADATA matrix (decode.cu:272-282), inverts the
  survivor submatrix on host, and streams the recovery GEMM the same way.
"""

from __future__ import annotations

import functools
import inspect
import os
import threading
import time

import numpy as np

from collections import OrderedDict
from contextlib import contextmanager, nullcontext

from .codec import RSCodec
from .obs import attrib as _obs_attrib, health as _obs_health, \
    metrics as _obs_metrics, runlog as _obs_runlog, tracing as _obs_tracing
from .parallel.io_executor import DrainExecutor, FleetPipeline
from .parallel.pipeline import AsyncWindow, DeviceStagingRing, SegmentPrefetcher
from .resilience import faults as _faults, retry as _retry
from .utils.fileformat import (
    append_checksums,
    chunk_crc32,
    chunk_file_name,
    chunk_size_for,
    chunk_size_for_layout,
    crc32_of,
    metadata_file_name,
    parse_chunk_index,
    read_archive_meta,
    read_conf,
    rewrite_checksums,
    write_conf,
    write_metadata,
)
from .utils.timing import PhaseTimer


class UndecidedSubsetError(ValueError):
    """The decodable-subset search hit its candidate cap without finding an
    invertible k-subset.  Distinct from exhaustion: more combinations exist,
    so the archive is NOT proven unrecoverable (scan_file reports this as
    ``decodable: "unknown"`` rather than false)."""


class ChunkIntegrityError(ValueError):
    """A surviving chunk's bytes are unusable — CRC mismatch, truncated or
    vanished after the scan that selected it (the TOCTOU window), or
    unreadable after retries.

    ``bad_chunks`` maps chunk index -> file path, so callers can build a new
    conf from different survivors (the checksum extension turns silent
    corruption into a recoverable erasure); :func:`auto_decode_file` uses it
    to exclude the named chunks and reselect automatically.
    """

    def __init__(self, bad_chunks: dict[int, str],
                 reason: str = "chunk checksum mismatch (corrupt survivors)"):
        self.bad_chunks = dict(bad_chunks)
        names = ", ".join(f"{i}:{p}" for i, p in sorted(bad_chunks.items()))
        super().__init__(
            f"{reason}: {names}; "
            "pick different survivors in the conf file"
        )


# Default segment sizing: bound host+device working set to ~64 MiB of natives
# per in-flight segment (k rows x seg_cols bytes).
DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024


# -- deep-profiling hook (RS_PROFILE) ----------------------------------------
#
# jax.profiler capture used to be a CLI-only wrapper around encode/decode
# (cli.py's --profile-dir); lifting it here puts EVERY file-level entry
# point — scrub, repair, fleet, chaos recovery loops, library callers —
# under the same deep-profiling surface.  RS_PROFILE=<dir> (or the CLI
# flag, now an alias that latches profile_dir_override) wraps the
# OUTERMOST observed operation in jax.profiler.trace(dir); nested entry
# points (auto_decode -> decode, fleet -> repair) join the active capture
# instead of re-entering the profiler.

_PROFILE_LOCK = threading.Lock()
_PROFILE_ACTIVE = False
_PROFILE_DIR_OVERRIDE: str | None = None


def profile_dir_override(profile_dir: str | None) -> None:
    """Latch a capture directory for this process regardless of
    ``RS_PROFILE`` — the in-process equivalent of exporting the env var
    (the CLI's deprecated ``--profile-dir`` alias routes through this
    instead of wrapping the operation itself).  Pass None to clear."""
    global _PROFILE_DIR_OVERRIDE
    _PROFILE_DIR_OVERRIDE = profile_dir


@contextmanager
def _profile_session():
    """jax.profiler capture for one outermost file operation (no-op when
    RS_PROFILE is unset and no override is latched; nested operations
    record into the outer capture)."""
    profile_dir = _PROFILE_DIR_OVERRIDE or os.environ.get("RS_PROFILE")
    if not profile_dir:
        yield
        return
    global _PROFILE_ACTIVE
    with _PROFILE_LOCK:
        owner = not _PROFILE_ACTIVE
        if owner:
            _PROFILE_ACTIVE = True
    if not owner:
        yield
        return
    try:
        import jax

        with jax.profiler.trace(profile_dir):
            yield
    finally:
        with _PROFILE_LOCK:
            _PROFILE_ACTIVE = False


def _observed_file_op(op: str):
    """Wrap a file-level entry point with the unified observability surface
    (docs/OBSERVABILITY.md): every wrapped function accepts an extra
    keyword-only ``trace_path=`` argument that — like the ``RS_TRACE`` env
    var — activates a span-tracing session exported as Chrome-trace /
    Perfetto JSON on completion, records a top-level span, and counts the
    operation in ``rs_file_ops_total`` (RS_METRICS).  Sessions are
    reentrant, so nested entry points (auto_decode -> decode, fleet ->
    repair) record into ONE trace owned by the outermost call.

    With ``RS_RUNLOG`` set, every wrapped call — success OR failure —
    also appends one structured record to the persistent run ledger
    (obs/runlog.py): op, config {k,n,w,strategy}, input bytes, wall,
    the PhaseTimer phase decomposition and the outcome.  Nested entry
    points each get their own record (a fleet repair's per-archive
    zero-size fallthroughs are real operations too)."""

    def deco(fn):
        sig = inspect.signature(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            trace_path = kwargs.pop("trace_path", None)
            # Rearm the retry budget per file-level entry: it bounds the
            # retry storm of ONE operation; without this a long-lived
            # process would silently lose all transient-retry protection
            # once the cumulative budget drained (docs/RESILIENCE.md).
            _retry.reset_budget()
            t0 = time.perf_counter()
            # Entry snapshot of a caller-supplied timer: nested fleet ops
            # share one, and the record must carry THIS op's delta, not
            # the fleet's running totals.
            phases0 = (
                _obs_runlog.timer_phases(sig, args, kwargs)
                if _obs_runlog.enabled() else None
            )
            error: BaseException | None = None
            try:
                with _profile_session(), _obs_tracing.session(trace_path):
                    with _obs_tracing.span(op, lane="op"):
                        out = fn(*args, **kwargs)
            except BaseException as e:
                error = e
                raise
            finally:
                # Failure records matter MOST (the regression watch and
                # the error-rate trend both read them); recording itself
                # never raises into the operation.
                if _obs_runlog.enabled():
                    _obs_runlog.record_file_op(
                        op, sig, args, kwargs,
                        wall=time.perf_counter() - t0, error=error,
                        phases_before=phases0,
                    )
            _obs_metrics.counter(
                "rs_file_ops_total", "file-level operations completed"
            ).labels(op=op).inc()
            # Tail latency of the whole operation (p50/p99 next to the
            # mean the ledger already trends) — successes only; failures
            # are counted by outcome in the ledger, and mixing their
            # walls into the latency series would skew the percentiles.
            _obs_metrics.quantile(
                "rs_file_op_wall_seconds",
                "file-level operation wall seconds (streaming quantiles)",
            ).labels(op=op).observe(time.perf_counter() - t0)
            return out

        return wrapper

    return deco

# Fleet repair routes batched survivor inversions to the device on TPU
# backends per the measured k x batch grid
# (bench_captures/inverse_nopivot_tpu_20260801T001751Z.jsonl, real v5e):
# the device wins at (k=10, b=1024: 3.46x), (k=32, b>=256: 2.1-5.6x) and
# (k=64, b>=64: 1.10-1.25x — a thin but consistent margin across three
# batch sizes); it loses at every k=128 cell (0.54-0.90x) and at small
# batches for every k (the ~0.13-0.15 s flat dispatch floor is the tunnel
# round trip — a colocated host would cross over earlier).  That capture
# also REFUTES the r4 hypothesis that the per-step pivot scan caused the
# k=128 loss: the scan-free no-pivot elimination times are identical to
# the pivoting ones on TPU (the lax.scan over k elimination steps itself
# is the cost), so depth stays host-routed.  CPU backends keep the
# ungated device dispatch (14-136x at every measured point,
# inverse_cpu_20260730T174508Z.jsonl).
def _device_invert_min_batch_tpu(k: int) -> int | None:
    """Group-size threshold for the batched device inverter on TPU.

    At the measured depths (k = 10/32/64/128) the value is the smallest
    batch where the device dispatch beat the per-archive host loop (None
    where the host won every cell); unmeasured intermediate depths take
    the STRICTER neighbouring threshold — e.g. k=20 requires 1024, not
    k=32's 256, because (k=10, b=256) measured a 0.81x LOSS."""
    if k > 64:
        return None
    if k == 64:
        return 64
    if k >= 32:
        return 256
    return 1024


def _segment_cols(chunk_size: int, native_num: int, segment_bytes: int) -> int:
    cols = max(1, segment_bytes // max(1, native_num))
    # Lane-align segments (TPU tiles are 128 wide) except when the chunk
    # itself is smaller.
    if cols < chunk_size:
        cols = max(128, cols - cols % 128)
    return min(cols, chunk_size)


def _staging_ring(
    prefetch, codec, seg_cols: int, sym: int, depth: int, out_rows=None
):
    """The H2D stage all three file loops (encode/decode/repair) share:
    bucket-pad each prefetched segment and issue its async device_put
    (``codec.stage_segment``), ``depth`` segments ahead of the consumer.
    ``out_rows`` is the loop's dispatch output row count (lets the stage
    skip the donation-recovery host copy when the output can't alias)."""
    return DeviceStagingRing(
        prefetch,
        lambda tag, seg: codec.stage_segment(
            seg, cap=seg_cols // sym, sym=sym, out_rows=out_rows
        ),
        depth=depth,
    )


def warm_plan(
    native_num: int,
    parity_num: int,
    *,
    w: int = 8,
    generator: str = "vandermonde",
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    file_bytes: int | None = None,
) -> dict:
    """Pre-compile the encode executable for one plan-cache shape bucket.

    The residency hook (docs/SERVE.md): a resident process — the serve
    daemon at startup (``rs serve --warm k,n``), a long-lived embedder —
    pays the AOT compile HERE instead of inside its first real request.
    Stages one zero segment of the bucket the workload will hit
    (``file_bytes`` sizes it like :func:`encode_file` would; default one
    full segment) and blocks until the dispatch lands in the shared plan
    cache, where every later :func:`encode_file`/:func:`encode_fleet`
    with the same ``(k, p, w, strategy)`` shape finds it warm.  Returns
    the resolved shape (strategy ``auto`` pinned to its backend choice).
    """
    if w not in (8, 16):
        raise ValueError(f"file-layer symbol width must be 8 or 16, got {w}")
    sym = w // 8
    codec = RSCodec(
        native_num, parity_num, w=w, generator=generator, strategy=strategy
    )
    chunk = (
        chunk_size_for(file_bytes, native_num, sym)
        if file_bytes else max(sym, segment_bytes)
    )
    seg_cols = _segment_cols(chunk, native_num, segment_bytes)
    seg = np.zeros((native_num, seg_cols), dtype=np.uint8)
    staged = codec.stage_segment(
        seg, cap=seg_cols // sym, sym=sym,
        out_rows=codec.parity_block.shape[0],
    )
    np.asarray(codec.encode(staged))  # block: the compile is now cached
    return {
        "k": native_num, "p": parity_num, "w": w,
        "strategy": codec.strategy, "generator": generator,
        "cols": seg_cols,
    }


@contextmanager
def _fleet_lane():
    """The fleet scaffold every multi-file entry point shares: one ordered
    write-behind lane, archives committed behind their own writes, and —
    the ordering-sensitive part — ``abort`` (running the still-registered
    cleanups) only AFTER the executor context exited with its workers
    joined, so no in-flight drain races a cleanup's closes/unlinks."""
    pipe = FleetPipeline(DrainExecutor(ordered=True))
    try:
        with pipe.executor:
            yield pipe
    except BaseException:
        pipe.abort()
        raise


def _drain_ctx(fleet: FleetPipeline | None, *, ordered: bool = True):
    """Write-behind executor for one dispatch loop (the 5th pipeline stage:
    write ∥ dispatch — see parallel/io_executor.py and docs/IO.md).

    Inside a fleet operation the loop rides the fleet's shared ordered
    lane (not closed per file — archive j+1's dispatches overlap archive
    j's drain); standalone it owns a fresh ``DrainExecutor`` whose context
    exit is the write barrier, placed inside the caller's ``with`` block so
    every write lands before files are closed or promoted.  ``ordered``
    must stay True for drains with cross-segment state (incremental CRC,
    shared-``fp`` streaming writes); the offset-addressed ``os.pwrite``
    collectives pass False to let ``RS_IO_WRITERS`` workers race.
    """
    if fleet is not None:
        return nullcontext(fleet.executor)
    return DrainExecutor(ordered=ordered)


@contextmanager
def _dispatch_span(op: str, off: int, cols: int):
    """Per-segment dispatch span (one per dispatched segment, with its
    column range in args — the trace's unit of accountability).  Also
    feeds the dispatch tail-latency quantiles (`rs analyze` reads the
    p50/p99 split to tell dispatch-bound strategies from memory-bound
    ones) and samples device memory at the segment boundary — this is
    the ONE per-segment sampling site (all six dispatch loops, mesh
    included, pass through here)."""
    t0 = time.perf_counter()
    with _obs_tracing.span(
        "dispatch", lane="dispatch", op=op, off=int(off), cols=int(cols)
    ):
        yield
    _obs_metrics.quantile(
        "rs_dispatch_wall_seconds",
        "per-segment dispatch wall seconds (streaming quantiles)",
    ).labels(op=op).observe(time.perf_counter() - t0)
    _obs_attrib.sample_device_memory()


def _write_deinterleaved_block(
    out_fp, off: int, cols: int, blk: np.ndarray, sym: int, total_size: int
) -> None:
    """Interleaved-layout output write shared by decode_file and
    locate_decode_file (docs/UPDATE.md): the chunk-byte window
    [off, off+cols) of the k rows holds the CONTIGUOUS file range
    [off*k, (off+cols)*k) — one de-interleave and one write per segment
    instead of k scattered row writes, clamped to the real file size."""
    from .update.layout import deinterleave

    k = blk.shape[0]
    lo = off * k
    if lo >= total_size:
        return
    hi = min(lo + cols * k, total_size)
    out_fp.seek(lo)
    out_fp.write(deinterleave(blk, sym)[: hi - lo].tobytes())
    _obs_metrics.counter(
        "rs_io_write_bytes_total",
        "bytes write by the staging-I/O layer",
    ).labels(call="stream_write").inc(hi - lo)


def _segment_spans(chunk_size: int, seg_cols: int) -> list[tuple[int, int]]:
    """(off, cols) spans covering [0, chunk_size) in seg_cols steps."""
    spans = []
    off = 0
    while off < chunk_size:
        cols = min(seg_cols, chunk_size - off)
        spans.append((off, cols))
        off += cols
    return spans


def _check_gfwidth(w: int, meta_path: str) -> None:
    """Reject metadata symbol widths this build does not code for (every
    entry point that reads .METADATA validates before using ``w``)."""
    if w not in (8, 16):
        raise ValueError(
            f"unsupported gfwidth {w} in {meta_path!r} "
            "(this build handles w=8 and w=16 files)"
        )


def _mesh_processes(mesh) -> list[int]:
    """Sorted process indices a mesh's devices span ([] for mesh=None)."""
    if mesh is None:
        return []
    return sorted({d.process_index for d in mesh.devices.flat})


def _open_chunk(
    path: str, chunk: int, index: int | None = None, scope: str = "read"
) -> np.ndarray:
    """Read-only byte view of a chunk file, validated against the expected
    size.  Zero-size archives (chunk == 0, foreign reference encodes of an
    empty file) get an empty array — np.memmap refuses zero-byte files.

    This is a resilience boundary (docs/RESILIENCE.md): the fault plane's
    read hook fires here (``scope`` distinguishes decode reads from scrub
    CRC reads), transient open failures retry under the default policy,
    and — the TOCTOU fix — a chunk that passed the archive scan but shrank
    before this open raises :class:`ChunkIntegrityError` naming ``index``
    (when the caller supplies it) so :func:`auto_decode_file` can exclude
    it and reselect survivors instead of dying on a raw ValueError."""

    def attempt() -> np.ndarray:
        _faults.on_read(path, index=index, scope=scope)
        mm = (
            np.zeros(0, dtype=np.uint8)
            if chunk == 0
            else np.memmap(path, dtype=np.uint8, mode="r")
        )
        if mm.shape[0] < chunk:
            if index is not None:
                raise ChunkIntegrityError(
                    {index: path},
                    reason=f"chunk truncated after scan "
                    f"({mm.shape[0]} of {chunk} bytes)",
                )
            raise ValueError(
                f"chunk {path!r} is {mm.shape[0]} bytes, expected {chunk}"
            )
        return _faults.corrupt(path, index, mm, scope=scope)

    return _retry.default_policy().call(attempt, op="chunk_open")


class _ArchiveCommit:
    """The single-host encode paths' ``.rs_tmp`` crash-atomicity scaffold
    (row and interleaved share it): every output — n chunk files AND
    .METADATA — writes to a temp name and the whole set promotes only
    after every byte landed, chunks first and .METADATA last (its
    presence is the marker of a complete encode).  ``discard`` unlinks
    temps and retracts chunks a failing commit loop already promoted —
    unless they pre-existed (re-encode over an archive), whose previous
    bytes are unrecoverable by rename and whose partial new set still
    scans/repairs via the old .METADATA."""

    def __init__(self, file_name: str, n: int):
        self.file_name = file_name
        self.written: list[str] = [
            chunk_file_name(file_name, i) for i in range(n)
        ] + [metadata_file_name(file_name)]
        self.tmps = {name: name + ".rs_tmp" for name in self.written}
        self._preexisting = {
            name for name in self.written if os.path.exists(name)
        }
        self._committed: list[str] = []

    @property
    def meta_tmp(self) -> str:
        return self.tmps[metadata_file_name(self.file_name)]

    def promote(self) -> None:
        for name in self.written[:-1]:
            os.replace(self.tmps[name], name)
            self._committed.append(name)
        os.replace(self.meta_tmp, metadata_file_name(self.file_name))

    def discard(self) -> None:
        for tmp in self.tmps.values():
            if os.path.exists(tmp):
                os.unlink(tmp)
        for name in self._committed:
            if name not in self._preexisting and os.path.exists(name):
                os.unlink(name)


def _write_empty_atomic(out_path: str) -> str:
    """Atomically produce a zero-byte output file (the decode result of a
    totalSize=0 archive) under the same .rs_tmp commit protocol."""
    tmp_path = out_path + ".rs_tmp"
    with open(tmp_path, "wb"):
        pass
    os.replace(tmp_path, out_path)
    return out_path


def _broadcast_lead_verdict(scan_err, procs, what: str) -> None:
    """Lockstep lead-error propagation for collectives whose lead does
    work peers cannot see (archive scan, survivor selection, conf write).

    Broadcasts an ok/error flag from the lead; on error every process
    raises — the lead its original exception, peers a RuntimeError naming
    the lead — instead of the peers wedging at the next barrier until
    coordinator teardown.  Call on ALL processes, before the barrier that
    consumes the lead's work.  (Collectives that already broadcast lead
    state piggyback a sentinel on that array instead — e.g. the -1 health
    state in _repair_file_multiprocess, the CRC bad_mask in
    _decode_file_multiprocess — saving the extra collective.)
    """
    from jax.experimental import multihost_utils

    flag = np.array([1 if scan_err is not None else 0], dtype=np.int32)
    flag = np.asarray(
        multihost_utils.broadcast_one_to_all(flag, is_source=_is_lead(procs))
    )
    if flag[0]:
        if scan_err is not None:
            raise scan_err
        raise RuntimeError(
            f"{what} failed on the lead process (process {procs[0]}); "
            "see its log for the cause"
        )


def _is_lead(procs) -> bool:
    """Whether this process is the collective's lead (True single-process)."""
    if len(procs) <= 1:
        return True
    import jax

    return jax.process_index() == procs[0]


def _write_native_chunks(
    src: np.ndarray,
    file_name: str,
    tmps: dict[str, str],
    k: int,
    chunk: int,
    total_size: int,
    copy_step: int,
    crcs: dict[int, int] | None,
    timer: PhaseTimer,
    executor=None,
) -> None:
    """Write the k native chunk temp files: straight copies of the k file
    ranges, tail zero-padded, in bounded slices (a 100 GB chunk never
    materialises in RAM), with optional incremental CRC32.

    With an ``executor`` (the encode's write-behind lane) each chunk copy
    is queued as a drain task instead of running here: the dispatch thread
    proceeds straight to parity streaming while the natives land on the
    writer lane (tasks touch distinct files and distinct ``crcs`` keys, so
    lane ordering is irrelevant; ``src`` is a read-only view)."""

    def write_one(i: int) -> None:
        with timer.phase("write natives (io)"):
            lo, hi = i * chunk, min((i + 1) * chunk, total_size)
            crc = 0
            with open(tmps[chunk_file_name(file_name, i)], "wb") as fp:
                for s in range(lo, hi, copy_step):
                    buf = src[s : min(s + copy_step, hi)].tobytes()
                    fp.write(buf)
                    if crcs is not None:
                        crc = crc32_of(buf, crc)
                pad = chunk - max(0, hi - lo)
                zeros = b"\x00" * min(pad, copy_step)
                for s in range(0, pad, copy_step):
                    buf = zeros[: min(copy_step, pad - s)]
                    fp.write(buf)
                    if crcs is not None:
                        crc = crc32_of(buf, crc)
            if crcs is not None:
                crcs[i] = crc

    for i in range(k):
        if executor is not None:
            executor.submit(lambda i=i: write_one(i), nbytes=chunk)
        else:
            write_one(i)


@_observed_file_op("encode")
def encode_file(
    file_name: str,
    native_num: int,
    parity_num: int,
    *,
    generator: str = "vandermonde",
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    mesh=None,
    stripe_sharded: bool = False,
    checksums: bool = False,
    w: int = 8,
    layout: str = "row",
    timer: PhaseTimer | None = None,
    _fleet: FleetPipeline | None = None,
) -> list[str]:
    """Encode ``file_name`` into n = k + p chunk files plus .METADATA.

    Returns the list of files written.  ``pipeline_depth`` is the number of
    segments allowed in flight (maps the reference's ``-s`` flag).  With a
    ``mesh``, segments are sharded across devices (see parallel/sharded.py).
    ``checksums=True`` appends per-chunk CRC32 lines to .METADATA (format
    extension; decode verifies them automatically when present).  Off by
    default so the metadata stays byte-identical to the reference's.
    ``w``: symbol width — 8 (reference-compatible) or 16 (wide-symbol
    extension: chunks hold little-endian uint16 symbols, recorded in
    .METADATA as ``# gfwidth 16``; supports up to 65536 total chunks where
    GF(2^8) caps out at 256).

    Observability: like every file-level entry point, accepts a
    keyword-only ``trace_path=`` (or the ``RS_TRACE`` env var) that writes
    a per-segment Chrome-trace/Perfetto JSON timeline, and feeds the
    ``RS_METRICS`` registry — see docs/OBSERVABILITY.md.
    """
    timer = timer or PhaseTimer(enabled=False)
    if w not in (8, 16):
        raise ValueError(f"file-layer symbol width must be 8 or 16, got {w}")
    sym = w // 8
    k, p = native_num, parity_num
    codec = RSCodec(
        k, p, w=w, generator=generator, strategy=strategy,
        mesh=mesh, stripe_sharded=stripe_sharded,
    )
    total_size = os.path.getsize(file_name)
    if total_size == 0:
        raise ValueError(f"refusing to encode empty file {file_name!r}")
    if layout not in ("row", "interleaved"):
        raise ValueError(
            f"unknown chunk layout {layout!r} (want row or interleaved)"
        )
    if layout == "interleaved":
        # The append-mode layout (docs/UPDATE.md): file symbol s lives in
        # row s % k, column s // k, so `rs append` only ever touches the
        # tail column block.  Single-host: the mesh collectives assume
        # the reference's row-contiguous staging.
        if mesh is not None:
            raise ValueError(
                "interleaved layout encodes single-host; drop --devices"
            )
        return _encode_file_interleaved(
            file_name, codec, total_size, segment_bytes=segment_bytes,
            pipeline_depth=pipeline_depth, checksums=checksums,
            timer=timer, _fleet=_fleet,
        )
    chunk = chunk_size_for(total_size, k, sym)
    seg_cols = _segment_cols(chunk, k, segment_bytes)

    if len(_mesh_processes(mesh)) > 1:
        if _fleet is not None:
            raise ValueError(
                "fleet encode is single-host; multi-process encodes are "
                "collectives with their own barriers"
            )
        return _encode_file_multiprocess(
            file_name, codec, chunk, total_size, seg_cols,
            checksums=checksums, pipeline_depth=pipeline_depth, timer=timer,
        )

    src = np.memmap(file_name, dtype=np.uint8, mode="r")

    # Failure atomicity (same contract decode and repair already keep):
    # a mid-encode crash leaves no partial ``_<i>_`` files for scan_file
    # to misread as a damaged archive (_ArchiveCommit).
    commit = _ArchiveCommit(file_name, k + p)
    written, tmps = commit.written, commit.tmps

    # Native chunks: straight copies of the k file ranges, tail zero-padded.
    # Copied in bounded slices so a 100 GB chunk never materialises in RAM.
    copy_step = max(1, segment_bytes)
    crcs: dict[int, int] | None = {} if checksums else None

    def gather_segment(off: int, cols: int) -> np.ndarray:
        """(k, cols) segment of the striped view, zero-padded.  Uses the
        native pread gather when built (one syscall per row instead of
        Python slice copies); NumPy fallback reuses the open memmap.
        Runs on the prefetch worker thread (reads-only: safe).  A
        resilience read boundary: fault hook + transient-retry (the
        gather writes a fresh buffer, so re-running it is exact)."""
        from . import native

        def attempt() -> np.ndarray:
            _faults.on_read(file_name, scope="read")
            return native.stripe_read(
                file_name, chunk, k, off, cols, total_size, fallback_src=src
            )

        with timer.phase("stage segment (io)"):
            return _retry.default_policy().call(attempt, op="encode_stage")

    parity_files: list = []

    def finalize() -> None:
        # The commit tail: runs only after every parity write landed — on
        # the caller thread standalone, on the fleet's writer lane (behind
        # this file's drains) in batch mode.
        for fp in parity_files:
            fp.close()
        with timer.phase("write metadata (io)"):
            write_metadata(
                commit.meta_tmp, total_size, p, k, codec.total_matrix, w=w
            )
            if crcs is not None:
                append_checksums(commit.meta_tmp, crcs)
        commit.promote()

    def cleanup() -> None:
        for fp in parity_files:
            if not fp.closed:
                fp.close()
        commit.discard()

    # In a fleet, cleanup is registered up front and runs via the fleet's
    # abort (after its workers joined) — never inline, where it would race
    # this file's still-queued drains on the shared lane.
    key = _fleet.register(cleanup) if _fleet is not None else None
    try:
        with _drain_ctx(_fleet) as dex:
            # Native chunk copies ride the writer lane too: the dispatch
            # thread proceeds straight to parity streaming while the k
            # straight copies land write-behind (sync with RS_IO_WRITERS=0).
            _write_native_chunks(
                src, file_name, tmps, k, chunk, total_size, copy_step,
                crcs, timer, executor=dex,
            )

            # Parity chunks: stream segments through the device, staging
            # on a worker thread (SegmentPrefetcher) so read IO overlaps
            # the drain's D2H + parity writes — the three-way overlap of
            # the reference's stream loop (encode.cu:165-218).
            for j in range(p):
                parity_files.append(
                    open(tmps[chunk_file_name(file_name, k + j)], "wb")
                )
            with SegmentPrefetcher(
                _segment_spans(chunk, seg_cols), gather_segment,
                depth=pipeline_depth,
            ) as prefetch, AsyncWindow(
                pipeline_depth,
                lambda tag, fut: _drain_parity(
                    (*tag, fut), parity_files, timer, crcs, k
                ),
                executor=dex,
            ) as window:
                # 5-stage pipeline: the prefetcher reads segment i+2, the
                # ring issues segment i+1's H2D (an async device_put of the
                # bucket-padded segment, see plan.py) while segment i
                # computes, and the write-behind executor drains segment
                # i-1's D2H + parity writes off the dispatch thread.
                # Ordered lane: the incremental parity CRC (and the
                # no-toolchain seek/write fallback) need commits in column
                # order.
                staging = _staging_ring(
                    prefetch, codec, seg_cols, sym, pipeline_depth,
                    out_rows=codec.parity_block.shape[0],
                )
                for (off, cols), seg in staging:
                    with timer.phase("encode dispatch"), _dispatch_span(
                        "encode", off, cols
                    ):
                        parity = codec.encode(seg)  # async
                    window.push((off, cols), parity)
        if _fleet is not None:
            _fleet.commit(key, finalize)
        else:
            finalize()
    except BaseException:
        if _fleet is None:
            cleanup()
        raise
    return written


def _drain_parity(entry, parity_files, timer, crcs=None, k=0) -> None:
    from . import native

    off, cols, parity = entry
    with timer.phase("encode compute"):
        parity_np = np.asarray(parity)  # blocks on device + D2H
    if parity_np.dtype != np.uint8:
        parity_np = np.ascontiguousarray(parity_np).view(np.uint8)  # LE symbol bytes
    # Segments drain strictly in column order (AsyncWindow is FIFO), so
    # incremental CRC over each parity row is well-defined.  The CRC
    # advance is computed BEFORE the write but committed only AFTER it
    # lands: the writer lane may retry this whole drain on a transient
    # write error (docs/RESILIENCE.md), and a half-committed accumulator
    # would silently corrupt the checksum lines.
    new_crcs = (
        {
            k + j: crc32_of(parity_np[j], crcs.get(k + j, 0))
            for j in range(parity_np.shape[0])
        }
        if crcs is not None else None
    )
    with timer.phase("write parity (io)"):
        native.scatter_write(parity_files, parity_np, off)
    if new_crcs is not None:
        crcs.update(new_crcs)


def _encode_file_interleaved(
    file_name: str,
    codec: RSCodec,
    total_size: int,
    *,
    segment_bytes: int,
    pipeline_depth: int,
    checksums: bool,
    timer: PhaseTimer,
    _fleet: FleetPipeline | None,
) -> list[str]:
    """Single-host encode under the interleaved chunk layout
    (docs/UPDATE.md): each segment is ONE contiguous read of the source
    file (bytes [off*k, (off+cols)*k)) interleaved into the (k, cols)
    stripe, natives and parity both scatter-written per column window.
    Keeps :func:`encode_file`'s contracts: .rs_tmp atomicity, CRC32
    extension lines, write-behind drain lane, fleet composition."""
    from . import native
    from .update.layout import interleave

    k, p, w = codec.native_num, codec.parity_num, codec.w
    sym = w // 8
    chunk = chunk_size_for_layout(total_size, k, sym, "interleaved")
    seg_cols = _segment_cols(chunk, k, segment_bytes)
    src = np.memmap(file_name, dtype=np.uint8, mode="r")

    commit = _ArchiveCommit(file_name, k + p)
    written, tmps = commit.written, commit.tmps
    crcs: dict[int, int] | None = {} if checksums else None
    files: list = []

    def gather(off: int, cols: int) -> np.ndarray:
        # One contiguous pread range per segment — the layout's staging
        # win (row-major staging needs k scattered range reads).  Same
        # resilience boundary as the row gather: fault hook + retry into
        # a fresh buffer.
        def attempt() -> np.ndarray:
            _faults.on_read(file_name, scope="read")
            lo = off * k
            hi = min(lo + cols * k, total_size)
            buf = np.zeros(cols * k, dtype=np.uint8)
            if lo < hi:
                buf[: hi - lo] = src[lo:hi]
            return interleave(buf, k, sym)

        with timer.phase("stage segment (io)"):
            return _retry.default_policy().call(attempt, op="encode_stage")

    def drain(tag, payload) -> None:
        off, cols = tag
        seg_host, parity = payload
        with timer.phase("encode compute"):
            parity_np = np.asarray(parity)
        if parity_np.dtype != np.uint8:
            parity_np = np.ascontiguousarray(parity_np).view(np.uint8)
        new_crcs = (
            {
                **{i: crc32_of(seg_host[i], crcs.get(i, 0))
                   for i in range(k)},
                **{k + j: crc32_of(parity_np[j], crcs.get(k + j, 0))
                   for j in range(p)},
            }
            if crcs is not None else None
        )
        with timer.phase("write natives (io)"):
            native.scatter_write(files[:k], seg_host, off)
        with timer.phase("write parity (io)"):
            native.scatter_write(files[k:], parity_np, off)
        if new_crcs is not None:
            crcs.update(new_crcs)

    def finalize() -> None:
        for fp in files:
            fp.close()
        with timer.phase("write metadata (io)"):
            write_metadata(
                commit.meta_tmp, total_size, p, k, codec.total_matrix, w=w,
                layout="interleaved",
            )
            if crcs is not None:
                append_checksums(commit.meta_tmp, crcs)
        commit.promote()

    def cleanup() -> None:
        for fp in files:
            if not fp.closed:
                fp.close()
        commit.discard()

    key = _fleet.register(cleanup) if _fleet is not None else None
    try:
        with _drain_ctx(_fleet) as dex:
            for name in written[:-1]:
                files.append(open(tmps[name], "wb"))
            with SegmentPrefetcher(
                _segment_spans(chunk, seg_cols), gather,
                depth=pipeline_depth,
            ) as prefetch, AsyncWindow(
                pipeline_depth, drain, executor=dex
            ) as window:
                for (off, cols), seg in prefetch:
                    with timer.phase("encode dispatch"), _dispatch_span(
                        "encode", off, cols
                    ):
                        staged = codec.stage_segment(
                            seg, cap=seg_cols // sym, sym=sym,
                            out_rows=codec.parity_block.shape[0],
                        )
                        parity = codec.encode(staged)  # async
                    window.push((off, cols), (seg, parity))
        if _fleet is not None:
            _fleet.commit(key, finalize)
        else:
            finalize()
    except BaseException:
        if _fleet is None:
            cleanup()
        raise
    return written


def _encode_file_multiprocess(
    file_name: str,
    codec: RSCodec,
    chunk: int,
    total_size: int,
    seg_cols: int,
    *,
    checksums: bool,
    pipeline_depth: int,
    timer: PhaseTimer,
) -> list[str]:
    """Multi-host file encode over a process-spanning mesh.

    The reference tops out at one machine (pthread-per-GPU, SURVEY §2);
    this is the genuinely-distributed extension: every participating host
    stages only ITS portion of each segment (the byte ranges its mesh
    devices own), the global array is assembled with
    ``make_array_from_process_local_data`` (put_sharded's multi-process
    branch), the sharded GEMM runs collectively, and each host writes only
    its addressable output shards into the shared-filesystem chunk files.
    Requirements: a shared filesystem; w=8 and the w=16 wide-symbol
    extension both work (device columns are whole symbols, so w=16 byte
    offsets are 2x the sharding's symbol spans).

    ``stripe_sharded`` composes with multi-process: the k axis shards
    across the mesh too (each host stages only its stripe rows — the
    wide-stripe DCN layout of BASELINE config 4), the psum rides the
    process boundary, and hosts on stripe row 0 write the (replicated)
    parity output.

    All processes must call encode_file with the same arguments (it is a
    collective).  The lead process (lowest process index in the mesh)
    writes natives and .METADATA and performs the atomic promotion; the
    cross-process barriers are ``sync_global_devices``.
    """
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import native
    from .parallel.mesh import COLS
    from .parallel.sharded import put_sharded, sharded_gf_matmul

    mesh = codec.mesh
    k, p = codec.native_num, codec.parity_num
    sym = codec.w // 8
    stripe_sharded = codec.stripe_sharded

    lead = jax.process_index() == min(
        d.process_index for d in mesh.devices.flat
    )
    cols_size = mesh.shape[COLS]
    # Input sharding: wide-stripe mode also shards the k axis — each host
    # stages only the stripe rows its devices own (its share of the file),
    # the DCN-scale layout BASELINE config 4 describes.  The GEMM's output
    # is replicated along stripe (psum), so only stripe-row-0 hosts write
    # parity (identical replicas elsewhere — writing them would just
    # duplicate shared-FS IO).
    in_sharding, writes_parity = _stripe_io_roles(mesh, stripe_sharded)
    sharding = NamedSharding(mesh, P(None, COLS))

    written: list[str] = [
        chunk_file_name(file_name, i) for i in range(k + p)
    ] + [metadata_file_name(file_name)]
    tmps = {name: name + ".rs_tmp" for name in written}
    parity_names = [chunk_file_name(file_name, k + j) for j in range(p)]

    src = np.memmap(file_name, dtype=np.uint8, mode="r")
    copy_step = max(1, seg_cols * k)
    crcs: dict[int, int] | None = {} if checksums else None
    preexisting = {name for name in written if os.path.exists(name)}
    committed: list[str] = []

    try:
        if lead:
            _write_native_chunks(
                src, file_name, tmps, k, chunk, total_size, copy_step, crcs,
                timer,
            )
            # Pre-size parity temp files so every process can open r+b and
            # pwrite its shard ranges.
            for name in parity_names:
                with open(tmps[name], "wb") as fp:
                    fp.truncate(chunk)
        multihost_utils.sync_global_devices("rs_encode_files_created")

        def stage(off: int, cols: int):
            # Padded global width in SYMBOLS (equal per-device shards for
            # make_array_from_process_local_data); parity of the zero pad is
            # zero and is trimmed at write time.
            cols_s = cols // sym
            W = ((cols_s + cols_size - 1) // cols_size) * cols_size
            if not stripe_sharded:
                lo, hi = _local_col_span(sharding, k, W)
                with timer.phase("stage segment (io)"):
                    seg = native.stripe_read(
                        file_name, chunk, k, off + lo * sym, (hi - lo) * sym,
                        total_size, fallback_src=src,
                    )
                    return seg.view(np.uint16) if sym == 2 else seg
            # Wide stripe: this host stages only its (stripe rows x column
            # span) block — its own share of the file's byte ranges.
            r0, r1, lo, hi = _local_block(in_sharding, (k, W))
            with timer.phase("stage segment (io)"):
                seg = np.zeros((r1 - r0, (hi - lo) * sym), dtype=np.uint8)
                for i in range(r0, r1):
                    b0 = i * chunk + off + lo * sym
                    b1 = min(
                        b0 + (hi - lo) * sym, (i + 1) * chunk, total_size
                    )
                    n = max(0, b1 - b0)
                    if n:
                        seg[i - r0, :n] = src[b0 : b0 + n]
                return seg.view(np.uint16) if sym == 2 else seg

        parity_fps = [open(tmps[name], "r+b") for name in parity_names]
        try:

            def drain(tag, parity_sharded) -> None:
                off, cols = tag
                if not writes_parity:
                    # Replica holder (stripe rows >= 1): row 0 writes the
                    # identical bytes.  Block for window backpressure only
                    # — no device-to-host copy of parity this host drops.
                    with timer.phase("encode compute"):
                        jax.block_until_ready(parity_sharded)
                    return
                with timer.phase("encode compute"):
                    shards = _trimmed_shards(parity_sharded, cols, sym)
                with timer.phase("write parity (io)"):
                    for col0, data in shards:
                        for j in range(p):
                            os.pwrite(
                                parity_fps[j].fileno(),
                                data[j].tobytes(),
                                off + col0,
                            )

            # Out-of-order write-behind: every drain is an os.pwrite at its
            # own offset into pre-sized temps (no cross-segment state), so
            # RS_IO_WRITERS workers may race freely.
            with SegmentPrefetcher(
                _segment_spans(chunk, seg_cols), stage, depth=pipeline_depth
            ) as prefetch, _drain_ctx(None, ordered=False) as dex, AsyncWindow(
                pipeline_depth, drain, executor=dex
            ) as window:
                for (off, cols), local_seg in prefetch:
                    with timer.phase("encode dispatch"), _dispatch_span(
                        "encode", off, cols
                    ):
                        Bd = put_sharded(local_seg, mesh, stripe_sharded)
                        parity = sharded_gf_matmul(
                            np.asarray(codec.parity_block), Bd,
                            mesh=mesh, w=codec.w, strategy=codec.strategy,
                            stripe_sharded=stripe_sharded,
                        )
                    window.push((off, cols), parity)
        finally:
            for fp in parity_fps:
                fp.close()
        multihost_utils.sync_global_devices("rs_encode_parity_written")

        if lead:
            if crcs is not None:
                # Parity rows were written by many hosts; the lead reads the
                # finished temp files back for the checksum lines.
                with timer.phase("write metadata (io)"):
                    for j, name in enumerate(parity_names):
                        mm = np.memmap(tmps[name], dtype=np.uint8, mode="r")
                        crcs[k + j] = chunk_crc32(mm, chunk, copy_step)
            meta_tmp = tmps[metadata_file_name(file_name)]
            with timer.phase("write metadata (io)"):
                write_metadata(
                    meta_tmp, total_size, p, k, codec.total_matrix, w=codec.w
                )
                if crcs is not None:
                    append_checksums(meta_tmp, crcs)
            for name in written[:-1]:
                os.replace(tmps[name], name)
                committed.append(name)
            os.replace(meta_tmp, metadata_file_name(file_name))
    except BaseException:
        # Same atomicity contract as the single-process path, applied to
        # the SHARED filesystem: unlink every temp (any process can — the
        # paths are common, and losing the unlink race to a peer cleaning
        # the same path is fine), and retract chunks this encode promoted
        # that did not pre-exist.  A process that fails before a barrier
        # leaves its peers blocked in sync_global_devices until the jax
        # coordinator tears the job down — the shared-FS state is clean
        # either way.
        _unlink_shared_tmps(tmps.values())
        _unlink_shared_tmps(
            name for name in committed if name not in preexisting
        )
        raise
    multihost_utils.sync_global_devices("rs_encode_promoted")
    return written


@_observed_file_op("encode_fleet")
def encode_fleet(
    files,
    native_num: int,
    parity_num: int,
    *,
    generator: str = "vandermonde",
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    checksums: bool = False,
    w: int = 8,
    layout: str = "row",
    timer: PhaseTimer | None = None,
) -> dict[str, list[str]]:
    """Encode many files back to back through one shared write-behind lane.

    The fleet-level pipeline overlap: file j+1's native-chunk copies,
    stripe reads and GEMM dispatches stream on this thread while file j's
    parity D2H + writes drain on the shared writer lane, with each file's
    metadata write and atomic promote committed behind its own writes.
    The shared plan cache makes the interleave compile-free after the
    first file (identical (k, p, w, strategy) plans).  Single-host by
    construction (multi-process encodes are collectives — no ``mesh``).

    All-or-nothing per *file* (each keeps :func:`encode_file`'s atomicity
    contract), fail-fast across the fleet: the first failing file raises,
    later files are not attempted, and every uncommitted file's temps are
    cleaned up.  Returns ``{file: [paths written]}``.
    """
    timer = timer or PhaseTimer(enabled=False)
    files = list(files)
    results: dict[str, list[str]] = {}
    with _fleet_lane() as pipe:
        for f in files:
            results[f] = encode_file(
                f, native_num, parity_num,
                generator=generator, strategy=strategy,
                segment_bytes=segment_bytes,
                pipeline_depth=pipeline_depth,
                checksums=checksums, w=w, layout=layout,
                timer=timer, _fleet=pipe,
            )
    return results


@_observed_file_op("decode_fleet")
def decode_fleet(
    files,
    outputs: dict[str, str] | None = None,
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    verify_checksums: bool | None = None,
    timer: PhaseTimer | None = None,
) -> dict[str, str]:
    """Auto-decode many archives through one shared write-behind lane.

    Batch counterpart of :func:`auto_decode_file` (survivor discovery per
    archive, CRC-verified subset selection, conf written next to each
    archive), with the fleet-level overlap of :func:`encode_fleet`:
    archive j+1's scan + survivor reads + recovery dispatches run while
    archive j's output writes drain, and each archive's truncate + atomic
    rename commits behind its own writes.  ``outputs`` optionally maps
    ``in_file`` to an output path (default: in place, like decode).

    Fail-fast: the first unrecoverable or failing archive raises; outputs
    already committed stay, uncommitted temps are cleaned up.  Returns
    ``{file: output path}``.
    """
    timer = timer or PhaseTimer(enabled=False)
    files = list(files)
    outputs = outputs or {}
    results: dict[str, str] = {}
    with _fleet_lane() as pipe:
        for f in files:
            results[f] = auto_decode_file(
                f, outputs.get(f),
                strategy=strategy, segment_bytes=segment_bytes,
                pipeline_depth=pipeline_depth,
                verify_checksums=verify_checksums,
                timer=timer, _fleet=pipe,
            )
    return results


@_observed_file_op("decode")
def decode_file(
    in_file: str,
    conf_file: str,
    output: str | None = None,
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    mesh=None,
    stripe_sharded: bool = False,
    verify_checksums: bool | None = None,
    timer: PhaseTimer | None = None,
    _fleet: FleetPipeline | None = None,
    _fallback_rows: list[int] | None = None,
) -> str:
    """Rebuild ``in_file`` from the k surviving chunks listed in
    ``conf_file``.  Returns the output path (defaults to ``in_file``,
    mirroring the reference's overwrite-input default, decode.cu:410-427).

    ``verify_checksums``: None (default) verifies survivors against the
    CRC32 extension lines when .METADATA carries them; True requires them;
    False skips verification.  Raises :class:`ChunkIntegrityError` naming
    the corrupt chunks so the caller can retry with different survivors.

    ``_fallback_rows`` (private, supplied by :func:`auto_decode_file`):
    extra healthy chunk indices whose files live next to ``in_file``.
    With a pool, a *mid-stream* survivor failure — a read error that
    outlives its retries, attributable to one chunk — triggers degraded
    decode: the failed survivor is swapped for a pool chunk, the decode
    matrix is re-derived, and streaming resumes from the first
    uncommitted segment instead of aborting the run
    (docs/RESILIENCE.md).  When no pool chunk can cover the failure, it
    surfaces as :class:`ChunkIntegrityError` naming the survivor (the
    open-time contract), so auto-decode's outer loop can exclude it and
    reselect.
    """
    timer = timer or PhaseTimer(enabled=False)
    if len(_mesh_processes(mesh)) > 1:
        if _fleet is not None:
            raise ValueError(
                "fleet decode is single-host; multi-process decodes are "
                "collectives with their own barriers"
            )
        # The multi-process path does its own lead-verified checksum
        # pre-pass and collective recovery.
        return _decode_file_multiprocess(
            in_file, conf_file, output,
            strategy=strategy, segment_bytes=segment_bytes,
            pipeline_depth=pipeline_depth, mesh=mesh,
            stripe_sharded=stripe_sharded,
            verify_checksums=verify_checksums, timer=timer,
        )
    with timer.phase("read metadata (io)"):
        meta = read_archive_meta(metadata_file_name(in_file))
        total_size, p, k = meta.total_size, meta.parity_num, meta.native_num
        total_mat, w, crcs = meta.total_mat, meta.w, meta.crcs
        layout = meta.layout
    _check_gfwidth(w, metadata_file_name(in_file))
    if total_mat is None:
        total_mat = _regenerate_total_matrix(p, k, w)
    if int(total_mat.max(initial=0)) >= (1 << w):
        raise ValueError(
            f"metadata matrix entry {int(total_mat.max())} out of range for "
            f"GF(2^{w}) — corrupt or foreign .METADATA"
        )
    sym = w // 8
    chunk = meta.chunk
    names = read_conf(conf_file)
    if len(names) != k:
        raise ValueError(f"conf file lists {len(names)} chunks, need k={k}")
    rows = [parse_chunk_index(nm) for nm in names]

    conf_dir = os.path.dirname(os.path.abspath(conf_file))

    def resolve(nm: str) -> str:
        for cand in (nm, os.path.join(conf_dir, os.path.basename(nm)),
                     os.path.join(os.path.dirname(os.path.abspath(in_file)),
                                  os.path.basename(nm))):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(f"surviving chunk {nm!r} not found")

    with timer.phase("open chunks (io)"):
        maps = []
        paths = []
        bad_open: dict[int, str] = {}
        for pos, nm in enumerate(names):
            path = resolve(nm)
            try:
                mm = _open_chunk(path, chunk, index=rows[pos])
            except ChunkIntegrityError as e:
                bad_open.update(e.bad_chunks)
                continue
            except OSError:
                # The TOCTOU window: this chunk existed when the conf (or
                # auto-decode scan) selected it but vanished or became
                # unreadable (retries included) before this open.  Collect
                # and name it instead of dying on a raw error so
                # auto_decode_file can exclude it and reselect.  A conf
                # naming a chunk that was NEVER found still raises
                # FileNotFoundError from resolve() above.
                bad_open[rows[pos]] = path
                continue
            maps.append(mm)
            paths.append(path)
        if bad_open:
            raise ChunkIntegrityError(
                bad_open,
                reason="survivor chunks unreadable, truncated or vanished "
                "after selection",
            )

    if verify_checksums is not False:
        if verify_checksums and not crcs:
            raise ValueError(
                f"{metadata_file_name(in_file)!r} has no checksum lines "
                "but verify_checksums=True"
            )
        if crcs:
            uncovered = [r for r in rows if r not in crcs]
            if verify_checksums and uncovered:
                raise ValueError(
                    f"metadata has no CRC for survivor chunk(s) {uncovered} "
                    "but verify_checksums=True"
                )
            # Verification is a separate pre-pass (reads survivors once more
            # than strictly needed): corruption is detected BEFORE any device
            # compute or output writing, and the error names the bad chunks
            # while the conf can still be fixed.
            with timer.phase("verify checksums"):
                bad = {}
                for row, mm, path in zip(rows, maps, paths):
                    if row not in crcs:
                        continue
                    if chunk_crc32(mm, chunk, segment_bytes) != crcs[row]:
                        bad[row] = path
                if bad:
                    raise ChunkIntegrityError(bad)

    if total_size == 0:
        # Foreign zero-byte archive (the reference encoder sizes by ftell
        # with no empty-file guard, cpu-rs.c:492-495, so an empty input
        # yields totalSize=0 metadata): every chunk is zero bytes and the
        # original is the empty file.  Placed AFTER chunk resolution and
        # the checksum contract checks — a conf naming absent chunks or
        # verify_checksums=True without CRC lines still fails loudly.
        return _write_empty_atomic(output or in_file)

    codec = RSCodec(
        k, p, w=w, strategy=strategy, mesh=mesh, stripe_sharded=stripe_sharded
    )
    total_mat = total_mat.astype(codec.gf.dtype)

    # Partial-recovery optimisation: surviving NATIVE chunks are already the
    # answer — copy their bytes straight through and run the recovery GEMM
    # only for the missing native rows.  The reference always multiplies the
    # full k x k (decode.cu:89-227); here a 4-of-14 erasure does 4/10 of
    # that work, and the all-natives scenario does no device work at all.
    # (For survivor rows that are natives, the corresponding rows of the
    # inverse are unit vectors, so dropping them is exact, not approximate.)
    # Only valid when the metadata matrix is systematic (identity top block)
    # — a foreign encoder may write any matrix, and we trust the file.
    systematic = np.array_equal(total_mat[:k], np.eye(k, dtype=total_mat.dtype))

    out_path = output or in_file
    seg_cols = _segment_cols(chunk, k, segment_bytes)
    tmp_path = out_path + ".rs_tmp"
    segments = _segment_spans(chunk, seg_cols)

    # Mutable survivor state: the degraded-decode path swaps a mid-stream-
    # failing survivor for a fallback chunk and resumes, so everything
    # derived from the survivor set lives here and is rebuilt by _derive().
    st: dict = {
        "rows": list(rows), "maps": list(maps), "paths": list(paths),
        "fps": [],
    }

    def _derive() -> None:
        with timer.phase("invert matrix"):
            dec_mat = codec.decode_matrix_from(total_mat, st["rows"])
        native_pos = (
            {r: idx for idx, r in enumerate(st["rows"]) if r < k}
            if systematic else {}
        )
        missing = [i for i in range(k) if i not in native_pos]
        st["native_pos"] = native_pos
        st["rec_row"] = {i: j for j, i in enumerate(missing)}
        st["dec_missing"] = dec_mat[missing] if missing else None
        for fp in st["fps"]:
            if not fp.closed:
                fp.close()
        # Read fds for the pread gather — only the recovery path stages
        # segments; the all-natives path copies through the memmaps.
        st["fps"] = (
            [open(p_, "rb") for p_ in st["paths"]]
            if st["dec_missing"] is not None else []
        )

    _derive()

    try:
        out_fp = open(tmp_path, "wb")
    except BaseException:
        # cleanup() below closes these, but it cannot exist yet without
        # out_fp — an unwritable output target must not leak k chunk fds.
        for fp in st["fps"]:
            fp.close()
        raise

    def finalize() -> None:
        # Runs after every output write landed (standalone: after the
        # drain executor's barrier; fleet: behind this file's drains on
        # the shared writer lane).
        out_fp.truncate(total_size)
        out_fp.close()
        for fp in st["fps"]:
            fp.close()
        os.replace(tmp_path, out_path)

    def cleanup() -> None:
        if not out_fp.closed:
            out_fp.close()
        for fp in st["fps"]:
            if not fp.closed:
                fp.close()
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)

    # Contiguous segments fully written.  Drains commit in dispatch order
    # (ordered lane / FIFO window), and dispatch order is ascending from
    # each attempt's resume point, so the max committed index is also the
    # length of the committed prefix — the degraded path's resume point.
    committed = {"n": 0}

    def write_row(i: int, off: int, cols: int, row_bytes: np.ndarray):
        lo = i * chunk + off
        if lo >= total_size:
            return
        hi = min(lo + cols, total_size)
        out_fp.seek(lo)
        out_fp.write(row_bytes[: hi - lo].tobytes())
        _obs_metrics.counter(
            "rs_io_write_bytes_total",
            "bytes write by the staging-I/O layer",
        ).labels(call="stream_write").inc(hi - lo)

    def write_interleaved(off: int, cols: int, blk: np.ndarray):
        _write_deinterleaved_block(out_fp, off, cols, blk, sym, total_size)

    def _stream(segs) -> None:
        # Bind THIS attempt's derived state into the closures: drains a
        # fleet lane already queued keep the survivor set their recovery
        # GEMM actually used (any valid set recovers identical bytes, so
        # mixed-attempt drains still write a consistent output).
        native_pos = st["native_pos"]
        rec_row = st["rec_row"]
        dec_missing = st["dec_missing"]
        maps_l, paths_l = st["maps"], st["paths"]
        rows_l, fps_l = st["rows"], st["fps"]

        def drain(tag, rec):
            off, cols = tag
            with timer.phase("decode compute"):
                rec_np = np.asarray(rec) if rec is not None else None
            if rec_np is not None and rec_np.dtype != np.uint8:
                rec_np = np.ascontiguousarray(rec_np).view(np.uint8)  # LE
            with timer.phase("write output (io)"):
                if layout == "interleaved":
                    blk = np.empty((k, cols), dtype=np.uint8)
                    for i in range(k):
                        if i in native_pos:
                            blk[i] = maps_l[native_pos[i]][off : off + cols]
                        else:
                            blk[i] = rec_np[rec_row[i]][:cols]
                    write_interleaved(off, cols, blk)
                else:
                    for i in range(k):
                        if i in native_pos:
                            src_row = maps_l[native_pos[i]][off : off + cols]
                            write_row(i, off, cols, src_row)
                        else:
                            write_row(i, off, cols, rec_np[rec_row[i]])
            committed["n"] = max(committed["n"], off // seg_cols + 1)

        from . import native

        if dec_missing is None:
            with _drain_ctx(_fleet) as dex, AsyncWindow(
                pipeline_depth, drain, executor=dex
            ) as window:
                for off, cols in segs:
                    # all natives survived: pure copy, nothing staged
                    window.push((off, cols), None)
            return

        def stage(off: int, cols: int) -> np.ndarray:
            # Native pread gather (one syscall per surviving chunk);
            # memmap copies as fallback.  Runs on the prefetch worker so
            # read IO overlaps the drain's output writes.  A resilience
            # read boundary: per-survivor fault hook + transient-retry
            # (the gather fills a fresh buffer — idempotent).
            def attempt() -> np.ndarray:
                _faults.on_reads(paths_l, rows_l)
                return native.gather_rows(
                    fps_l, off, cols, fallback_maps=maps_l
                )

            with timer.phase("stage segment (io)"):
                return _retry.default_policy().call(
                    attempt, op="decode_stage"
                )

        # Ordered write-behind: the streaming shared-fp seek/write
        # commit must stay in column order, but it runs on the writer
        # lane — the dispatch loop never blocks on D2H or fp.write.
        with SegmentPrefetcher(
            segs, stage, depth=pipeline_depth
        ) as prefetch, _drain_ctx(_fleet) as dex, AsyncWindow(
            pipeline_depth, drain, executor=dex
        ) as window:
            staging = _staging_ring(
                prefetch, codec, seg_cols, sym, pipeline_depth,
                out_rows=dec_missing.shape[0],
            )
            for (off, cols), seg in staging:
                with timer.phase("decode dispatch"), _dispatch_span(
                    "decode", off, cols
                ):
                    rec = codec.decode(dec_missing, seg)  # async
                window.push((off, cols), rec)

    def _attribute(e: BaseException) -> list[int]:
        """Survivor rows a mid-stream read failure pins on: injected
        faults carry their chunk index; real failures are probed with
        fstat (a chunk truncated or unlinked under us shows up here)."""
        if isinstance(e, _faults.InjectedReadError):
            return [e.index] if e.index in st["rows"] else []
        bad = []
        for r, fp in zip(st["rows"], st["fps"]):
            try:
                if os.fstat(fp.fileno()).st_size < chunk:
                    bad.append(r)
            except OSError:
                bad.append(r)
        return bad

    pool = [r for r in (_fallback_rows or []) if r not in set(st["rows"])]

    # Swapped-in pool chunks get the same read-time integrity treatment
    # the initial survivors got: CRC-verified whenever the pre-pass above
    # verified (verify_checksums=True, or default-on with CRC lines) —
    # a pool chunk that rotted after the scan must not decode silently.
    verify_swaps = verify_checksums is not False and bool(crcs)

    def _reselect(bad: list[int]) -> bool:
        """Swap the failed survivors for pool chunks and re-derive the
        decode state; False when the pool cannot cover them (or every
        replacement set hits a singular submatrix)."""
        from .ops.inverse import SingularMatrixError

        keep = [
            (r, m, p_)
            for r, m, p_ in zip(st["rows"], st["maps"], st["paths"])
            if r not in bad
        ]
        while True:
            fresh = []
            while pool and len(keep) + len(fresh) < k:
                r = pool.pop(0)
                p_ = chunk_file_name(in_file, r)
                try:
                    m = _open_chunk(p_, chunk, index=r)
                    if (
                        verify_swaps and r in crcs
                        and chunk_crc32(m, chunk, segment_bytes) != crcs[r]
                    ):
                        continue  # rotted after the scan; try the next
                except (ValueError, OSError):
                    continue  # this fallback is damaged too; try the next
                fresh.append((r, m, p_))
            if len(keep) + len(fresh) < k:
                return False
            merged = keep + fresh
            st["rows"] = [r for r, _, _ in merged]
            st["maps"] = [m for _, m, _ in merged]
            st["paths"] = [p_ for _, _, p_ in merged]
            try:
                _derive()
            except SingularMatrixError:
                continue  # rare non-MDS corner: try further pool chunks
            return True

    key = _fleet.register(cleanup) if _fleet is not None else None
    reselects = 0
    max_reselects = max(0, _retry.int_env("RS_RETRY_RESELECT", 3))
    try:
        while True:
            try:
                _stream(segments[committed["n"]:])
                break
            except OSError as e:
                bad = [r for r in _attribute(e) if r in st["rows"]]
                if not bad:
                    raise  # unattributable (e.g. a write-side error)
                # Snapshot the failing rows' paths NOW: a failed
                # _reselect leaves st mutated with the bad rows already
                # dropped, and the error below must still name them.
                bad_paths = {
                    r: p_ for r, p_ in zip(st["rows"], st["paths"])
                    if r in bad
                }
                swapped = False
                if reselects < max_reselects:
                    if _fleet is not None:
                        # Let this archive's queued drains land (their
                        # bytes are correct for their segments) so
                        # ``committed`` is final before the resume point
                        # is chosen.
                        _fleet.executor.flush()
                    swapped = _reselect(bad)
                if not swapped:
                    # Attributed but unswappable: surface the failing
                    # survivor BY NAME so auto_decode_file's outer loop
                    # can exclude it, rescan and reselect — the same
                    # contract as an open-time (TOCTOU) failure.
                    raise ChunkIntegrityError(
                        bad_paths,
                        reason="survivor chunk failed mid-stream reads "
                        "past retries",
                    ) from e
                reselects += 1
                _obs_tracing.instant(
                    "degraded_reselect", lane="retry",
                    bad=",".join(map(str, bad)),
                    resume_segment=committed["n"],
                )
        if reselects:
            _obs_metrics.counter(
                "rs_degraded_decodes_total",
                "decodes completed after mid-stream survivor reselection",
            ).labels(stage="midstream").inc()
        if _fleet is not None:
            _fleet.commit(key, finalize)
        else:
            finalize()
    except BaseException:
        if _fleet is None:
            cleanup()
        raise
    return out_path


def _local_col_span(sharding, k: int, W: int) -> tuple[int, int]:
    """This process's contiguous column range of a (k, W) cols-sharded
    global array (shared by the multi-process encode/decode/repair
    collectives)."""
    idx = sharding.addressable_devices_indices_map((k, W))
    spans = sorted((s[1].start, s[1].stop) for s in idx.values())
    lo, hi = spans[0][0], spans[-1][1]
    if any(a[1] != b[0] for a, b in zip(spans, spans[1:])):
        raise ValueError(
            "mesh cols axis gives this process a non-contiguous "
            "column range; build the mesh from jax.devices() order"
        )
    return lo, hi


def _local_block(sharding, shape) -> tuple[int, int, int, int]:
    """This process's contiguous (row, col) block of a 2-D sharded global
    array — the staging layout of the wide-stripe (row-sharded) encode
    collective, generalising :func:`_local_col_span` to both axes.

    Returns ``(r0, r1, c0, c1)``.  Each axis must tile contiguously and the
    process's shards must form the full cartesian block (meshes built from
    ``jax.devices()`` order do)."""
    idx = sharding.addressable_devices_indices_map(shape)

    def axis_span(a: int) -> tuple[int, int]:
        spans = sorted({
            (s[a].start or 0,
             shape[a] if s[a].stop is None else s[a].stop)
            for s in idx.values()
        })
        if any(x[1] != y[0] for x, y in zip(spans, spans[1:])):
            raise ValueError(
                f"mesh axis {a} gives this process a non-contiguous range; "
                "build the mesh from jax.devices() order"
            )
        return spans[0][0], spans[-1][1]

    r0, r1 = axis_span(0)
    c0, c1 = axis_span(1)
    return r0, r1, c0, c1


def _stripe_io_roles(mesh, stripe_sharded: bool):
    """Input sharding and write role for the wide-stripe collectives.

    Returns ``(in_sharding, writes_output)``: the data sharding
    (``P(STRIPE, COLS)`` under wide-stripe, ``P(None, COLS)`` otherwise)
    and whether THIS process writes the GEMM output.  Under stripe
    sharding the output is psum-replicated along the stripe axis, so only
    hosts whose devices sit on stripe index 0 write (located by axis NAME
    — a mesh built with transposed axis order still elects a writer set
    that covers every column shard).  Shared by the encode, decode and
    repair collectives so the election rule cannot drift between them.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .parallel.mesh import COLS, STRIPE

    in_sharding = NamedSharding(
        mesh, P(STRIPE if stripe_sharded else None, COLS)
    )
    if not stripe_sharded:
        return in_sharding, True
    ax = list(mesh.axis_names).index(STRIPE)
    row0 = np.take(np.asarray(mesh.devices), 0, axis=ax)
    writes = jax.process_index() in {
        d.process_index for d in row0.flat
    }
    return in_sharding, writes


def _make_padded_stage(fps, maps, chunk, cols_size, sharding, k, timer, sym=1):
    """Segment stager shared by the multi-process decode and repair
    collectives: reads this process's block of the k survivor files —
    its column span, and (when ``sharding`` also shards the stripe/k axis,
    the wide-stripe mode) only its survivor rows — zero-filling the pad
    columns past the chunk end (equal per-device shards need the padded
    width; the pad's decoded garbage is dropped by the trimmed writes).
    Sharding spans are in SYMBOL units (``sym`` bytes each — 2 for w=16,
    whose segments come back as uint16 views); the file reads convert
    back to byte offsets."""
    from . import native

    def stage(off: int, cols: int):
        off_s, cols_s, chunk_s = off // sym, cols // sym, chunk // sym
        W = ((cols_s + cols_size - 1) // cols_size) * cols_size
        r0, r1, lo, hi = _local_block(sharding, (k, W))
        readable = max(0, min(off_s + hi, chunk_s) - (off_s + lo))
        with timer.phase("stage segment (io)"):
            seg = np.zeros((r1 - r0, (hi - lo) * sym), dtype=np.uint8)
            if readable:
                seg[:, : readable * sym] = native.gather_rows(
                    fps[r0:r1], (off_s + lo) * sym, readable * sym,
                    fallback_maps=maps[r0:r1],
                )
            return seg.view(np.uint16) if sym == 2 else seg

    return stage


def _trimmed_shards(sharded, cols: int, sym: int = 1):
    """Materialise the addressable shards of a cols-sharded GEMM output as
    ``(byte_col0, uint8 rows)`` pairs, trimmed to the segment's real width
    (the zero-pad columns staged for equal per-device shards are dropped
    here).  Blocks on the device; callers time it under their compute
    phase.  ``sym``-byte symbols are flattened to little-endian bytes, the
    chunk-file byte order."""
    out = []
    seen: set = set()
    cols_s = cols // sym
    for sh in sharded.addressable_shards:
        col0 = sh.index[1].start or 0  # None for an unsharded cols axis
        if col0 in seen:
            # stripe-replicated output: every stripe row holds an identical
            # replica of each column shard — materialise one per range.
            continue
        seen.add(col0)
        data = np.asarray(sh.data)
        n_cols = min(data.shape[1], cols_s - col0)
        if n_cols <= 0:
            continue
        rows = np.ascontiguousarray(data[:, :n_cols])
        if rows.dtype != np.uint8:
            rows = rows.view(np.uint8)
        out.append((col0 * sym, rows))
    return out


def _unlink_shared_tmps(paths) -> None:
    """Best-effort cleanup of shared-FS temp files from a failing
    collective: every process runs this near-simultaneously against the
    same paths, so losing the exists/unlink race to a peer is success, not
    an error to bury the original exception under."""
    for tmp in paths:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass


def _decode_file_multiprocess(
    in_file: str,
    conf_file: str,
    output: str | None,
    *,
    strategy: str,
    segment_bytes: int,
    pipeline_depth: int,
    mesh,
    stripe_sharded: bool = False,
    verify_checksums: bool | None,
    timer: PhaseTimer,
) -> str:
    """Multi-host file decode over a process-spanning mesh (collective).

    Mirrors :func:`_encode_file_multiprocess` (including its wide-stripe
    composition: ``stripe_sharded`` shards the SURVIVOR axis across hosts,
    each staging only its survivor rows, with stripe-row-0 hosts writing
    the psum-replicated recovery): every host stages only its
    column span of each survivor segment, the recovery GEMM runs sharded
    over the mesh, and each host pwrites its addressable output shards into
    a shared-filesystem temp the lead process pre-sizes and atomically
    promotes.  Surviving-native passthrough rows are copied round-robin
    across hosts (partial recovery — only the missing rows ride the
    device).  The checksum pre-pass runs on the lead only and its verdict
    is broadcast, so a corrupt survivor raises the same
    :class:`ChunkIntegrityError` on every process instead of wedging peers
    at a barrier.  Requirements: shared filesystem, w=8 or w=16 (same
    contract as multi-process encode).
    """
    import jax
    from jax.experimental import multihost_utils

    from .parallel.mesh import COLS
    from .parallel.sharded import put_sharded, sharded_gf_matmul

    procs = _mesh_processes(mesh)
    lead = _is_lead(procs)

    with timer.phase("read metadata (io)"):
        meta_mp = read_archive_meta(metadata_file_name(in_file))
        total_size, p, k = (
            meta_mp.total_size, meta_mp.parity_num, meta_mp.native_num
        )
        total_mat, w, crcs = meta_mp.total_mat, meta_mp.w, meta_mp.crcs
    _check_gfwidth(w, metadata_file_name(in_file))
    if meta_mp.layout != "row":
        raise ValueError(
            f"{in_file!r} uses the {meta_mp.layout!r} chunk layout; "
            "multi-process decode handles row-layout archives only — "
            "decode single-host"
        )
    sym = w // 8
    if total_mat is None:
        total_mat = _regenerate_total_matrix(p, k, w)
    if int(total_mat.max(initial=0)) >= (1 << w):
        raise ValueError(
            f"metadata matrix entry {int(total_mat.max())} out of range for "
            f"GF(2^{w}) — corrupt or foreign .METADATA"
        )
    chunk = chunk_size_for(total_size, k, sym)
    names = read_conf(conf_file)
    if len(names) != k:
        raise ValueError(f"conf file lists {len(names)} chunks, need k={k}")
    rows = [parse_chunk_index(nm) for nm in names]

    conf_dir = os.path.dirname(os.path.abspath(conf_file))

    def resolve(nm: str) -> str:
        for cand in (nm, os.path.join(conf_dir, os.path.basename(nm)),
                     os.path.join(os.path.dirname(os.path.abspath(in_file)),
                                  os.path.basename(nm))):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(f"surviving chunk {nm!r} not found")

    with timer.phase("open chunks (io)"):
        maps, paths = [], []
        for pos, nm in enumerate(names):
            path = resolve(nm)
            mm = _open_chunk(path, chunk, index=rows[pos])
            maps.append(mm)
            paths.append(path)

    if verify_checksums is not False:
        if verify_checksums and not crcs:
            raise ValueError(
                f"{metadata_file_name(in_file)!r} has no checksum lines "
                "but verify_checksums=True"
            )
        if crcs:
            uncovered = [r for r in rows if r not in crcs]
            if verify_checksums and uncovered:
                raise ValueError(
                    f"metadata has no CRC for survivor chunk(s) {uncovered} "
                    "but verify_checksums=True"
                )
            # Lead-only CRC pass; verdict broadcast as a (k,) 0/1 mask so
            # every process raises (or proceeds) in lockstep.
            with timer.phase("verify checksums"):
                bad_mask = np.zeros(k, dtype=np.int32)
                if lead:
                    for pos, (row, mm) in enumerate(zip(rows, maps)):
                        if row in crcs and (
                            chunk_crc32(mm, chunk, segment_bytes) != crcs[row]
                        ):
                            bad_mask[pos] = 1
                bad_mask = np.asarray(
                    multihost_utils.broadcast_one_to_all(
                        bad_mask, is_source=lead
                    )
                )
                if bad_mask.any():
                    raise ChunkIntegrityError({
                        rows[pos]: paths[pos]
                        for pos in np.flatnonzero(bad_mask)
                    })

    if total_size == 0:
        # Foreign zero-byte archive (see decode_file — same placement,
        # after chunk resolution and the checksum contract checks): the
        # lead writes the empty output; all processes leave in lockstep.
        out_path = output or in_file
        if lead:
            _write_empty_atomic(out_path)
        multihost_utils.sync_global_devices("rs_decode_promoted")
        return out_path

    codec = RSCodec(
        k, p, w=w, strategy=strategy, mesh=mesh,
        stripe_sharded=stripe_sharded,
    )
    total_mat = total_mat.astype(codec.gf.dtype)
    with timer.phase("invert matrix"):
        dec_mat = codec.decode_matrix_from(total_mat, rows)

    # Same partial-recovery split as the single-process path.
    systematic = np.array_equal(total_mat[:k], np.eye(k, dtype=total_mat.dtype))
    native_pos = (
        {r: idx for idx, r in enumerate(rows) if r < k} if systematic else {}
    )
    missing = [i for i in range(k) if i not in native_pos]
    dec_missing = dec_mat[missing] if missing else None

    out_path = output or in_file
    tmp_path = out_path + ".rs_tmp"
    seg_cols = _segment_cols(chunk, k, segment_bytes)
    cols_size = mesh.shape[COLS]
    # Wide-stripe mode: the SURVIVOR axis shards across hosts too — each
    # stages only its survivor rows; the recovered output is replicated
    # along stripe (psum), so only stripe-row-0 hosts write it.
    in_sharding, writes_output = _stripe_io_roles(mesh, stripe_sharded)
    copy_step = max(1, segment_bytes)

    try:
        if lead:
            with open(tmp_path, "wb") as fp:
                fp.truncate(total_size)
        multihost_utils.sync_global_devices("rs_decode_tmp_created")

        out_fp = open(tmp_path, "r+b")
        fps = [open(p_, "rb") for p_ in paths] if dec_missing is not None else []
        try:
            def pwrite_row(i: int, off: int, row_bytes: np.ndarray) -> None:
                lo = i * chunk + off
                if lo >= total_size:
                    return
                hi = min(lo + row_bytes.shape[0], total_size)
                os.pwrite(
                    out_fp.fileno(),
                    np.ascontiguousarray(row_bytes[: hi - lo]).tobytes(),
                    lo,
                )

            # Surviving natives: straight host copies, split round-robin
            # across the participating hosts (no device involved).
            with timer.phase("write output (io)"):
                my_rank = procs.index(jax.process_index())
                for idx, i in enumerate(sorted(native_pos)):
                    if idx % len(procs) != my_rank:
                        continue
                    mm = maps[native_pos[i]]
                    for s in range(0, chunk, copy_step):
                        pwrite_row(i, s, mm[s : min(s + copy_step, chunk)])

            if dec_missing is not None:
                stage = _make_padded_stage(
                    fps, maps, chunk, cols_size, in_sharding, k, timer, sym
                )

                def drain(tag, rec_sharded) -> None:
                    off, cols = tag
                    if not writes_output:
                        # Replica holder: block for window backpressure
                        # only (stripe row 0 writes the identical bytes).
                        with timer.phase("decode compute"):
                            jax.block_until_ready(rec_sharded)
                        return
                    with timer.phase("decode compute"):
                        shards = _trimmed_shards(rec_sharded, cols, sym)
                    with timer.phase("write output (io)"):
                        for col0, data in shards:
                            for j, i in enumerate(missing):
                                pwrite_row(i, off + col0, data[j])

                # Out-of-order write-behind (offset-addressed pwrites into
                # the lead-pre-sized temp; no cross-segment state).
                with SegmentPrefetcher(
                    _segment_spans(chunk, seg_cols), stage,
                    depth=pipeline_depth,
                ) as prefetch, _drain_ctx(
                    None, ordered=False
                ) as dex, AsyncWindow(
                    pipeline_depth, drain, executor=dex
                ) as window:
                    for (off, cols), local_seg in prefetch:
                        with timer.phase("decode dispatch"), _dispatch_span(
                            "decode", off, cols
                        ):
                            Bd = put_sharded(local_seg, mesh, stripe_sharded)
                            rec = sharded_gf_matmul(
                                np.asarray(dec_missing), Bd,
                                mesh=mesh, w=w, strategy=codec.strategy,
                                stripe_sharded=stripe_sharded,
                            )
                        window.push((off, cols), rec)
        finally:
            out_fp.close()
            for fp in fps:
                fp.close()
        multihost_utils.sync_global_devices("rs_decode_written")
        if lead:
            os.replace(tmp_path, out_path)
    except BaseException:
        _unlink_shared_tmps([tmp_path])
        raise
    multihost_utils.sync_global_devices("rs_decode_promoted")
    return out_path


def _regenerate_total_matrix(p: int, k: int, w: int) -> np.ndarray:
    """Canonical [I; Vandermonde] total matrix for sizes-only (CPU-RS
    dialect) metadata — bit-identical to the reference's regeneration."""
    from .models.vandermonde import total_matrix
    from .ops.gf import get_field

    return total_matrix(p, k, get_field(w))


class _ChunkScan:
    """Result of scanning an encode's chunk set: metadata fields plus which
    chunk indices are healthy, CRC-failing, or missing."""

    def __init__(self, in_file, total_size, p, k, total_mat, w, crcs,
                 chunk, healthy, bad, layout="row", generation=0):
        self.in_file = in_file
        self.total_size = total_size
        self.p = p
        self.k = k
        self.total_mat = total_mat
        self.w = w
        self.crcs = crcs
        self.chunk = chunk
        self.healthy = healthy          # indices with full-size, CRC-clean files
        self.bad = bad                  # {index: path} damaged: truncated or CRC-fail
        self.layout = layout            # chunk layout (docs/UPDATE.md)
        self.generation = generation    # update/append commit counter
        self.missing = sorted(
            set(range(k + p)) - set(healthy) - set(bad)
        )

    @property
    def unhealthy(self):
        """All chunk indices needing repair (corrupt or absent)."""
        return sorted(set(self.bad) | set(self.missing))

    def excluding(self, bad: dict[int, str]) -> "_ChunkScan":
        """A view of this scan with ``bad`` chunks demoted from healthy —
        how auto-decode folds in failures discovered AFTER the scan
        (TOCTOU opens, mid-stream read errors) before reselecting."""
        return _ChunkScan(
            self.in_file, self.total_size, self.p, self.k, self.total_mat,
            self.w, self.crcs, self.chunk,
            [i for i in self.healthy if i not in bad],
            {**self.bad, **bad},
            layout=self.layout, generation=self.generation,
        )


def _scan_chunks(in_file: str, segment_bytes: int) -> _ChunkScan:
    """Discover chunk health next to ``in_file`` (size + CRC checks).

    The scrub instrumentation point: every archive scan counts itself
    and its per-chunk verdicts (``rs_scrub_archives_scanned_total`` /
    ``rs_scrub_chunks_total{state}``) and records one span on the
    ``scrub`` lane, so fleet-wide health sweeps (scan_file, repair_fleet,
    auto-decode discovery) all feed the same series.
    """
    with _obs_tracing.span("scan_chunks", lane="scrub", file=in_file):
        meta_path = metadata_file_name(in_file)
        meta = read_archive_meta(meta_path)
        total_size, p, k = meta.total_size, meta.parity_num, meta.native_num
        total_mat, w, crcs = meta.total_mat, meta.w, meta.crcs
        _check_gfwidth(w, meta_path)
        if total_mat is None:
            total_mat = _regenerate_total_matrix(p, k, w)
        if int(total_mat.max(initial=0)) >= (1 << w):
            raise ValueError(
                f"metadata matrix entry {int(total_mat.max())} out of range "
                f"for GF(2^{w}) — corrupt or foreign .METADATA"
            )
        # Layout-aware chunk length: interleaved archives (the append-mode
        # extension, docs/UPDATE.md) size chunks by columns, not rows.
        # Everything below — size check, CRC over the whole chunk file,
        # health verdicts — is layout-agnostic given the right length.
        chunk = meta.chunk
        chunk_states = _obs_metrics.counter(
            "rs_scrub_chunks_total", "chunk verdicts from archive scans"
        )
        healthy: list[int] = []
        bad: dict[int, str] = {}
        # Per-index damage verdicts for the health plane (obs/health.py):
        # one rs_damage "scan" event per scan, whose FULL state map (an
        # empty one included — a clean scan clears prior damage) is the
        # fleet model's scrub-freshness signal.
        damage_states: dict[int, str] = {}
        for i in range(k + p):
            path = chunk_file_name(in_file, i)
            if not os.path.exists(path):
                chunk_states.labels(state="missing").inc()
                damage_states[i] = "missing"
                continue
            if os.path.getsize(path) < chunk:
                bad[i] = path  # present but truncated — damage, not loss
                chunk_states.labels(state="truncated").inc()
                damage_states[i] = "truncated"
                continue
            if i in crcs:
                try:
                    # empty-safe for chunk == 0; scope="scrub" addresses
                    # the fault plane's scrub boundary
                    mm = _open_chunk(path, chunk, index=i, scope="scrub")
                except ChunkIntegrityError:
                    # Shrank between the getsize above and this open.
                    bad[i] = path
                    chunk_states.labels(state="truncated").inc()
                    damage_states[i] = "truncated"
                    continue
                except OSError:
                    # Degraded read: a chunk that stays unreadable after
                    # retries is damage to record, not a reason to fail
                    # the whole archive scan — scrub carries on and
                    # repair treats it like any other corrupt chunk.
                    bad[i] = path
                    chunk_states.labels(state="read_error").inc()
                    damage_states[i] = "read_error"
                    continue
                if chunk_crc32(mm, chunk, segment_bytes) != crcs[i]:
                    bad[i] = path
                    chunk_states.labels(state="crc_mismatch").inc()
                    damage_states[i] = "crc_mismatch"
                    continue
            healthy.append(i)
            chunk_states.labels(state="healthy").inc()
        _obs_metrics.counter(
            "rs_scrub_archives_scanned_total", "archive health scans"
        ).labels(outcome="damaged" if bad or len(healthy) < k + p
                 else "clean").inc()
        _obs_health.record_damage(
            "scan", in_file, states=damage_states, k=k, p=p, w=w,
            generation=meta.generation,
        )
        return _ChunkScan(
            in_file, total_size, p, k, total_mat, w, crcs, chunk, healthy,
            bad, layout=meta.layout, generation=meta.generation,
        )


# -- generation-keyed survivor-subset cache -----------------------------------
#
# Decode-side warm-path amortization (docs/PLAN.md "Generation-keyed
# schedule entries"): every auto-decode attempt, scrub verdict and
# repair pass used to re-run the subset search and re-invert the k x k
# submatrix — and, under ``strategy="xor"``, every DISTINCT survivor
# subset compiles its own inverse schedule.  This cache pins one chosen
# subset + verified inverse per (archive, generation): subset churn
# (different parity chunks dying and coming back, natives reappearing,
# fleet re-passes) keeps resolving to the pinned subset as long as it is
# still fully healthy, so the xor schedule for its inverse compiles
# exactly once per archive generation.  An update/append bumps the
# metadata generation and invalidates the entry; a total-matrix change
# (re-encode under the same name, different generator) is caught by the
# matrix digest.  ``PLAN_CACHE.clear()`` clears this too — the pinned
# inverse's schedule lives in the caches that clear drops.

_SUBSET_CACHE: "OrderedDict[str, dict]" = OrderedDict()
_SUBSET_LOCK = threading.Lock()
_SUBSET_CACHE_MAX = 128
_SUBSET_STATS = {"hits": 0, "misses": 0, "stale": 0}


def clear_subset_cache() -> None:
    """Drop the generation-keyed survivor-subset cache (paired with
    ``PLAN_CACHE.clear()``; stats reset too)."""
    with _SUBSET_LOCK:
        _SUBSET_CACHE.clear()
        for key in _SUBSET_STATS:
            _SUBSET_STATS[key] = 0


def subset_cache_stats() -> dict:
    """Doctor surface: entry count + this process's hit/miss/stale
    tallies (``rs doctor`` strategies section)."""
    with _SUBSET_LOCK:
        return {"entries": len(_SUBSET_CACHE), **_SUBSET_STATS}


def _subset_mat_digest(scan: _ChunkScan) -> str:
    from .ops.xor_gemm import matrix_digest

    return matrix_digest(scan.total_mat, scan.w)


def _cached_subset(scan: _ChunkScan):
    """The pinned (chosen, inverse) for this archive generation, or None
    when absent, generation-stale, matrix-mismatched, or no longer fully
    healthy in this scan."""
    key = os.path.abspath(scan.in_file)
    with _SUBSET_LOCK:
        ent = _SUBSET_CACHE.get(key)
    if ent is None:
        return None
    if (
        ent["generation"] != scan.generation
        or ent["mat_digest"] != _subset_mat_digest(scan)
        or len(ent["chosen"]) != scan.k
    ):
        with _SUBSET_LOCK:
            if _SUBSET_CACHE.get(key) is ent:
                del _SUBSET_CACHE[key]
            _SUBSET_STATS["stale"] += 1
        return None
    if not set(ent["chosen"]) <= set(scan.healthy):
        # Not stale — the pinned subset just isn't available under THIS
        # scan's damage; a later scan with those chunks back reuses it.
        return None
    with _SUBSET_LOCK:
        if key in _SUBSET_CACHE:
            _SUBSET_CACHE.move_to_end(key)
        _SUBSET_STATS["hits"] += 1
    return list(ent["chosen"]), ent["inv"]


def _remember_subset(scan: _ChunkScan, chosen, inv) -> None:
    key = os.path.abspath(scan.in_file)
    ent = {
        "generation": scan.generation,
        "mat_digest": _subset_mat_digest(scan),
        "chosen": tuple(int(c) for c in chosen),
        "inv": inv,
    }
    with _SUBSET_LOCK:
        _SUBSET_CACHE[key] = ent
        _SUBSET_CACHE.move_to_end(key)
        while len(_SUBSET_CACHE) > _SUBSET_CACHE_MAX:
            _SUBSET_CACHE.popitem(last=False)
        _SUBSET_STATS["misses"] += 1


def _select_decodable_subset(scan: _ChunkScan, *, cap: int = 100,
                             skip: int = 0):
    """Pick k healthy chunk indices whose submatrix inverts; returns
    ``(chosen, inverse)`` so callers don't re-invert.

    Natives-first candidate order (partial recovery makes them free), then
    parity; lazily falls back through other subsets on singularity.  The cap
    bounds pathological non-MDS matrices; Vandermonde/Cauchy submatrices
    are near-always invertible so the first try is the common case.

    ``skip``/``cap`` window the candidate stream so a caller that caught
    :class:`UndecidedSubsetError` can continue the search where the last
    batch stopped (:func:`_select_subset_retrying`) instead of redoing —
    and then abandoning — the same ``cap`` singular candidates.

    A fresh-window call (``skip == 0``) first consults the
    generation-keyed subset cache: the archive's pinned subset — still
    fully healthy under this scan, same generation, same matrix — comes
    back with zero search, zero inversion and (under ``strategy="xor"``)
    zero new schedule compiles.
    """
    from itertools import combinations

    from .ops.gf import get_field
    from .ops.inverse import SingularMatrixError, invert_matrix

    k = scan.k
    if len(scan.healthy) < k:
        raise ValueError(
            f"only {len(scan.healthy)} healthy chunks of the k={k} needed "
            f"(corrupt: {sorted(scan.bad)}, missing: {scan.missing})"
        )
    if skip == 0:
        hit = _cached_subset(scan)
        if hit is not None:
            return hit
    gf = get_field(scan.w)
    mat = scan.total_mat.astype(gf.dtype)
    capped = False
    for attempt, subset in enumerate(combinations(scan.healthy, k)):
        if attempt < skip:
            continue
        if attempt >= skip + cap:
            capped = True
            break
        try:
            inv = invert_matrix(mat[list(subset)], gf)
            _remember_subset(scan, subset, inv)
            return list(subset), inv
        except SingularMatrixError:
            continue
    # Distinguish "search space exhausted" from "search cap hit": with the
    # cap hit, a later subset could still invert, so the archive is not
    # proven unrecoverable.
    if capped:
        raise UndecidedSubsetError(
            f"no decodable k={k} subset within candidate subsets "
            f"[{skip}, {skip + cap}) of healthy chunks {scan.healthy}; "
            "more combinations exist — this archive is not proven "
            "unrecoverable"
        )
    raise ValueError(
        f"no decodable k={k} subset among healthy chunks {scan.healthy}"
    )


def _select_subset_retrying(scan: _ChunkScan, attempts: int | None = None):
    """Surface the singular-minor retry discipline (ops/inverse.py's
    verify-and-fallback) at the subset level: on
    :class:`UndecidedSubsetError` keep searching the next candidate batch
    instead of propagating, up to ``RS_RETRY_SUBSET_ATTEMPTS`` batches of
    100 (bounded — the candidate space is combinatorial)."""
    cap = 100
    attempts = (
        max(1, _retry.int_env("RS_RETRY_SUBSET_ATTEMPTS", 3))
        if attempts is None else max(1, attempts)
    )
    last: UndecidedSubsetError | None = None
    for batch in range(attempts):
        try:
            return _select_decodable_subset(scan, cap=cap, skip=batch * cap)
        except UndecidedSubsetError as e:
            last = e
            _obs_metrics.counter(
                "rs_retries_total", "retry-policy outcomes"
            ).labels(outcome="subset_retry").inc()
    raise last


@_observed_file_op("auto_decode")
def auto_decode_file(
    in_file: str,
    output: str | None = None,
    *,
    conf_out: str | None = None,
    **decode_kwargs,
) -> str:
    """Decode without a hand-written conf: discover surviving chunks, drop
    corrupt ones, pick a decodable k-subset, and rebuild the file.

    The reference has no equivalent — its conf file is the (manual) fault
    model (unit-test.sh, SURVEY §4).  This automates the full self-healing
    flow the CRC32 extension enables:

    1. scan for ``_<i>_<name>`` chunk files next to ``in_file``;
    2. discard wrong-sized chunks, and (when .METADATA carries CRC lines)
       chunks whose bytes fail their checksum;
    3. choose k survivors, natives first (cheapest: partial recovery copies
       them through), falling back to other subsets if the selected
       submatrix is singular;
    4. write the chosen survivor list as a conf file (``conf_out``, default
       ``<in_file>.auto.conf`` — an auditable artifact in the reference's
       own format) and run :func:`decode_file` with it.

    Raises ValueError when fewer than k healthy chunks remain or no
    decodable subset exists.  ``decode_kwargs`` pass through to decode_file.

    Resilience (docs/RESILIENCE.md): this is the degraded-read entry
    point.  Survivors that fail AFTER the scan selected them — truncated
    or unlinked in the scan-to-decode window (TOCTOU), CRC-failing at
    read time, or erroring mid-stream past their retries — surface as
    :class:`ChunkIntegrityError`; this function excludes the named chunks,
    rescans, reselects a fresh subset and redecodes, up to
    ``RS_RETRY_RESELECT`` attempts.  The unselected healthy chunks are
    also handed to :func:`decode_file` as a fallback pool, so a
    *mid-stream* failure first tries an in-place survivor swap that
    resumes from the failed segment instead of restarting.  A subset
    search that hits its candidate cap (:class:`UndecidedSubsetError`)
    continues into the next candidate batches instead of propagating
    (``RS_RETRY_SUBSET_ATTEMPTS``).

    Integrity note: the scan CRC-verifies the chunks it selects, and the
    inner decode skips re-verification by default — corruption appearing in
    the scan-to-decode window (TOCTOU) is caught only when it changes a
    chunk's size or readability.  Callers needing end-to-end integrity on
    live-mutating storage should pass ``verify_checksums=True`` explicitly
    to re-check content at read time.
    """
    conf_path = conf_out or (in_file + ".auto.conf")
    procs = _mesh_processes(decode_kwargs.get("mesh"))
    if len(procs) > 1:
        # With a process-spanning mesh this is a collective: only the LEAD
        # scans (one CRC read of the archive, not one per host) and writes
        # the conf to the shared filesystem; peers wait at the barrier.
        # The scan verdict — ok or error — is broadcast before that
        # barrier so a lead-side failure (corrupt metadata, unrecoverable
        # archive) raises on every process instead of wedging the peers
        # until coordinator teardown.  No degraded retry loop here: a
        # mid-collective survivor swap would need its own barrier
        # choreography on every process.
        from jax.experimental import multihost_utils

        scan_err: Exception | None = None
        if _is_lead(procs):
            try:
                scan = _scan_chunks(
                    in_file,
                    decode_kwargs.get("segment_bytes", DEFAULT_SEGMENT_BYTES),
                )
                chosen, _ = _select_subset_retrying(scan)
                write_conf(
                    conf_path,
                    [os.path.basename(chunk_file_name(in_file, i))
                     for i in chosen],
                )
            except Exception as e:
                scan_err = e
        _broadcast_lead_verdict(
            scan_err, procs, "archive scan / survivor selection"
        )
        multihost_utils.sync_global_devices("rs_auto_conf_written")
        if decode_kwargs.get("verify_checksums") is None:
            decode_kwargs["verify_checksums"] = False
        return decode_file(in_file, conf_path, output, **decode_kwargs)

    attempts = max(1, _retry.int_env("RS_RETRY_RESELECT", 3) + 1)
    excluded: dict[int, str] = {}
    last: Exception | None = None
    locate_mode = _locate_mode()

    def _locate_kwargs() -> dict:
        out = {
            key: decode_kwargs[key]
            for key in ("strategy", "segment_bytes", "pipeline_depth",
                        "timer")
            if key in decode_kwargs
        }
        out["conf_out"] = conf_path
        return out

    for attempt in range(attempts):
        scan = _scan_chunks(
            in_file, decode_kwargs.get("segment_bytes", DEFAULT_SEGMENT_BYTES)
        )
        if excluded:
            scan = scan.excluding(excluded)
        # Escalation rung 0 — locate-first when CRC verification cannot
        # protect this decode: the archive carries NO checksum lines, so
        # silent bitrot would pass straight into the output.  (When CRC
        # lines exist, the _scan_chunks above already read and verified
        # every chunk — even under the caller's verify_checksums=False,
        # which only skips decode_file's SECOND pass — so rot cannot
        # reach the erasure decode and locate would be pure overhead.)
        # RS_LOCATE=force engages it unconditionally, RS_LOCATE=off
        # never.  Prerequisites (systematic matrix, erasures <= p,
        # non-empty archive) fall back to the erasure ladder below; a
        # transient locate failure falls back too — the erasure ladder
        # owns the retry/degraded machinery.
        crc_off = not scan.crcs
        if (
            attempt == 0
            and scan.total_size > 0
            and (locate_mode == "force"
                 or (locate_mode == "auto" and crc_off))
            and _locate_context(scan) is not None
        ):
            from .gf_decode import UnlocatableError

            try:
                return locate_decode_file(
                    in_file, output, _scan=scan, **_locate_kwargs()
                )
            except UnlocatableError:
                raise  # never fall back to a silently-wrong erasure decode
            except (ValueError, OSError) as e:
                # Anything else locate trips over (transient I/O, subset
                # search cap, foreign-metadata corners) belongs to the
                # erasure ladder below — it owns the retry/reselect
                # machinery and raises the canonical errors.
                _obs_tracing.instant(
                    "locate_fallback", lane="retry",
                    error=type(e).__name__,
                )
        chosen, _ = _select_subset_retrying(scan)
        write_conf(
            conf_path,
            [os.path.basename(chunk_file_name(in_file, i)) for i in chosen],
        )
        kwargs = dict(decode_kwargs)
        # The scan above already CRC-verified exactly the chunks it
        # selected — don't pay a second full read in decode_file unless
        # the caller explicitly demanded verification.
        if kwargs.get("verify_checksums") is None:
            kwargs["verify_checksums"] = False
        try:
            out = decode_file(
                in_file, conf_path, output,
                _fallback_rows=[i for i in scan.healthy if i not in chosen],
                **kwargs,
            )
        except (ChunkIntegrityError, FileNotFoundError) as e:
            last = e
            if isinstance(e, ChunkIntegrityError):
                excluded.update(e.bad_chunks)
                # Survivors that failed AFTER the scan selected them
                # (TOCTOU opens, mid-stream read errors) are damage the
                # scan's state map missed — feed them to the health
                # plane under their own event so the fleet model sees
                # decode-discovered loss too.
                _obs_health.record_damage(
                    "decode_failure", in_file,
                    chunks=sorted(e.bad_chunks),
                    k=scan.k, p=scan.p, w=scan.w,
                    generation=scan.generation,
                )
            if attempt + 1 >= attempts:
                # Escalation's final rung: the reselect loop is
                # exhausted — survivors keep failing under the erasure
                # model.  One error-locating attempt (fresh scan, all
                # present chunks, syndrome-verified corrections) before
                # giving up; its own failure re-raises the LADDER's
                # error, the actionable one.
                if locate_mode != "off":
                    rescan = _scan_chunks(
                        in_file,
                        decode_kwargs.get(
                            "segment_bytes", DEFAULT_SEGMENT_BYTES
                        ),
                    )
                    if _locate_context(rescan) is not None:
                        try:
                            out = locate_decode_file(
                                in_file, output, _scan=rescan,
                                **_locate_kwargs()
                            )
                        except (ValueError, OSError):
                            raise e
                        _obs_metrics.counter(
                            "rs_degraded_decodes_total",
                            "decodes completed after survivor "
                            "reselection",
                        ).labels(stage="locate").inc()
                        return out
                raise
            _obs_tracing.instant(
                "degraded_reselect", lane="retry", attempt=attempt + 1,
                error=type(e).__name__,
            )
            continue
        if attempt:
            _obs_metrics.counter(
                "rs_degraded_decodes_total",
                "decodes completed after survivor reselection",
            ).labels(stage="reselect").inc()
        return out
    raise last  # unreachable: the last attempt re-raises above


# -- error-locating decode (gf_decode/, docs/RESILIENCE.md) -------------------
#
# The escalation ladder's final rung: silent bitrot — corruption in a
# chunk that passes no CRC — is invisible to the erasure path, which
# would propagate it into the output.  The locate path reads ALL present
# chunks, computes parity-check syndromes per segment (a plan-cached
# GF-GEMM, codec.syndrome), solves the key equation for error locations
# + magnitudes (gf_decode/bw.py), patches the located symbols in place,
# and only then runs the normal inverse-GEMM reconstruction.  Columns
# whose damage exceeds t = floor((p - erasures)/2) raise
# UnlocatableError — never a silently wrong output.


def _locate_mode() -> str:
    """RS_LOCATE knob: ``auto`` (default — engage when CRC verification
    is unavailable/off), ``off`` (never), ``force`` (locate-first even
    with CRCs)."""
    v = os.environ.get("RS_LOCATE", "auto").strip().lower()
    if v in ("0", "off", "no", "false"):
        return "off"
    if v in ("1", "force", "always"):
        return "force"
    return "auto"


def _locate_context(scan: "_ChunkScan"):
    """A gf_decode.LocateContext for this scan, or None when the locate
    prerequisites don't hold (non-systematic foreign matrix, more
    erasures than parity, zero-size archive) — callers fall back to the
    erasure-only ladder."""
    from .gf_decode import LocateContext

    if scan.chunk == 0 or len(scan.healthy) < scan.k:
        return None
    try:
        return LocateContext(
            scan.total_mat, scan.k, scan.p, scan.w, scan.healthy
        )
    except ValueError:
        return None


def _count_syndrome_verdict(verdict: str) -> None:
    _obs_metrics.counter(
        "rs_syndrome_checks_total",
        "per-segment syndrome-check verdicts (error-locating decode)",
    ).labels(verdict=verdict).inc()


def _count_located(n: int, w: int) -> None:
    if n:
        _obs_metrics.counter(
            "rs_located_errors_total",
            "symbol errors located and corrected by syndrome decode",
        ).labels(w=w).inc(n)


def _locate_segment_fixes(ctx, codec, seg, seg_cols, sym, off, cols, timer,
                          want_packed: bool = False):
    """One segment's syndrome check: dispatch S = check @ seg through the
    plan cache, locate on host, return ``(fixes, packed)`` — the verified
    corrections dict (column -> [(chunk, magnitude)]) plus, with
    ``want_packed`` under ``strategy="xor"``, the segment's
    :class:`..ops.xor_gemm.PackedOperand` so the caller's recovery GEMM
    reuses the pack stage this syndrome dispatch already paid
    (docs/XOR.md "Packed-operand reuse"; None otherwise).  Raises
    gf_decode.UnlocatableError past the t bound (counted before it
    propagates)."""
    from .gf_decode import UnlocatableError

    if ctx.r == 0:
        _count_syndrome_verdict("no_headroom")
        return {}, None
    with timer.phase("syndrome dispatch"), _dispatch_span(
        "syndrome", off, cols
    ):
        staged = codec.stage_segment(
            seg, cap=seg_cols // sym, sym=sym, out_rows=ctx.r
        )
        packed = codec.pack_operand(staged) if want_packed else None
        S = codec.syndrome(
            ctx.check, packed if packed is not None else staged
        )  # async
    with timer.phase("syndrome locate"):
        S_np = np.asarray(S).astype(np.int64)
        try:
            fixes = ctx.locate(S_np)
        except UnlocatableError:
            _count_syndrome_verdict("unlocatable")
            raise
    _count_syndrome_verdict("silent_bitrot" if fixes else "clean")
    _count_located(sum(len(v) for v in fixes.values()), ctx.w)
    return fixes, packed


def _syndrome_sweep(
    in_file: str,
    scan: "_ChunkScan",
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    timer: PhaseTimer | None = None,
) -> tuple[str, set[int], int]:
    """Read-only syndrome pre-check over an archive's present chunks (the
    scrub half of the locate path — ``rs scrub --syndrome``).

    Returns ``(verdict, located_chunks, symbol_errors, complete)``;
    verdict is one of ``clean`` / ``silent_bitrot`` (with the rotten
    chunk indices attributed — no CRCs involved) / ``unlocatable``
    (damage beyond the t bound somewhere; the sweep stops at the first
    such segment, so ``complete`` is False and ``located_chunks`` covers
    only the segments checked up to that point — a PARTIAL attribution,
    each entry individually verified) / ``no_headroom`` (erasures
    consumed the check, or the metadata is foreign/non-systematic —
    nothing checkable)."""
    from . import native
    from .gf_decode import UnlocatableError

    timer = timer or PhaseTimer(enabled=False)
    ctx = _locate_context(scan)
    if ctx is None or ctx.r == 0:
        _count_syndrome_verdict("no_headroom")
        return "no_headroom", set(), 0, True
    codec = RSCodec(scan.k, scan.p, w=scan.w, strategy=strategy)
    sym = scan.w // 8
    seg_cols = _segment_cols(scan.chunk, scan.k, segment_bytes)
    paths = [chunk_file_name(in_file, i) for i in ctx.survivors]
    fps = [open(p_, "rb") for p_ in paths]
    maps = [np.memmap(p_, dtype=np.uint8, mode="r") for p_ in paths]
    located: set[int] = set()
    errors = 0
    try:
        def stage(off: int, cols: int) -> np.ndarray:
            def attempt() -> np.ndarray:
                _faults.on_reads(paths, ctx.survivors, scope="scrub")
                return native.gather_rows(fps, off, cols, fallback_maps=maps)

            with timer.phase("stage segment (io)"):
                return _retry.default_policy().call(
                    attempt, op="syndrome_stage"
                )

        with SegmentPrefetcher(
            _segment_spans(scan.chunk, seg_cols), stage, depth=2
        ) as prefetch:
            for (off, cols), seg in prefetch:
                try:
                    fixes, _ = _locate_segment_fixes(
                        ctx, codec, seg, seg_cols, sym, off, cols, timer
                    )
                except UnlocatableError:
                    return "unlocatable", located, errors, False
                for col_fixes in fixes.values():
                    for chunk_idx, _mag in col_fixes:
                        located.add(chunk_idx)
                    errors += len(col_fixes)
    finally:
        for fp in fps:
            fp.close()
    return (
        ("silent_bitrot" if located else "clean"), located, errors, True
    )


@_observed_file_op("locate_decode")
def locate_decode_file(
    in_file: str,
    output: str | None = None,
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    conf_out: str | None = None,
    timer: PhaseTimer | None = None,
    _scan: "_ChunkScan | None" = None,
) -> str:
    """Rebuild ``in_file`` with error-LOCATING decode (``rs decode
    --locate``): no conf and no CRCs needed — silent bitrot in up to
    ``t = floor((p - missing)/2)`` chunks per symbol column is found,
    attributed and corrected from the code's own redundancy before the
    normal inverse-GEMM reconstruction runs.

    Reads ALL present full-size chunks; missing/truncated ones are
    erasures (classical trade: 2·errors + erasures <= p per column).
    Raises :class:`gf_decode.UnlocatableError` when any column's damage
    exceeds the bound — the archive may be wrong in ways the code cannot
    pin down, and fabricating bytes is worse than failing.  Semantics,
    miscorrection bounds and knobs: docs/RESILIENCE.md "Error location".
    """
    from . import native
    from .ops.gf import get_field

    timer = timer or PhaseTimer(enabled=False)
    t_start = time.perf_counter()
    # ``_scan`` (private, supplied by auto_decode_file's escalation
    # rungs): reuse the ladder's fresh scan instead of re-reading — and
    # re-CRC-ing, on checksummed archives — the whole chunk set.
    if _scan is not None:
        scan = _scan
    else:
        with timer.phase("scan chunks (io)"):
            scan = _scan_chunks(in_file, segment_bytes)
    if scan.total_size == 0:
        # Zero-size foreign archive: same contract as decode_file.
        _select_decodable_subset(scan)
        return _write_empty_atomic(output or in_file)
    ctx = _locate_context(scan)
    if ctx is None:
        from .gf_decode import is_systematic

        if not is_systematic(scan.total_mat, scan.k):
            raise ValueError(
                f"{in_file!r}: error-locating decode needs a systematic "
                "total matrix; this archive's metadata is foreign — use "
                "the erasure decoder (rs -d --auto)"
            )
        raise ValueError(
            f"only {len(scan.healthy)} healthy chunks of the k={scan.k} "
            f"needed (corrupt: {sorted(scan.bad)}, missing: "
            f"{scan.missing}) — past erasure recovery, locate cannot help"
        )
    k, p, w = scan.k, scan.p, scan.w
    sym = w // 8
    chunk = scan.chunk
    seg_cols = _segment_cols(chunk, k, segment_bytes)
    codec = RSCodec(k, p, w=w, strategy=strategy)
    gf = get_field(w)

    # Recovery GEMM for natives lost to ERASURE (located errors are
    # patched in place, so present natives pass straight through).  With
    # no native missing — the dominant silent-bitrot case — there is
    # nothing to invert: the k natives themselves are the (trivially
    # decodable) survivor set, and the subset search would be dead work
    # whose UndecidedSubsetError corner could fail an otherwise
    # recoverable archive.
    missing = [i for i in range(k) if i not in set(ctx.survivors)]
    if missing:
        with timer.phase("invert matrix"):
            chosen, inv = _select_decodable_subset(scan)
        dec_missing = np.asarray(inv).astype(gf.dtype)[missing]
    else:
        chosen, dec_missing = list(range(k)), None
    row_of = {c: i for i, c in enumerate(ctx.survivors)}
    chosen_rows = [row_of[c] for c in chosen]
    rec_row = {i: j for j, i in enumerate(missing)}

    if conf_out:
        write_conf(
            conf_out,
            [os.path.basename(chunk_file_name(in_file, i)) for i in chosen],
        )

    out_path = output or in_file
    tmp_path = out_path + ".rs_tmp"
    paths = [chunk_file_name(in_file, i) for i in ctx.survivors]
    fps = [open(p_, "rb") for p_ in paths]
    maps = [np.memmap(p_, dtype=np.uint8, mode="r") for p_ in paths]
    try:
        out_fp = open(tmp_path, "wb")
    except BaseException:
        for fp in fps:
            fp.close()
        raise

    def write_row(i: int, off: int, cols: int, row_bytes: np.ndarray):
        lo = i * chunk + off
        if lo >= scan.total_size:
            return
        hi = min(lo + cols, scan.total_size)
        out_fp.seek(lo)
        out_fp.write(np.ascontiguousarray(row_bytes[: hi - lo]).tobytes())
        _obs_metrics.counter(
            "rs_io_write_bytes_total",
            "bytes write by the staging-I/O layer",
        ).labels(call="stream_write").inc(hi - lo)

    def stage(off: int, cols: int) -> np.ndarray:
        def attempt() -> np.ndarray:
            _faults.on_reads(paths, ctx.survivors)
            return native.gather_rows(fps, off, cols, fallback_maps=maps)

        with timer.phase("stage segment (io)"):
            return _retry.default_policy().call(attempt, op="locate_stage")

    try:
        from .gf_decode import correct_segment

        # Sequential segment loop (prefetch overlaps the reads): the
        # host-side locate between the syndrome GEMM and the recovery
        # GEMM is a true pipeline barrier — np.asarray(S) both fences
        # the async staging H2D (so the later in-place patch cannot race
        # it) and hands the solver concrete syndromes.  Robustness path,
        # not the hot path; the write-behind lanes stay with decode_file.
        with SegmentPrefetcher(
            _segment_spans(chunk, seg_cols), stage, depth=pipeline_depth
        ) as prefetch:
            for (off, cols), seg in prefetch:
                # Packed-domain reuse (docs/XOR.md): under strategy="xor"
                # the syndrome dispatch packs the full survivor stack
                # into bit-planes; the recovery GEMM below consumes the
                # SAME rows, so it selects its survivor subset's planes
                # from the returned handle instead of round-tripping
                # through byte domain and re-packing — the pack stage
                # (~60% of xor wall) runs once per segment, not twice.
                fixes, packed = _locate_segment_fixes(
                    ctx, codec, seg, seg_cols, sym, off, cols, timer,
                    want_packed=dec_missing is not None,
                )
                if fixes:
                    segv = seg.view(np.uint16) if sym == 2 else seg
                    correct_segment(segv, fixes, row_of)
                    # The planes pre-date the in-place patch: a corrected
                    # segment re-stages below so the recovery GEMM reads
                    # the patched bytes, never the stale planes.
                    packed = None
                rec_np = None
                if dec_missing is not None:
                    with timer.phase("locate dispatch"), _dispatch_span(
                        "decode", off, cols
                    ):
                        if packed is not None:
                            rec = codec.decode(
                                dec_missing, packed.select(chosen_rows)
                            )
                        else:
                            staged = codec.stage_segment(
                                np.ascontiguousarray(seg[chosen_rows]),
                                cap=seg_cols // sym, sym=sym,
                                out_rows=dec_missing.shape[0],
                            )
                            rec = codec.decode(dec_missing, staged)
                    with timer.phase("decode compute"):
                        rec_np = np.asarray(rec)
                    if rec_np.dtype != np.uint8:
                        rec_np = np.ascontiguousarray(rec_np).view(np.uint8)
                with timer.phase("write output (io)"):
                    if scan.layout == "interleaved":
                        blk = np.empty((k, cols), dtype=np.uint8)
                        for i in range(k):
                            blk[i] = (
                                seg[row_of[i], :cols] if i in row_of
                                else rec_np[rec_row[i]][:cols]
                            )
                        _write_deinterleaved_block(
                            out_fp, off, cols, blk, sym, scan.total_size
                        )
                    else:
                        for i in range(k):
                            if i in row_of:
                                write_row(i, off, cols, seg[row_of[i]])
                        for i in missing:
                            write_row(i, off, cols, rec_np[rec_row[i]])
        out_fp.truncate(scan.total_size)
        out_fp.close()
        for fp in fps:
            fp.close()
        os.replace(tmp_path, out_path)
    except BaseException:
        if not out_fp.closed:
            out_fp.close()
        for fp in fps:
            if not fp.closed:
                fp.close()
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
        raise
    _obs_metrics.quantile(
        "rs_locate_decode_wall_seconds",
        "error-locating decode wall seconds (streaming quantiles)",
    ).observe(time.perf_counter() - t_start)
    return out_path


@_observed_file_op("repair")
def repair_file(
    in_file: str,
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    mesh=None,
    stripe_sharded: bool = False,
    timer: PhaseTimer | None = None,
) -> list[int]:
    """Regenerate every lost or corrupt chunk of an encode, in place.

    The reference can only restore the original *file* (decode.cu); a
    storage deployment also needs to heal the *archive* — rebuild missing
    chunk files, parity included, so future failures stay survivable.  Any
    target chunk row t is a GF-linear function of any decodable survivor
    set: ``row_t = T[t] . inv(T[surv])``, so all targets rebuild in ONE
    streamed GEMM over the survivors (natives and parity alike — no
    decode-then-re-encode double pass).

    Returns the list of chunk indices rebuilt ([] when the archive is
    already healthy).  Rebuilt chunks' CRC lines in .METADATA are refreshed
    when checksums are present.  Raises ValueError when fewer than k
    healthy chunks remain.

    With a ``mesh`` the rebuild GEMM fans out across devices exactly like
    encode/decode (archive repair is the same bulk-data shape — the
    reference runs its decode multi-GPU, decode.cu:335-378);
    ``stripe_sharded`` additionally shards the survivor/k axis.
    """
    timer = timer or PhaseTimer(enabled=False)
    if len(_mesh_processes(mesh)) > 1:
        return _count_repair_outcome(_repair_file_multiprocess(
            in_file, strategy=strategy, segment_bytes=segment_bytes,
            pipeline_depth=pipeline_depth, mesh=mesh,
            stripe_sharded=stripe_sharded, timer=timer,
        ), in_file)
    with timer.phase("scan chunks (io)"):
        scan = _scan_chunks(in_file, segment_bytes)
    targets = scan.unhealthy
    if not targets:
        return _count_repair_outcome([])
    if scan.chunk == 0:
        # Zero-size foreign archive: every chunk is the empty file, so
        # "rebuild" is recreating empties — no survivors read, no GEMM.
        # Still subject to the >=k-healthy contract (raises otherwise) so
        # repairability matches scan_file's decodable verdict: an archive
        # that cannot produce a valid k-chunk conf is not "repairable".
        _repair_select_or_fail(scan)
        for t in targets:
            _write_empty_atomic(chunk_file_name(in_file, t))
        if scan.crcs:
            rewrite_checksums(
                metadata_file_name(in_file),
                {**scan.crcs, **{t: 0 for t in targets}},  # crc32(b"") == 0
            )
        return _count_repair_outcome(targets, in_file, scan)
    with timer.phase("invert matrix"):
        chosen, inv = _repair_select_or_fail(scan)
    return _count_repair_outcome(_repair_streamed(
        in_file, scan, chosen, inv, strategy=strategy,
        segment_bytes=segment_bytes, pipeline_depth=pipeline_depth,
        mesh=mesh, stripe_sharded=stripe_sharded, timer=timer,
    ), in_file, scan)


def _count_repair_outcome(rebuilt: list[int], in_file: str | None = None,
                          scan: "_ChunkScan | None" = None) -> list[int]:
    """Count one archive's repair verdict (the scrub/repair loop's
    outcome series): ``rs_repair_outcomes_total{outcome}`` plus the
    rebuilt-chunk volume.  Identity on its argument so the return sites
    stay one-liners.  With ``in_file``, a non-empty rebuild also appends
    one ``rs_damage`` "repair" event so the health plane clears the
    rebuilt chunks from the archive's damage map."""
    _obs_metrics.counter(
        "rs_repair_outcomes_total", "archive repair outcomes"
    ).labels(outcome="rebuilt" if rebuilt else "healthy").inc()
    if rebuilt:
        _obs_metrics.counter(
            "rs_repair_chunks_rebuilt_total",
            "chunk files regenerated by repair",
        ).inc(len(rebuilt))
        if in_file is not None:
            _obs_health.record_damage(
                "repair", in_file, chunks=rebuilt,
                k=scan.k if scan else None, p=scan.p if scan else None,
                w=scan.w if scan else None,
                generation=scan.generation if scan else None,
            )
    return rebuilt


def _repair_select_or_fail(scan: "_ChunkScan"):
    """Survivor-subset selection for a single-archive repair, recording
    the failure to the health plane before it propagates: an archive
    repair cannot fix is the strongest at-risk signal the fleet model
    has (the repair-failure term in docs/HEALTH.md's risk formula)."""
    try:
        return _select_decodable_subset(scan)
    except ValueError as e:
        _obs_health.record_damage(
            "repair_failed", scan.in_file,
            k=scan.k, p=scan.p, w=scan.w, generation=scan.generation,
            verdict="undecided" if isinstance(e, UndecidedSubsetError)
            else "unrecoverable",
        )
        raise


def _repair_streamed(
    in_file: str,
    scan: "_ChunkScan",
    chosen: list[int],
    inv: np.ndarray,
    *,
    strategy: str,
    segment_bytes: int,
    pipeline_depth: int,
    mesh,
    stripe_sharded: bool,
    timer: PhaseTimer,
    fleet: FleetPipeline | None = None,
) -> list[int]:
    """The streaming rebuild half of :func:`repair_file`: given a completed
    scan and a chosen survivor subset with its inverse, regenerate every
    unhealthy chunk.  Split out so :func:`repair_fleet` can supply inverses
    computed in one batched on-device dispatch — and, with ``fleet``, ride
    the fleet's shared write-behind lane: this archive's promote/checksum
    commit queues behind its writes while the caller already streams the
    next archive's reads and dispatches."""
    from .ops.gf import get_field

    targets = scan.unhealthy
    with timer.phase("rebuild matrix"):
        gf = get_field(scan.w)
        mat = scan.total_mat.astype(gf.dtype)
        inv = np.asarray(inv).astype(gf.dtype)
        rebuild_mat = gf.matmul(mat[targets], inv)  # (targets, k)

    codec = RSCodec(
        scan.k, scan.p, w=scan.w, strategy=strategy,
        mesh=mesh, stripe_sharded=stripe_sharded,
    )
    sym = scan.w // 8
    chunk = scan.chunk
    seg_cols = _segment_cols(chunk, scan.k, segment_bytes)

    from . import native

    surv_fps = [open(chunk_file_name(in_file, i), "rb") for i in chosen]
    surv_maps = [
        np.memmap(chunk_file_name(in_file, i), dtype=np.uint8, mode="r")
        for i in chosen
    ]
    # Rebuild into temp files; atomically swap in only when every segment
    # landed (a failed repair must not destroy a corrupt-but-present chunk:
    # its surviving bytes may still matter to a different recovery tool).
    tmp_paths = {t: chunk_file_name(in_file, t) + ".rs_tmp" for t in targets}
    out_fps = {t: open(tmp_paths[t], "wb") for t in targets}
    new_crcs: dict[int, int] = {}

    def drain(tag, rebuilt):
        off, cols = tag
        with timer.phase("repair compute"):
            reb = np.asarray(rebuilt)
        if reb.dtype != np.uint8:
            reb = np.ascontiguousarray(reb).view(np.uint8)
        # CRC advance committed only after the write lands — the writer
        # lane may retry this whole drain (see _drain_parity).
        delta = (
            {t: crc32_of(reb[j], new_crcs.get(t, 0))
             for j, t in enumerate(targets)}
            if scan.crcs else None
        )
        with timer.phase("write chunks (io)"):
            native.scatter_write([out_fps[t] for t in targets], reb, off)
        if delta is not None:
            new_crcs.update(delta)

    surv_paths = [chunk_file_name(in_file, i) for i in chosen]

    def stage(off: int, cols: int) -> np.ndarray:
        # On the prefetch worker: survivor reads overlap rebuilt-chunk
        # writes.  Resilience read boundary (fault hook + transient-retry
        # into a fresh buffer), like the decode stage.
        def attempt() -> np.ndarray:
            _faults.on_reads(surv_paths, chosen)
            return native.gather_rows(
                surv_fps, off, cols, fallback_maps=surv_maps
            )

        with timer.phase("stage segment (io)"):
            return _retry.default_policy().call(attempt, op="repair_stage")

    def finalize() -> None:
        # Promote only after every rebuilt segment landed: standalone this
        # runs after the drain barrier; in a fleet it queues on the ordered
        # writer lane behind this archive's writes.
        for t in targets:
            out_fps[t].close()
        for fp in surv_fps:
            fp.close()
        for t in targets:
            os.replace(tmp_paths[t], chunk_file_name(in_file, t))
        if scan.crcs:
            with timer.phase("write metadata (io)"):
                rewrite_checksums(
                    metadata_file_name(in_file), {**scan.crcs, **new_crcs}
                )

    def cleanup() -> None:
        for fp in surv_fps:
            if not fp.closed:
                fp.close()
        for t, fp in out_fps.items():
            if not fp.closed:
                fp.close()
            if os.path.exists(tmp_paths[t]):
                os.unlink(tmp_paths[t])

    key = fleet.register(cleanup) if fleet is not None else None
    try:
        # Ordered write-behind lane: scatter_write's no-toolchain fallback
        # shares fp positions and the incremental CRC needs column order.
        with SegmentPrefetcher(
            _segment_spans(chunk, seg_cols), stage, depth=pipeline_depth
        ) as prefetch, _drain_ctx(fleet) as dex, AsyncWindow(
            pipeline_depth, drain, executor=dex
        ) as window:
            staging = _staging_ring(
                prefetch, codec, seg_cols, sym, pipeline_depth,
                out_rows=rebuild_mat.shape[0],
            )
            for (off, cols), seg in staging:
                with timer.phase("repair dispatch"), _dispatch_span(
                    "repair", off, cols
                ):
                    rebuilt = codec.decode(rebuild_mat, seg)  # async GEMM
                window.push((off, cols), rebuilt)
        if fleet is not None:
            fleet.commit(key, finalize)
        else:
            finalize()
    except BaseException:
        if fleet is None:
            cleanup()
        raise
    return targets


def _repair_file_multiprocess(
    in_file: str,
    *,
    strategy: str,
    segment_bytes: int,
    pipeline_depth: int,
    mesh,
    stripe_sharded: bool = False,
    timer: PhaseTimer,
) -> list[int]:
    """Multi-host archive repair over a process-spanning mesh (collective).

    The lead process scans chunk health (the CRC pass reads every present
    chunk once — doing it on all hosts would multiply that IO) and
    broadcasts the per-chunk state, so every process derives the same
    survivor subset and rebuild matrix deterministically.  The rebuild GEMM
    then streams exactly like multi-process encode: each host stages its
    block of the survivors (column span; survivor-row span too under
    ``stripe_sharded``, the wide-stripe composition), and pwrites its
    addressable shards of every rebuilt chunk into lead-pre-sized
    shared-filesystem temps that the lead atomically promotes — under
    stripe sharding the rebuilt output is psum-replicated, so stripe-row-0
    hosts write it.  Requirements: shared filesystem, w=8 or w=16.
    """
    import jax
    from jax.experimental import multihost_utils

    from .ops.gf import get_field
    from .parallel.mesh import COLS
    from .parallel.sharded import put_sharded, sharded_gf_matmul

    procs = _mesh_processes(mesh)
    lead = _is_lead(procs)

    # Health state: lead scans (CRC IO once, not once per host), peers get
    # the verdict as a (k+p,) array: 0 = missing, 1 = healthy, 2 = damaged.
    with timer.phase("scan chunks (io)"):
        meta = metadata_file_name(in_file)
        meta_obj = read_archive_meta(meta)
        total_size, p, k = (
            meta_obj.total_size, meta_obj.parity_num, meta_obj.native_num
        )
        total_mat, w, crcs = meta_obj.total_mat, meta_obj.w, meta_obj.crcs
        _check_gfwidth(w, meta)
        sym = w // 8
        if total_mat is None:
            total_mat = _regenerate_total_matrix(p, k, w)
        state = np.zeros(k + p, dtype=np.int32)
        scan_err: Exception | None = None
        if lead:
            # A lead-side scan failure must reach the peers as an error,
            # not leave them wedged at the broadcast: sentinel the whole
            # state array (-1 is outside the 0/1/2 health encoding), then
            # raise in lockstep after the collective.
            try:
                scan = _scan_chunks(in_file, segment_bytes)
                state[scan.healthy] = 1
                state[sorted(scan.bad)] = 2
            except Exception as e:
                scan_err = e
                state[:] = -1
        state = np.asarray(
            multihost_utils.broadcast_one_to_all(state, is_source=lead)
        )
        if (state < 0).any():
            if scan_err is not None:
                raise scan_err
            raise RuntimeError(
                "chunk scan failed on the lead process "
                f"(process {procs[0]}); see its log for the cause"
            )
    healthy = [int(i) for i in np.flatnonzero(state == 1)]
    bad = {
        int(i): chunk_file_name(in_file, int(i))
        for i in np.flatnonzero(state == 2)
    }
    # Repair is chunk-layout-agnostic (column-wise linear algebra over
    # whole chunk files) — only the expected chunk LENGTH differs.
    chunk = chunk_size_for_layout(total_size, k, sym, meta_obj.layout)
    scan_view = _ChunkScan(
        in_file, total_size, p, k, total_mat, w, crcs, chunk, healthy,
        bad, layout=meta_obj.layout, generation=meta_obj.generation,
    )
    targets = scan_view.unhealthy
    if not targets:
        return []
    if chunk == 0:
        # Zero-size foreign archive (see repair_file): the lead recreates
        # the empty chunks; all processes leave in lockstep.  Same
        # >=k-healthy contract as the general path (raises everywhere —
        # all processes share the broadcast health state).
        _select_decodable_subset(scan_view)
        if lead:
            for t in targets:
                _write_empty_atomic(chunk_file_name(in_file, t))
            if crcs:
                rewrite_checksums(
                    meta, {**crcs, **{t: 0 for t in targets}}
                )
        multihost_utils.sync_global_devices("rs_repair_promoted")
        return targets

    with timer.phase("invert matrix"):
        chosen, inv = _select_decodable_subset(scan_view)
        gf = get_field(w)
        mat = total_mat.astype(gf.dtype)
        rebuild_mat = gf.matmul(mat[targets], inv)  # (targets, k)

    codec = RSCodec(
        k, p, w=w, strategy=strategy, mesh=mesh,
        stripe_sharded=stripe_sharded,
    )
    seg_cols = _segment_cols(chunk, k, segment_bytes)
    cols_size = mesh.shape[COLS]
    in_sharding, writes_output = _stripe_io_roles(mesh, stripe_sharded)
    tmp_paths = {t: chunk_file_name(in_file, t) + ".rs_tmp" for t in targets}
    new_crcs: dict[int, int] = {}

    try:
        if lead:
            for t in targets:
                with open(tmp_paths[t], "wb") as fp:
                    fp.truncate(chunk)
        multihost_utils.sync_global_devices("rs_repair_tmps_created")

        surv_fps = [
            open(chunk_file_name(in_file, i), "rb") for i in chosen
        ]
        surv_maps = [
            np.memmap(chunk_file_name(in_file, i), dtype=np.uint8, mode="r")
            for i in chosen
        ]
        out_fps = {t: open(tmp_paths[t], "r+b") for t in targets}
        try:
            stage = _make_padded_stage(
                surv_fps, surv_maps, chunk, cols_size, in_sharding, k,
                timer, sym,
            )

            def drain(tag, rebuilt_sharded) -> None:
                off, cols = tag
                if not writes_output:
                    with timer.phase("repair compute"):
                        jax.block_until_ready(rebuilt_sharded)
                    return
                with timer.phase("repair compute"):
                    shards = _trimmed_shards(rebuilt_sharded, cols, sym)
                with timer.phase("write chunks (io)"):
                    for col0, data in shards:
                        for j, t in enumerate(targets):
                            os.pwrite(
                                out_fps[t].fileno(),
                                data[j].tobytes(),
                                off + col0,
                            )

            # Out-of-order write-behind (offset-addressed pwrites into the
            # lead-pre-sized temps; CRCs recomputed from files afterwards).
            with SegmentPrefetcher(
                _segment_spans(chunk, seg_cols), stage, depth=pipeline_depth
            ) as prefetch, _drain_ctx(None, ordered=False) as dex, AsyncWindow(
                pipeline_depth, drain, executor=dex
            ) as window:
                for (off, cols), local_seg in prefetch:
                    with timer.phase("repair dispatch"), _dispatch_span(
                        "repair", off, cols
                    ):
                        Bd = put_sharded(local_seg, mesh, stripe_sharded)
                        rebuilt = sharded_gf_matmul(
                            np.asarray(rebuild_mat), Bd,
                            mesh=mesh, w=w, strategy=codec.strategy,
                            stripe_sharded=stripe_sharded,
                        )
                    window.push((off, cols), rebuilt)
        finally:
            for fp in surv_fps:
                fp.close()
            for fp in out_fps.values():
                fp.close()
        multihost_utils.sync_global_devices("rs_repair_written")

        if lead:
            if crcs:
                with timer.phase("write metadata (io)"):
                    for t in targets:
                        mm = np.memmap(tmp_paths[t], dtype=np.uint8, mode="r")
                        new_crcs[t] = chunk_crc32(mm, chunk, segment_bytes)
            for t in targets:
                os.replace(tmp_paths[t], chunk_file_name(in_file, t))
            if crcs:
                with timer.phase("write metadata (io)"):
                    rewrite_checksums(meta, {**crcs, **new_crcs})
    except BaseException:
        _unlink_shared_tmps(tmp_paths.values())
        raise
    multihost_utils.sync_global_devices("rs_repair_promoted")
    return targets


@_observed_file_op("repair_fleet")
def repair_fleet(
    files,
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    pipeline_depth: int = 2,
    timer: PhaseTimer | None = None,
) -> dict[str, list[int]]:
    """Scrub-and-repair many archives in one pass (fleet scrubbing).

    The reference's dormant GPU inverter (matrix.cu:667-744) and its
    blocked-inversion experiment (decode-gj.cu:1059-1201) pointed at
    putting matrix inversion on the device; the shape where that
    parallelism actually occurs in a storage system is ACROSS archives — a
    periodic scrub finds many damaged archives, each needing its own
    survivor-subset inverse.  This entry point scans every archive, solves
    all the k x k survivor inversions of each (k, w) config in ONE batched
    on-device dispatch (:func:`.ops.inverse.invert_matrix_jax_batch`),
    host-verifies each inverse with a single GF matmul (falling back to
    the host inverter on any mismatch or singular flag), then streams each
    archive's rebuild exactly like :func:`repair_file`.

    All-or-nothing validation: every archive is scanned and its inverse
    solved BEFORE any rebuild is written; if any archive is unscannable or
    unrecoverable, raises ValueError naming every such archive and repairs
    nothing.  Single-host (no mesh): fleet parallelism batches the
    inversions, and the per-archive rebuild pipelines *interleave* through
    one shared write-behind lane (parallel/io_executor.py): archive j+1's
    survivor reads and GEMM dispatches overlap archive j's D2H + chunk
    writes, with each archive's promote/CRC commit queued behind its own
    writes.  The shared plan cache means the interleave adds zero
    compiles; ``RS_IO_WRITERS=0`` restores the fully sequential rebuild.

    Returns ``{file: [rebuilt chunk indices]}`` ([] for healthy archives).
    """
    from .ops.gf import get_field
    from .ops.inverse import invert_matrix_jax_batch, mds_nopivot_order

    timer = timer or PhaseTimer(enabled=False)
    files = list(files)
    errors: dict[str, str] = {}
    with timer.phase("scan chunks (io)"):
        scans: dict[str, _ChunkScan] = {}
        for f in files:
            try:
                scans[f] = _scan_chunks(f, segment_bytes)
            except Exception as e:
                errors[f] = f"{type(e).__name__}: {e}"
    # First-choice survivor subsets, grouped by (k, w) so each group is one
    # stacked (b, k, k) inversion dispatch.  ``healthy`` is in chunk-index
    # order, so healthy[:k] is exactly the natives-first candidate
    # _select_decodable_subset would try first (the near-always-invertible
    # common case for Vandermonde/Cauchy).
    chosen_inv: dict[str, tuple[list[int], np.ndarray]] = {}
    groups: dict[tuple[int, int], list[str]] = {}
    for f, s in scans.items():
        if not s.unhealthy:
            continue
        if s.chunk == 0:
            # Zero-size archives skip inversion but NOT validation: an
            # unrecoverable one must surface here, before any rebuild (the
            # all-or-nothing contract), with the same >=k-healthy rule
            # repair_file applies.
            try:
                _select_decodable_subset(s)
            except ValueError as e:
                errors[f] = str(e)
            continue
        if len(s.healthy) < s.k:
            errors[f] = (
                f"only {len(s.healthy)} healthy chunks of the k={s.k} needed "
                f"(corrupt: {sorted(s.bad)}, missing: {s.missing})"
            )
            continue
        # Re-pass reuse: a fleet sweeping the same archives (the scrub ->
        # repair loop) skips the batched inversion dispatch for every
        # archive whose pinned subset is still healthy at this generation.
        hit = _cached_subset(s)
        if hit is not None:
            chosen_inv[f] = hit
            continue
        groups.setdefault((s.k, s.w), []).append(f)
    with timer.phase("invert matrices (batched)"):
        from .utils.backend import tpu_devices_present

        for (k, w), group in groups.items():
            gf = get_field(w)
            min_batch = _device_invert_min_batch_tpu(k)
            if tpu_devices_present() and (
                min_batch is None or len(group) < min_batch
            ):
                # Measured routing — see _device_invert_min_batch_tpu for
                # the k x batch grid and its capture citation.
                for f in group:
                    try:
                        chosen_inv[f] = _select_decodable_subset(scans[f])
                    except ValueError as e:
                        errors[f] = str(e)
                continue
            # Scan-free elimination (pivot=False): with each surviving
            # native's identity row placed AT its own position
            # (mds_nopivot_order), pivoting is only ever needed inside the
            # tiny parity Schur complement — rare, flagged by ok=False,
            # and re-solved through the host search below.  Every inverse
            # is verified before use either way.  On TPU the no-pivot
            # times are indistinguishable from the pivoting ones
            # (inverse_nopivot_tpu_20260801T*: the elimination scan, not
            # the pivot search, is the cost), so this stays the dispatch
            # for its CPU win (1.25x, builder smoke) and simpler kernel.
            ordered = {
                f: mds_nopivot_order(scans[f].healthy[:k], k) for f in group
            }
            subs = [
                scans[f].total_mat[ordered[f]].astype(gf.dtype)
                for f in group
            ]
            invs, oks = invert_matrix_jax_batch(np.stack(subs), w, pivot=False)
            invs = np.asarray(invs).astype(gf.dtype)
            oks = np.asarray(oks)
            eye = np.eye(k, dtype=gf.dtype)
            for j, f in enumerate(group):
                verified = bool(oks[j]) and np.array_equal(
                    gf.matmul(subs[j], invs[j]), eye
                )
                if verified:
                    chosen_inv[f] = (ordered[f], invs[j])
                    _remember_subset(scans[f], ordered[f], invs[j])
                    continue
                # Singular first candidate (or a device-inverse mismatch —
                # never observed, but a wrong inverse must not write wrong
                # chunk bytes): the host search tries the other subsets.
                try:
                    chosen_inv[f] = _select_decodable_subset(scans[f])
                except ValueError as e:
                    errors[f] = str(e)
    if errors:
        _obs_metrics.counter(
            "rs_repair_outcomes_total", "archive repair outcomes"
        ).labels(outcome="unrecoverable").inc(len(errors))
        for f in sorted(errors):
            s = scans[f]
            _obs_health.record_damage(
                "repair_failed", f, k=s.k, p=s.p, w=s.w,
                generation=s.generation, verdict="unrecoverable",
            )
        raise ValueError(
            "unrecoverable archives (nothing repaired): "
            + "; ".join(f"{f}: {msg}" for f, msg in sorted(errors.items()))
        )
    # Fleet scheduler: one shared ordered write-behind lane; each archive
    # commits behind its own writes while the next archive's reads and
    # dispatches already stream on this thread.
    results: dict[str, list[int]] = {}
    with _fleet_lane() as pipe:
        for f in files:
            s = scans[f]
            if not s.unhealthy:
                results[f] = _count_repair_outcome([])
            elif s.chunk == 0:
                # Zero-size archives take repair_file's empty-rebuild
                # path (no streamed writes to overlap).
                results[f] = repair_file(
                    f, strategy=strategy, segment_bytes=segment_bytes,
                    pipeline_depth=pipeline_depth, timer=timer,
                )
            else:
                chosen, inv = chosen_inv[f]
                results[f] = _count_repair_outcome(_repair_streamed(
                    f, s, chosen, inv, strategy=strategy,
                    segment_bytes=segment_bytes,
                    pipeline_depth=pipeline_depth,
                    mesh=None, stripe_sharded=False, timer=timer,
                    fleet=pipe,
                ), f, s)
    return results


# -- partial-stripe updates and append-mode encoding (update/) ---------------
#
# RS linearity: parity' = parity ⊕ E·Δ, so a byte-range edit moves only
# its touched segment columns, and an append (interleaved layout) only
# the tail column block — docs/UPDATE.md.  Both ops are crash-atomic:
# undo journal before any in-place byte, atomic generation-bumping
# .METADATA rewrite as the commit point, rollback on failure or at the
# next open (recover_archive).


@_observed_file_op("update")
def update_file(
    file_name: str,
    at: int,
    data=None,
    *,
    src: str | None = None,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    timer: PhaseTimer | None = None,
) -> dict:
    """Overwrite bytes [at, at+len) of the archived file in place —
    ``rs update ARCHIVE --at OFF --in DELTA``.

    Only the affected segment columns are read and rewritten: Δ = new ⊕
    old per touched native column, ``E·Δ`` dispatched as a plan-cached
    GF-GEMM (op="update" — it reuses the warm encode executable), parity
    XOR-patched through an ordered pwrite lane, per-chunk CRC lines fixed
    by seekable crc32-combine (no full-chunk re-hash), and the metadata
    committed atomically with a generation bump.  Pass the new bytes as
    ``data`` or a file path as ``src``.  Returns the op summary dict
    (bytes, segments, chunks_touched, generation).  Works on both chunk
    layouts; requires the touched chunks healthy (repair first
    otherwise).
    """
    from .update import apply_update

    out = apply_update(
        file_name, at, data, src=src, strategy=strategy,
        segment_bytes=segment_bytes, timer=timer,
    )
    # Generation moved past the last verified scrub: the health plane
    # treats the archive as scrub-stale until it is re-scanned.
    _obs_health.record_damage(
        "update", file_name, generation=out.get("generation"),
    )
    return out


@_observed_file_op("append")
def append_file(
    file_name: str,
    data=None,
    *,
    src: str | None = None,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    timer: PhaseTimer | None = None,
) -> dict:
    """Grow the archived file by the payload bytes — ``rs append ARCHIVE
    --in DATA``.

    Interleaved-layout archives (``rs -e ... --layout interleaved``)
    extend every chunk by just the tail column block: cold columns are
    never read or written, and only the tail segment's parity is
    regenerated.  Row-layout (reference) archives accept appends bounded
    by their tail-padding slack (a larger chunk size would re-stripe the
    whole file).  Torn appends are detected and rolled back at the next
    open (undo journal + metadata generation).  Returns the op summary
    dict with the new ``total_size``.
    """
    from .update import apply_append

    out = apply_append(
        file_name, data, src=src, strategy=strategy,
        segment_bytes=segment_bytes, timer=timer,
    )
    _obs_health.record_damage(
        "update", file_name, generation=out.get("generation"),
    )
    return out


@_observed_file_op("update_many")
def update_file_many(
    file_name: str,
    edits,
    *,
    strategy: str = "auto",
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    timer: PhaseTimer | None = None,
    group_edits: int | None = None,
    group_tag: str | None = None,
    stage_hook=None,
) -> dict:
    """Apply an ORDERED batch of edits/appends to one archive under
    group commit — ``rs update ARCHIVE --edits FILE`` and the daemon's
    ``/update`` write combining (docs/UPDATE.md "Group commit").

    ``edits`` is a list of dicts: ``{"op": "update", "at": OFF,
    "data": bytes | "src": path}`` or ``{"op": "append", "data"/"src":
    ...}``.  Semantically byte-identical to applying the batch one
    :func:`update_file` / :func:`append_file` call at a time (later
    edits win overlapping bytes; an edit may target bytes an earlier
    append in the same batch created) — but the batch merges into
    touched column windows with ONE stacked ``E·Δ`` GEMM per window
    block, and commits under ONE journal fsync chain, ONE ``.METADATA``
    rewrite and ONE generation bump per window group (all-or-nothing:
    a torn group rolls back every edit via the journal; no edit is
    acknowledged before its group is durable).  ``RS_UPDATE_GROUP_WINDOW``
    caps edits per group (larger batches split into consecutive groups);
    ``group_edits`` overrides it for this call — pass ``len(edits)`` to
    force the whole batch into ONE all-or-nothing group.  Returns the
    aggregate summary dict (``edits``, ``groups``, ``windows``,
    ``segments``, ``chunks_touched``, ``total_size``, ``generation``).
    ``group_tag`` / ``stage_hook`` are the daemon write combiner's
    lifecycle joins (group id in span + summary; ``device_done`` stage
    callback — update/group.py).
    """
    from .update import apply_update_many

    out = apply_update_many(
        file_name, edits, strategy=strategy,
        segment_bytes=segment_bytes, timer=timer, group_edits=group_edits,
        group_tag=group_tag, stage_hook=stage_hook,
    )
    _obs_health.record_damage(
        "update", file_name, generation=out.get("generation"),
    )
    return out


def recover_archive(file_name: str) -> str:
    """Resolve a pending update/append journal next to ``file_name``
    (run automatically at the top of every update/append; exposed for
    ``rs update --recover`` and post-crash decode hygiene).  Returns
    ``none`` / ``stale_discarded`` / ``invalid_discarded`` /
    ``rolled_back``."""
    from .update import recover

    return recover(file_name)


# -- object-store façade (store/) ---------------------------------------------
#
# Many small objects share erasure-coded stripe archives instead of
# paying per-object metadata/chunks/journal (docs/STORE.md): a durable
# object index maps key -> (archive, byte range, CRC32), committed
# crash-atomically alongside the archive metadata it references.  PUT
# rides the group-commit append lane, GET decodes only the object's
# touched column windows, DELETE tombstones + zeroes via delta-parity,
# and compaction retires dead-heavy archives all-or-nothing.


@_observed_file_op("object_put")
def put_object(
    root: str,
    bucket: str,
    key: str,
    data=None,
    *,
    src: str | None = None,
    create: bool = True,
    k: int | None = None,
    p: int | None = None,
    w: int | None = None,
    stripe_bytes: int | None = None,
) -> dict:
    """Store one object under ``key`` in ``bucket`` — ``rs object put``.

    The payload comes as ``data`` bytes or a ``src`` file path.  The
    bucket is created on first use (``create=False`` refuses instead);
    the shape knobs apply only at creation — an existing bucket's
    manifest wins.  Returns the location dict (``arc``, ``at``, ``len``,
    ``crc``, ``gen``).  For PUT bursts, :func:`put_objects` commits the
    whole batch under ONE group-committed stripe append + ONE index
    fsync (the daemon's ``/o/`` write combining calls it)."""
    if (data is None) == (src is None):
        raise ValueError("pass exactly one of data= or src=")
    if src is not None:
        with open(src, "rb") as fp:
            data = fp.read()
    return put_objects(root, bucket, [(key, data)],
                       create=create, k=k, p=p, w=w,
                       stripe_bytes=stripe_bytes)[0]


def put_objects(
    root: str,
    bucket: str,
    items,
    *,
    create: bool = True,
    k: int | None = None,
    p: int | None = None,
    w: int | None = None,
    stripe_bytes: int | None = None,
) -> list[dict]:
    """Batch PUT: an ordered list of ``(key, bytes)`` committed as one
    group (one journal fsync chain, one metadata rewrite, one index
    fsync) — all-or-nothing; later duplicates win."""
    from . import store as _store

    b = _store.open_bucket(root, bucket, create=create, k=k, p=p, w=w,
                           stripe_bytes=stripe_bytes)
    return b.put_many(items)


@_observed_file_op("object_get")
def get_object(root: str, bucket: str, key: str) -> bytes:
    """Read one object's bytes — ``rs object get``.  Reconstructs ONLY
    the object's byte range (touched column windows; degraded decode
    when a native chunk is damaged), verified against the object's own
    CRC32 from the index — never silently wrong."""
    from . import store as _store

    return _store.open_bucket(root, bucket).get(key)


@_observed_file_op("object_delete")
def delete_object(root: str, bucket: str, key: str) -> dict:
    """Delete one object — ``rs object rm``: durable tombstone first
    (the commit point), then the dead range is zeroed through the
    delta-parity patch lane; space returns at the next compaction."""
    from . import store as _store

    return _store.open_bucket(root, bucket).delete(key)


def list_objects(root: str, bucket: str, *,
                 prefix: str = "") -> list[dict]:
    """Live objects in the bucket (tombstoned keys excluded), sorted by
    key — ``rs object ls``.  ``prefix`` narrows to keys starting with
    it; for bounded pages over a huge bucket use
    :func:`list_objects_page`."""
    from . import store as _store

    return _store.open_bucket(root, bucket).list_objects(prefix=prefix)


def list_objects_page(root: str, bucket: str, *, prefix: str = "",
                      limit: int = 0, cursor: str | None = None) -> dict:
    """One bounded page of live objects — ``rs object ls --limit``:
    ``{"objects", "truncated", "next"}`` where ``next`` is the opaque
    cursor resuming after the page's last key (None on the final
    page).  ``limit <= 0`` uses ``RS_STORE_LIST_LIMIT`` semantics from
    the caller (here: no bound)."""
    from . import store as _store

    return _store.open_bucket(root, bucket).list_page(
        prefix=prefix, limit=limit, cursor=cursor)


def stat_object(root: str, bucket: str, key: str) -> dict:
    """One object's index entry (archive, range, CRC, generation pin)
    — ``rs object stat``."""
    from . import store as _store

    return _store.open_bucket(root, bucket).stat(key)


@_observed_file_op("object_compact")
def compact_bucket(root: str, bucket: str, *, force: bool = False) -> dict:
    """Rewrite live objects out of dead-heavy sealed archives and
    retire them all-or-nothing — ``rs object compact``
    (``RS_STORE_COMPACT_DEAD_FRAC`` sets the trigger; ``force=True``
    compacts any sealed archive with dead bytes)."""
    from . import store as _store

    return _store.open_bucket(root, bucket).compact(force=force)


@_observed_file_op("scan")
def scan_file(
    in_file: str,
    *,
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    syndrome: bool = False,
) -> dict:
    """Read-only archive health report (the scrubbing half of repair).

    Returns ``{"k", "p", "w", "checksummed", "healthy", "corrupt",
    "missing", "decodable"}`` — ``corrupt`` lists present-but-damaged
    chunks (truncated or CRC-failing), ``missing`` absent ones, and
    ``decodable`` means the original file can be rebuilt (>= k healthy
    chunks with an invertible subset) — which equally means every damaged
    chunk is repairable.  ``decodable`` is tri-state: ``True`` / ``False``
    / ``"unknown"`` when the subset search hit its cap without a verdict
    (only reachable with pathological non-MDS matrices).

    ``syndrome=True`` (``rs scrub --syndrome``) adds the error-locating
    pre-check: parity-check syndromes over every present chunk attribute
    SILENT bitrot — corruption no size check and no CRC would catch — to
    its chunk index (``state="silent_bitrot"``), without reading a single
    checksum.  Located chunks are demoted from ``healthy`` into
    ``corrupt`` and ``decodable`` is re-derived; a verdict of
    ``unlocatable`` (per-column damage beyond t = floor((p-missing)/2))
    degrades ``decodable`` to ``"unknown"`` — the erasure math could
    still rebuild *bytes*, but nothing proves they'd be the right ones.
    The report gains ``{"syndrome": {"verdict", "silent_bitrot",
    "symbol_errors", "complete"}}`` — ``complete`` is False when the
    sweep stopped at an unlocatable segment, in which case
    ``silent_bitrot`` is a verified-but-PARTIAL attribution (and is not
    merged into ``corrupt``).
    """
    scan = _scan_chunks(in_file, segment_bytes)
    syn_report = None
    if syndrome:
        verdict, located, nerr, complete = _syndrome_sweep(
            in_file, scan, segment_bytes=segment_bytes
        )
        syn_report = {
            "verdict": verdict,
            "silent_bitrot": sorted(located),
            "symbol_errors": nerr,
            "complete": complete,
        }
        # Demote located chunks only on a COMPLETE attribution: the
        # unlocatable sweep stops at the first over-t segment, so its
        # located set covers a prefix of the archive — each entry is
        # individually verified, but presenting it as the damage set
        # (and feeding it to repair planning) would understate the rot.
        if located and verdict == "silent_bitrot":
            _obs_metrics.counter(
                "rs_scrub_chunks_total", "chunk verdicts from archive scans"
            ).labels(state="silent_bitrot").inc(len(located))
            scan = scan.excluding(
                {i: chunk_file_name(in_file, i) for i in located}
            )
        if located or verdict == "unlocatable":
            # Health plane: every located chunk is an individually
            # verified attribution (partial sweeps included — the
            # verdict field carries the completeness caveat).
            _obs_health.record_damage(
                "syndrome", in_file, chunks=sorted(located),
                k=scan.k, p=scan.p, w=scan.w,
                generation=scan.generation, verdict=verdict,
            )
    try:
        _select_decodable_subset(scan)
        ok = True
    except UndecidedSubsetError:
        ok = "unknown"
    except ValueError:
        ok = False
    if syn_report is not None and syn_report["verdict"] == "unlocatable":
        # Erasure-decodable maybe, but bytes unprovable: not True.
        ok = "unknown" if ok is True else ok
    _obs_metrics.counter(
        "rs_scrub_verdicts_total", "scan_file decodability verdicts"
    ).labels(decodable=str(ok)).inc()
    from .update.journal import journal_path

    report = {
        "k": scan.k,
        "p": scan.p,
        "w": scan.w,
        "checksummed": bool(scan.crcs),
        "layout": scan.layout,            # chunk layout (docs/UPDATE.md)
        "generation": scan.generation,    # update/append commit counter
        # A pending journal means the last update/append tore mid-patch:
        # recover_archive (or the next update/append) rolls it back.
        # Scrub REPORTS it — a read-only scan must not mutate the archive.
        "pending_journal": os.path.exists(journal_path(in_file)),
        "healthy": scan.healthy,
        "corrupt": sorted(scan.bad),  # present but truncated or CRC-failing
        "missing": scan.missing,      # absent files
        "decodable": ok,              # decodable implies repairable (one GEMM)
    }
    if syn_report is not None:
        report["syndrome"] = syn_report
    return report
