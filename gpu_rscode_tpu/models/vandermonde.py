"""Encoding-matrix generation (coding "models").

Capability parity with the reference's generator (``matrix.cu:752-759``:
``EM[i][j] = gf_pow((j+1) % 256, i)`` launched from ``encode.cu:134-141``,
CPU twin ``cpu-rs.c:446-463`` which stacks the identity on top).

The reference generates the Vandermonde block on the GPU with one thread per
entry; at (n-k) x k <= a few KB that is pure launch overhead, so the TPU build
generates it on host NumPy and ships it to the device as a constant folded
into the jitted encode (XLA hoists it).  A Cauchy generator is added as a
second coding model: unlike the plain (non-systematic-corrected) Vandermonde
the reference uses, every square submatrix of a Cauchy matrix is invertible,
which guarantees decodability for ANY k-subset of chunks.
"""

from __future__ import annotations

import numpy as np

from ..ops.gf import GaloisField, get_field


def vandermonde_matrix(parity_num: int, native_num: int, gf: GaloisField | None = None) -> np.ndarray:
    """(parity_num, native_num) Vandermonde block: ``V[i, j] = (j+1)^i``.

    Bit-identical to the reference's ``gen_encoding_matrix``
    (``matrix.cu:752-759``), including the ``(j+1) % size`` wrap.
    """
    gf = gf or get_field(8)
    j = (np.arange(native_num, dtype=np.int64) + 1) % gf.size
    i = np.arange(parity_num, dtype=np.int64)
    return gf.pow(j[None, :], i[:, None]).astype(gf.dtype)


def total_matrix(parity_num: int, native_num: int, gf: GaloisField | None = None) -> np.ndarray:
    """(native_num + parity_num, native_num) total encoding matrix ``[I; V]``.

    Identity block first, Vandermonde block below — the exact row order the
    reference writes to .METADATA (``encode.cu:61-101``) and the CPU oracle
    regenerates deterministically (``cpu-rs.c:459-463``).
    """
    gf = gf or get_field(8)
    eye = np.eye(native_num, dtype=gf.dtype)
    return np.concatenate([eye, vandermonde_matrix(parity_num, native_num, gf)], axis=0)


def cauchy_matrix(parity_num: int, native_num: int, gf: GaloisField | None = None) -> np.ndarray:
    """(parity_num, native_num) Cauchy block: ``C[i, j] = 1 / (x_i ^ y_j)``
    with ``x_i = native_num + i``, ``y_j = j``.

    Every square submatrix of ``[I; C]`` is invertible, so any k survivors
    decode — a guarantee the reference's Vandermonde-over-GF construction does
    not actually provide for all (n, k).  Requires ``n <= 2^w``.
    """
    gf = gf or get_field(8)
    if native_num + parity_num > gf.size:
        raise ValueError(f"n = {native_num + parity_num} exceeds field size {gf.size}")
    x = np.arange(native_num, native_num + parity_num, dtype=np.int64)
    y = np.arange(native_num, dtype=np.int64)
    return gf.inv(x[:, None] ^ y[None, :]).astype(gf.dtype)


GENERATORS = {
    "vandermonde": vandermonde_matrix,
    "cauchy": cauchy_matrix,
}


def generator_matrix(kind: str, parity_num: int, native_num: int, gf: GaloisField | None = None) -> np.ndarray:
    try:
        fn = GENERATORS[kind]
    except KeyError:
        raise ValueError(f"unknown generator {kind!r}; choose from {sorted(GENERATORS)}") from None
    return fn(parity_num, native_num, gf)
