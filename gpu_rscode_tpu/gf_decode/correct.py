"""Corrected decode — locate state per archive + in-place segment patch.

:class:`LocateContext` packages everything the file layer needs to run
error-locating decode over one archive: the (erasure-reduced) parity
check restricted to the surviving rows — the operand of the plan-cached
syndrome GEMM (:meth:`..codec.RSCodec.syndrome`) — the error budget
``t``, the BM fast-path points when the generator is the reference's
Vandermonde, and the row maps between "position in the gathered survivor
stack" and chunk index.

:func:`correct_segment` applies a verified correction set to the host
segment IN PLACE (symbol-wise XOR of the located magnitudes) before the
caller hands the patched rows to the normal inverse-GEMM reconstruction.
"""

from __future__ import annotations

import numpy as np

from ..ops.gf import get_field
from .bw import locate_segment
from .syndrome import (
    erasure_reduced_check,
    parity_check_matrix,
    vandermonde_points,
)


class LocateContext:
    """Per-archive error-locating state.

    ``survivors`` are the chunk indices whose files are present and
    full-size, in the exact order the file layer stacks their rows into
    gathered segments; the complement (missing/truncated chunks) are
    erasures, projected out of the check by :func:`erasure_reduced_check`.

    Attributes:

    ``check``
        (r, n_surv) reduced parity check restricted to survivor rows —
        what the syndrome GEMM dispatches against gathered segments
        (r = p - nu).  ``None``-like empty (r == 0) means no headroom:
        erasures consumed the whole check and nothing can be verified.
    ``t``
        Per-column error budget floor(r / 2) — the classical
        2·errors + erasures <= n - k trade.
    ``points``
        BM fast-path evaluation points (Vandermonde generator, no
        erasures) or None.
    """

    def __init__(self, total_mat, k: int, p: int, w: int, survivors):
        self.gf = get_field(w)
        self.k, self.p, self.w = int(k), int(p), int(w)
        self.n = self.k + self.p
        self.survivors = [int(s) for s in survivors]
        if sorted(set(self.survivors)) != sorted(self.survivors):
            raise ValueError(f"duplicate survivor rows: {self.survivors}")
        self.erasures = sorted(
            set(range(self.n)) - set(self.survivors)
        )
        H = parity_check_matrix(total_mat, self.k, self.gf)
        reduced = erasure_reduced_check(H, self.erasures, self.gf)
        if reduced is None:
            raise ValueError(
                f"{len(self.erasures)} chunks missing exceeds parity "
                f"p={self.p}: archive is past erasure recovery, locate "
                "cannot help"
            )
        self.check = np.ascontiguousarray(
            reduced[:, self.survivors]
        ).astype(self.gf.dtype)
        self.r = self.check.shape[0]
        self.t = self.r // 2
        # BM fast path only on the full (unreduced) check, where native
        # columns keep their power structure; identical verdicts either
        # way — the general tiers cover everything.
        self.points = (
            vandermonde_points(total_mat, self.k, self.gf)
            if not self.erasures else None
        )

    def locate(self, S_np) -> dict[int, list[tuple[int, int]]]:
        """Map a segment's host syndromes to verified corrections keyed
        by column, each ``(survivor CHUNK index, magnitude)`` — raises
        :class:`.bw.UnlocatableError` past the t bound."""
        raw = locate_segment(
            S_np, self.check.astype(np.int64), self.gf, points=self.points
        )
        return {
            col: [(self.survivors[pos], mag) for pos, mag in fixes]
            for col, fixes in raw.items()
        }


def correct_segment(seg, corrections, row_of_chunk) -> int:
    """XOR the located magnitudes into the host segment, in place.

    ``seg`` is the gathered (n_surv, cols) SYMBOL view (uint8 for w=8,
    uint16 for w=16) whose rows follow ``LocateContext.survivors``;
    ``row_of_chunk`` maps chunk index -> row in ``seg``.  Returns the
    number of symbol errors patched (the ``rs_located_errors_total``
    increment).
    """
    patched = 0
    for col, fixes in corrections.items():
        for chunk_idx, mag in fixes:
            seg[row_of_chunk[chunk_idx], col] ^= seg.dtype.type(mag)
            patched += 1
    return patched
