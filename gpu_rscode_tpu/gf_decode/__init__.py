"""Error-locating generalized-RS decode (gf_decode/) — silent-bitrot
recovery without checksums.

The erasure decoder (Vandermonde + Gauss-Jordan, the paper's path) can
only rebuild chunks it KNOWS are bad; a flipped byte in a chunk that
passes no CRC propagates silently into the reconstructed file.  This
subsystem adds the syndrome / Berlekamp–Welch machinery of arXiv
1702.07737 ("Decoding Generalized Reed-Solomon Codes"): a parity-check
view of the code, batched syndrome computation as a plan-cached GF-GEMM
(:mod:`.syndrome`), a key-equation solver over GF(2^8)/GF(2^16) that
returns error LOCATIONS and magnitudes per column (:mod:`.bw`), and a
corrected-decode that patches located symbols in place before the normal
inverse-GEMM reconstruction (:mod:`.correct`).

Wired through the resilience plane in :mod:`..api`:
``locate_decode_file`` (CLI ``rs decode --locate``), the scrub syndrome
pre-check (``rs scrub --syndrome``, ``state="silent_bitrot"``), and the
``auto_decode_file`` escalation ladder's final rung
(exclude → rescan → reselect → locate).  Semantics and knobs:
docs/RESILIENCE.md "Error location".
"""

from .bw import (  # noqa: F401
    UnlocatableError,
    berlekamp_massey,
    gf_solve,
    locate_column,
    locate_segment,
)
from .correct import LocateContext, correct_segment  # noqa: F401
from .syndrome import (  # noqa: F401
    erasure_reduced_check,
    is_systematic,
    parity_check_matrix,
    vandermonde_points,
)
