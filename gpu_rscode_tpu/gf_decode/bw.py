"""Berlekamp–Welch-style error location over GF(2^8)/GF(2^16).

Input: the per-column syndromes ``S = H' @ Y`` (:mod:`.syndrome`), where
``H'`` is the (possibly erasure-reduced) parity check restricted to the
available chunk rows.  Output, per nonzero column: the unique error
support of weight <= t = floor(r/2) with its magnitudes, or
:class:`UnlocatableError` — never a silently wrong correction:

* every candidate solution is VERIFIED exactly (``H'_J @ eps == S``)
  before it is returned, and
* a verified weight-<=t solution is THE truth whenever the real error
  weight is <= t: two distinct supports of weight <= t explaining one
  syndrome would difference to a codeword of weight <= 2t <= r < d_min,
  impossible for an MDS check.  (Beyond t the bounded-distance guarantee
  lapses — docs/RESILIENCE.md "t-bound semantics".)

Three solver tiers, cheapest first:

1. **Vectorised single-error match** — the dominant real case (one
   rotten chunk ⇒ one error per column): a single error at position i
   makes the syndrome column GF-proportional to check column ``h_i``, so
   normalising both to their leading coefficient turns location into an
   exact signature join (one ``searchsorted`` across ALL corrupted
   columns at once — a fully-rotted chunk locates in one vector pass).
2. **Berlekamp–Massey + Chien** (``points`` given — the reference's
   Vandermonde generator, no erasures): syndromes of native-position
   errors are power sums ``S_j = Σ eps_i a_i^j``, so the key equation
   ``Λ(z)·S(z) ≡ Ω(z) mod z^r`` yields the locator Λ directly;
   roots are searched over the k native points, magnitudes come from the
   small linear solve, and the verification pass catches supports that
   also touch parity chunks (→ tier 3).
3. **Candidate-support elimination** (any generator, erasures included):
   for e = 2..t, solve ``H'_J eps = S`` over every size-e support and
   keep the first verified solution — exact by the MDS uniqueness
   argument, combinatorially bounded by the tiny t this code runs at.
"""

from __future__ import annotations

from itertools import combinations

import numpy as np

from ..ops.gf import GaloisField


class UnlocatableError(ValueError):
    """Nonzero syndromes with no verified error pattern of weight <= t.

    The never-silently-wrong verdict: more than t symbol errors hit some
    column (or the check had no headroom, t == 0), so no correction is
    trustworthy and the caller must fail the operation, not guess.
    ``columns`` carries a sample of offending column indices, ``total``
    the full count, ``t`` the budget that was exceeded.
    """

    def __init__(self, columns, t: int, total: int | None = None):
        self.columns = [int(c) for c in columns[:16]]
        self.t = int(t)
        self.total = int(total if total is not None else len(columns))
        super().__init__(
            f"{self.total} column(s) carry errors no weight<={self.t} "
            f"pattern explains (first at {self.columns[:4]}): damage "
            "exceeds the locate bound — refusing to fabricate bytes"
        )


def gf_eliminate(aug, ncols: int, gf: GaloisField) -> int:
    """Gauss-Jordan over the first ``ncols`` columns of the int64
    augmented matrix, IN PLACE: pivot scan, row swap, ``gf.inv``
    normalisation, full-column XOR-eliminate.  Pivotless columns are
    skipped (callers read the meaning off the returned rank).  Returns
    the rank — pivot rows end up at the top, in column order.

    The ONE finite-field elimination kernel of the subsystem: the
    overdetermined magnitude solve (:func:`gf_solve`) and the erasure
    null-space reduction (:func:`.syndrome.erasure_reduced_check`) both
    run on it, so the subtle inner math cannot drift between them.
    """
    row = 0
    rows = aug.shape[0]
    for col in range(ncols):
        if row >= rows:
            break
        nz = np.nonzero(aug[row:, col])[0]
        if nz.size == 0:
            continue
        rr = row + int(nz[0])
        if rr != row:
            aug[[row, rr]] = aug[[rr, row]]
        aug[row] = gf.mul(aug[row], gf.inv(aug[row, col]))
        mask = aug[:, col] != 0
        mask[row] = False
        if mask.any():
            factors = aug[mask, col][:, None]
            aug[mask] ^= gf.mul(factors, aug[row][None, :]).astype(np.int64)
        row += 1
    return row


def gf_solve(A, b, gf: GaloisField):
    """Solve the (possibly overdetermined) GF system ``A x = b`` exactly.

    Returns the unique solution as int64, or None when A is column-rank
    deficient (ambiguous — never guess) or the system is inconsistent.
    """
    A = np.asarray(A, dtype=np.int64)
    b = np.asarray(b, dtype=np.int64)
    r, c = A.shape
    if c == 0 or r < c:
        return None
    aug = np.concatenate([A, b[:, None]], axis=1)
    rank = gf_eliminate(aug, c, gf)
    if rank < c:
        return None  # rank-deficient: support is ambiguous
    if np.any(aug[rank:, c]):
        return None  # inconsistent: this support cannot explain S
    # rank == c with skip-on-missing semantics means every column
    # pivoted, in order: rows 0..c-1 hold [I | x].
    return aug[:c, c].copy()


def berlekamp_massey(S, gf: GaloisField) -> tuple[list[int], int]:
    """Minimal LFSR (connection polynomial) for the syndrome sequence.

    ``S`` is the length-r power-sum sequence of one column; returns
    ``(C, L)`` with ``C = [1, c1, ..., cL]`` such that
    ``S_n = Σ_{i=1..L} c_i · S_{n-i}`` (GF arithmetic, XOR sums) — the
    error-locator Λ(z) whose roots are the inverse error points.
    """
    S = [int(s) for s in S]
    C = [1]
    B = [1]
    L, m, b = 0, 1, 1
    for n in range(len(S)):
        d = S[n]
        for i in range(1, L + 1):
            if i < len(C):
                d ^= int(gf.mul(C[i], S[n - i]))
        if d == 0:
            m += 1
            continue
        coef = int(gf.div(d, b))
        if 2 * L <= n:
            T = list(C)
            if len(B) + m > len(C):
                C = C + [0] * (len(B) + m - len(C))
            for i, bv in enumerate(B):
                C[i + m] ^= int(gf.mul(coef, bv))
            L = n + 1 - L
            B, b, m = T, d, 1
        else:
            if len(B) + m > len(C):
                C = C + [0] * (len(B) + m - len(C))
            for i, bv in enumerate(B):
                C[i + m] ^= int(gf.mul(coef, bv))
            m += 1
    while len(C) > 1 and C[-1] == 0:
        C.pop()
    return C, L


def _chien_roots(C, points, gf: GaloisField) -> list[int]:
    """Positions i whose inverse point is a root of the locator:
    ``Λ(a_i^{-1}) == 0``, evaluated vectorised over all native points."""
    xs = gf.inv(np.asarray(points, dtype=np.int64))
    acc = np.full(xs.shape, C[0], dtype=np.int64)
    xp = np.ones_like(xs)
    for c in C[1:]:
        xp = np.asarray(gf.mul(xp, xs), dtype=np.int64)
        if c:
            acc ^= np.asarray(gf.mul(c, xp), dtype=np.int64)
    return [int(i) for i in np.flatnonzero(acc == 0)]


def _verify(H_avail, support, mags, S_col, gf: GaloisField) -> bool:
    got = np.zeros(S_col.shape[0], dtype=np.int64)
    for pos, mag in zip(support, mags):
        got ^= np.asarray(
            gf.mul(int(mag), H_avail[:, pos]), dtype=np.int64
        )
    return bool(np.array_equal(got, np.asarray(S_col, dtype=np.int64)))


def _bm_locate(S_col, H_avail, points, t: int, gf: GaloisField):
    """Tier 2: key-equation solve for native-position supports."""
    C, L = berlekamp_massey(S_col, gf)
    if L == 0 or L > t or len(C) - 1 != L:
        return None
    roots = _chien_roots(C, points, gf)
    if len(roots) != L:
        return None  # locator doesn't split over the native points
    mags = gf_solve(H_avail[:, roots], S_col, gf)
    if mags is None or np.any(mags == 0):
        return None
    if not _verify(H_avail, roots, mags, S_col, gf):
        return None
    return list(zip(roots, (int(m) for m in mags)))


def _search_locate(S_col, H_avail, t: int, gf: GaloisField):
    """Tier 3: verified candidate-support elimination, minimal e first.

    All supports of the hit weight are enumerated and a SECOND verified
    solution makes the column ambiguous (None — unlocatable): in non-MDS
    corners (e.g. proportional columns surviving an erasure reduction)
    the minimal-weight pattern need not be unique, and returning the
    first hit would patch the wrong chunk — the silently-wrong outcome
    this module exists to rule out.  (Tier 1 declines those same
    positions via its duplicate-signature guard; this is the matching
    guard for the general tier.)"""
    n_av = H_avail.shape[1]
    for e in range(1, t + 1):
        hit = None
        for J in combinations(range(n_av), e):
            mags = gf_solve(H_avail[:, list(J)], S_col, gf)
            if mags is None or np.any(mags == 0):
                continue
            if not _verify(H_avail, J, mags, S_col, gf):
                continue
            if hit is not None:
                return None  # two verified supports at this weight
            hit = [(int(p_), int(m)) for p_, m in zip(J, mags)]
        if hit is not None:
            return hit
    return None


def locate_column(S_col, H_avail, gf: GaloisField, t: int, *, points=None):
    """Locate one column's errors; list of (position, magnitude) or None.

    Position indexes ``H_avail``'s columns (the caller maps back to chunk
    rows).  Every returned solution is exact-verified.
    """
    S_col = np.asarray(S_col, dtype=np.int64)
    if not S_col.any():
        return []
    if t <= 0:
        return None
    if points is not None:
        hit = _bm_locate(S_col, H_avail, points, t, gf)
        if hit is not None:
            return hit
    return _search_locate(S_col, H_avail, t, gf)


def _e1_match(S, H, gf: GaloisField):
    """Tier 1: vectorised single-error location for ALL columns at once.

    ``S`` (r, m) nonzero syndrome columns, ``H`` (r, n_av) check.  A
    single error at position i makes the column GF-proportional to
    ``h_i``; normalising each to its leading coefficient reduces the
    match to an exact signature join.  Returns ``(pos, mag)`` arrays with
    pos == -1 where no single-error explanation exists (or the check has
    proportional columns — a non-MDS corner where a singleton match would
    be ambiguous, so it is declined and the column falls through to the
    slower verified tiers).
    """
    S = np.asarray(S, dtype=np.int64)
    H = np.asarray(H, dtype=np.int64)
    r, m = S.shape
    n_av = H.shape[1]
    j = np.argmax(S != 0, axis=0)
    lead = S[j, np.arange(m)]
    norm = np.asarray(gf.div(S, lead[None, :]), dtype=np.int64)
    zero_h = ~(H != 0).any(axis=0)
    jH = np.argmax(H != 0, axis=0)
    leadH = H[jH, np.arange(n_av)].copy()
    leadH[zero_h] = 1  # all-zero check column: sig stays all-zero, no match
    normH = np.asarray(gf.div(H, leadH[None, :]), dtype=np.int64)

    sig = np.ascontiguousarray(norm.T.astype(np.uint16))
    sigH = np.ascontiguousarray(normH.T.astype(np.uint16))
    void = np.dtype((np.void, sig.dtype.itemsize * r))
    sv = sig.reshape(m, -1).view(void).ravel()
    hv = sigH.reshape(n_av, -1).view(void).ravel()

    order = np.argsort(hv)
    hs = hv[order]
    # Proportional check columns: any signature collision makes singleton
    # location ambiguous for those positions — decline them.
    dup = np.zeros(n_av, dtype=bool)
    if n_av > 1:
        eq = hs[1:] == hs[:-1]
        dup_sorted = np.zeros(n_av, dtype=bool)
        dup_sorted[1:] |= eq
        dup_sorted[:-1] |= eq
        dup[order] = dup_sorted
    idx = np.searchsorted(hs, sv)
    idx = np.clip(idx, 0, n_av - 1)
    cand = order[idx]
    ok = (hv[cand] == sv) & ~dup[cand] & ~zero_h[cand]
    pos = np.where(ok, cand, -1)
    denom = np.where(pos >= 0, leadH[np.clip(pos, 0, n_av - 1)], 1)
    mag = np.where(
        pos >= 0, np.asarray(gf.div(lead, denom), dtype=np.int64), 0
    )
    # The match IS the verification: sig equality means S_col ==
    # (lead/leadH) * h_pos exactly, with both leading rows aligned.
    return pos, mag


def locate_segment(S, H_avail, gf: GaloisField, *, points=None,
                   max_errors: int | None = None):
    """Locate every error in a segment's syndrome matrix.

    ``S`` (r, m) syndromes (host array), ``H_avail`` the reduced check
    restricted to available rows.  Returns ``{column: [(position,
    magnitude), ...]}`` for the columns that need patching; raises
    :class:`UnlocatableError` when any nonzero column has no verified
    weight-<=t explanation.  ``points`` enables the BM fast path (tier 2)
    for Vandermonde-generated archives with no erasures.
    """
    S = np.asarray(S, dtype=np.int64)
    r = H_avail.shape[0]
    t = (r // 2) if max_errors is None else min(max_errors, r // 2)
    bad = np.flatnonzero(S.any(axis=0))
    if bad.size == 0:
        return {}
    if t <= 0:
        raise UnlocatableError(bad.tolist(), t)
    corrections: dict[int, list[tuple[int, int]]] = {}
    pos, mag = _e1_match(S[:, bad], H_avail, gf)
    leftover = []
    for bi, col in enumerate(bad):
        if pos[bi] >= 0:
            corrections[int(col)] = [(int(pos[bi]), int(mag[bi]))]
        else:
            leftover.append(int(col))
    unlocatable = []
    for col in leftover:
        hit = locate_column(S[:, col], H_avail, gf, t, points=points)
        if not hit:  # None (no explanation) — [] impossible: col is bad
            unlocatable.append(col)
        else:
            corrections[col] = hit
    if unlocatable:
        raise UnlocatableError(unlocatable, t)
    return corrections
