"""Parity-check construction + erasure reduction for error-locating decode.

The code is the column space of the total matrix ``T = [I_k; G]``
(.METADATA's exact layout): a column of the stacked chunk array ``Y``
(n rows = k natives + p parity) is a valid codeword iff ``H @ Y == 0``
with

    H = [G | I_p]        (p, n)   since  H @ T = G ⊕ G = 0 over GF(2^w).

``S = H @ Y`` is the *syndrome*: zero columns are consistent, nonzero
columns carry exactly the error pattern's image ``H @ E`` — the input to
the key-equation solver (:mod:`.bw`).  The GEMM itself dispatches through
:meth:`..codec.RSCodec.syndrome` (plan-cached, strategy-aware — a
first-class kernel next to encode/decode; see docs/PLAN.md on syndrome
plan-cache entries).

Erasures (missing / known-bad chunks) contribute unknown terms to ``S``.
:func:`erasure_reduced_check` projects them out: a row transform ``R``
with ``R @ H[:, E] == 0`` yields the reduced check ``H' = R @ H`` whose
syndromes see only the *unknown* errors among surviving rows, with error
budget ``t' = floor((p - nu) / 2)`` — the classical errors-and-erasures
trade (2·errors + erasures <= n - k).
"""

from __future__ import annotations

import numpy as np

from ..ops.gf import GaloisField


def is_systematic(total_mat: np.ndarray, k: int) -> bool:
    """Whether the metadata matrix has the identity top block the locate
    path's parity-check construction assumes.  Foreign encoders may write
    any matrix; non-systematic archives stay erasure-only."""
    total_mat = np.asarray(total_mat)
    if total_mat.shape[0] <= k:
        return False
    return bool(
        np.array_equal(total_mat[:k], np.eye(k, dtype=total_mat.dtype))
    )


def parity_check_matrix(total_mat: np.ndarray, k: int,
                        gf: GaloisField) -> np.ndarray:
    """``H = [G | I_p]`` for a systematic total matrix ``[I; G]`` — the
    (p, n) parity check the syndrome GEMM dispatches."""
    total_mat = np.asarray(total_mat)
    if not is_systematic(total_mat, k):
        raise ValueError(
            "error-locating decode needs a systematic total matrix "
            "(identity top block); this archive's metadata is foreign — "
            "erasure-only decode still applies"
        )
    G = total_mat[k:].astype(gf.dtype)
    p = G.shape[0]
    return np.concatenate(
        [G, np.eye(p, dtype=gf.dtype)], axis=1
    )  # (p, k + p)


def vandermonde_points(total_mat: np.ndarray, k: int,
                       gf: GaloisField) -> np.ndarray | None:
    """The native-position evaluation points ``a_i = (i+1) mod 2^w`` IF
    the parity block is the reference's Vandermonde form (``G[j, i] =
    a_i^j``) — the structure the Berlekamp–Massey fast path keys on
    (power-sum syndromes).  Returns None for any other generator (Cauchy,
    foreign): those route through the general solver, same verdicts.
    Points must be distinct (k < 2^w) or the fast path is declined."""
    total_mat = np.asarray(total_mat)
    G = total_mat[k:]
    p = G.shape[0]
    if k >= gf.size:
        return None  # (i+1) mod 2^w wraps: points collide
    pts = (np.arange(k, dtype=np.int64) + 1) % gf.size
    want = gf.pow(
        pts[None, :], np.arange(p, dtype=np.int64)[:, None]
    ).astype(G.dtype)
    if not np.array_equal(G, want):
        return None
    return pts


def erasure_reduced_check(
    H: np.ndarray, erasure_cols: list[int], gf: GaloisField
) -> np.ndarray | None:
    """Row transform of ``H`` annihilating the erased columns.

    Returns ``H' = R @ H`` of shape (p - nu, n) with ``H'[:, e] == 0``
    for every erased position, or None when nu > p (more erasures than
    parity — nothing to check; the archive is already past erasure
    recovery too).  ``R`` is a null-space basis of ``H[:, E]^T``, found
    by GF Gauss elimination; for an MDS check (any p columns independent)
    the rank drop is exactly nu, so ``H'`` keeps p - nu independent rows.
    """
    from .bw import gf_eliminate

    H = np.asarray(H, dtype=np.int64)
    p = H.shape[0]
    E = sorted(set(int(e) for e in erasure_cols))
    if not E:
        return H.astype(gf.dtype)
    if len(E) > p:
        return None
    # Eliminate on [H_E | I_p] (the shared kernel — dependent erasure
    # columns, a non-MDS corner, just don't pivot): rows of the identity
    # half whose H_E half zeroed out form R, the left-null basis of H_E.
    aug = np.concatenate(
        [H[:, E], np.eye(p, dtype=np.int64)], axis=1
    )
    rank = gf_eliminate(aug, len(E), gf)
    R = aug[rank:, len(E):]  # (p - rank, p), R @ H_E == 0
    if R.shape[0] == 0:
        return np.zeros((0, H.shape[1]), dtype=gf.dtype)
    Hp = gf.matmul(R, H).astype(np.int64)
    # Exactness guard: the reduced check must really not see the erasures.
    if np.any(Hp[:, E]):
        raise AssertionError("erasure reduction left residual columns")
    return Hp.astype(gf.dtype)
