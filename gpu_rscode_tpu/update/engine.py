"""The delta-parity patch engine behind ``rs update`` / ``rs append``.

One shared pipeline serves both entry points (they differ only in the
byte range and whether the archive grows):

1. resolve any pending journal (:mod:`.journal` — a torn prior op rolls
   back before this one starts);
2. map the edited byte range to its touched column windows
   (:mod:`.layout`) — only those columns move, never the cold stripes;
3. per segment block: assemble the native delta ``Δ = new ⊕ old``
   (untouched rows stay zero), dispatch ``E·Δ`` as a plan-cached GF-GEMM
   (``codec.update`` — op="update" on the same bucket-ladder plan cache
   the encode path warms), and XOR-patch the parity columns in place;
4. journal old bytes (fsynced) BEFORE each block's patches, patch
   through an ordered random-access pwrite lane
   (``DrainExecutor.submit_pwrite`` — the fault plane's write boundary),
   and fix each touched chunk's CRC incrementally (:mod:`.crc` — no
   full-chunk re-hash);
5. commit: fsync the chunk files, then one crash-safe .METADATA rewrite
   (total size for appends, refreshed CRC lines, generation bump) —
   the atomic commit point — and discard the journal.

Any failure before the commit rolls back in-process (or, after a hard
crash, at the next open via :func:`.journal.recover`), so the archive is
always byte-identical to either its pre-op or post-op state.

``RS_UPDATE_CRASH=<stage>`` (test-only; stages ``after_journal`` /
``mid_patch`` / ``before_commit``) raises :class:`SimulatedCrash` at the
named point WITHOUT the in-process rollback, leaving the disk exactly as
a real crash would — the chaos ``update`` class's torn-op surface.
"""

from __future__ import annotations

import bisect
import os
import time

import numpy as np

from ..codec import RSCodec
from ..obs import metrics as _metrics, tracing as _tracing
from ..parallel.io_executor import DrainExecutor
from ..utils.fileformat import (
    chunk_file_name,
    chunk_size_for_layout,
    metadata_file_name,
    read_archive_meta,
    rewrite_metadata_lines,
)
from ..utils.timing import PhaseTimer
from . import journal as _journal
from .crc import crc32_append, crc32_patch
from .layout import deinterleave, interleave, touched_rows, touched_windows


class UpdateError(ValueError):
    """The archive cannot take this update/append as asked (range outside
    the file, missing chunks, foreign metadata, row-major append past the
    slack) — actionable, never a half-applied mutation."""


class SimulatedCrash(RuntimeError):
    """RS_UPDATE_CRASH fired: the op stops dead WITHOUT rolling back,
    exactly like a power cut — test/chaos surface only."""


def _crash_point(stage: str) -> None:
    if os.environ.get("RS_UPDATE_CRASH") == stage:
        raise SimulatedCrash(f"RS_UPDATE_CRASH={stage}")


def _load_payload(data, src) -> np.ndarray:
    """The edit/append bytes as a read-only uint8 array (``src`` path is
    memmapped — a multi-GB delta streams through the block loop without
    materialising)."""
    if (data is None) == (src is None):
        raise ValueError("pass exactly one of data= or src=")
    if src is not None:
        if os.path.getsize(src) == 0:
            return np.zeros(0, dtype=np.uint8)
        return np.memmap(src, dtype=np.uint8, mode="r")
    if isinstance(data, np.ndarray):
        return np.ascontiguousarray(data, dtype=np.uint8).reshape(-1)
    return np.frombuffer(bytes(data), dtype=np.uint8)


def _pread(fp, off: int, n: int) -> bytes:
    """n bytes at off, zero-filled past EOF (appends read the region they
    are about to create as zeros — the archive's own pad contract)."""
    got = os.pread(fp.fileno(), n, off)
    if len(got) < n:
        got += b"\x00" * (n - len(got))
    return got


def _block_bytes(k: int, sym: int, segment_bytes: int) -> int:
    """Nominal per-block chunk-byte width: bound the (k, block) working
    set to ~segment_bytes, symbol-aligned.  Deliberately NOT clamped to
    the touched window: this is also the plan-cache ``cap`` every block
    stages under, so a small edit buckets up the ladder (sharing plan
    classes with other edits and with encode's tail buckets) instead of
    compiling an exact-width executable per distinct edit size."""
    return max(sym, (segment_bytes // max(1, k)) // sym * sym)


def _intersecting(spans, starts, flo, fhi):
    """The ascending, disjoint ``(at, payload)`` spans overlapping file
    range [flo, fhi), located by bisect — a coalesced group may hold
    thousands of spans, and a linear scan per block (× per row on the
    row layout) made assembly O(blocks × rows × edits)."""
    i = bisect.bisect_right(starts, flo) - 1
    if i >= 0 and starts[i] + int(spans[i][1].shape[0]) <= flo:
        i += 1
    i = max(i, 0)
    while i < len(spans) and starts[i] < fhi:
        yield spans[i]
        i += 1


def _assemble_row_block(b0, b1, rows, fps, spans, chunk, k):
    """Row-major Δ for chunk-byte window [b0, b1): per touched row, the
    intersection of its file range with each edit span — old bytes read,
    new bytes from the span payload; untouched rows stay zero.  ``spans``
    is an ascending list of disjoint ``(at, payload)`` file ranges (one
    for a single edit, many for a coalesced group — :mod:`.group`)."""
    delta = np.zeros((k, b1 - b0), dtype=np.uint8)
    starts = [at for at, _ in spans]
    writes = []
    for r in rows:
        for at, payload in _intersecting(
            spans, starts, r * chunk + b0, r * chunk + b1
        ):
            lo = max(r * chunk + b0, at)
            hi = min(r * chunk + b1, at + int(payload.shape[0]))
            if lo >= hi:
                continue
            off = lo - r * chunk
            old = _pread(fps[r], off, hi - lo)
            new = np.ascontiguousarray(payload[lo - at : hi - at])
            delta[r, off - b0 : off - b0 + (hi - lo)] = (
                np.frombuffer(old, dtype=np.uint8) ^ new
            )
            writes.append((r, off, old, new.tobytes()))
    return delta, writes


def _assemble_interleaved_block(b0, b1, fps, spans, k, sym):
    """Interleaved Δ for chunk-byte window [b0, b1): gather the k old
    rows, de-interleave to file order, overlay every intersecting edit
    span, re-interleave.  All rows are candidates (the layout spreads
    every file byte across rows); rows whose Δ is zero and that gain no
    extension are dropped by the caller."""
    bw = b1 - b0
    old_rows = np.zeros((k, bw), dtype=np.uint8)
    for r in range(k):
        got = os.pread(fps[r].fileno(), bw, b0)
        if got:
            old_rows[r, : len(got)] = np.frombuffer(got, dtype=np.uint8)
    file_lo = (b0 // sym) * k * sym
    file_hi = file_lo + k * bw
    new_file = deinterleave(old_rows, sym).copy()
    starts = [at for at, _ in spans]
    for at, payload in _intersecting(spans, starts, file_lo, file_hi):
        lo = max(file_lo, at)
        hi = min(file_hi, at + int(payload.shape[0]))
        if lo < hi:
            new_file[lo - file_lo : hi - file_lo] = payload[lo - at : hi - at]
    new_rows = interleave(new_file, k, sym)
    delta = old_rows ^ new_rows
    writes = [
        (r, b0, old_rows[r].tobytes(), new_rows[r].tobytes())
        for r in range(k)
    ]
    return delta, writes


def apply_update(
    file_name: str,
    at: int,
    data=None,
    *,
    src: str | None = None,
    strategy: str = "auto",
    segment_bytes: int = 64 * 1024 * 1024,
    timer: PhaseTimer | None = None,
) -> dict:
    """In-place edit of the archived file's bytes [at, at+len) —
    ``parity' = parity ⊕ E·Δ``; only the touched segment columns move."""
    return _apply(
        file_name, at, _load_payload(data, src), grow=False,
        strategy=strategy, segment_bytes=segment_bytes, timer=timer,
    )


def apply_append(
    file_name: str,
    data=None,
    *,
    src: str | None = None,
    strategy: str = "auto",
    segment_bytes: int = 64 * 1024 * 1024,
    timer: PhaseTimer | None = None,
) -> dict:
    """Grow the archived file by the payload: interleaved archives extend
    every chunk's tail column block (cold columns untouched); row-major
    archives accept appends bounded by their tail-padding slack."""
    return _apply(
        file_name, None, _load_payload(data, src), grow=True,
        strategy=strategy, segment_bytes=segment_bytes, timer=timer,
    )


def _check_width(meta) -> None:
    """w=8/16 gate shared by the single-op and group engines."""
    if meta.w not in (8, 16):
        raise ValueError(
            f"unsupported gfwidth {meta.w} in {meta.path!r} "
            "(this build handles w=8 and w=16 files)"
        )


def _parity_coeffs(meta, gf):
    """The (p, k) parity coefficient block ``E`` from the archive's
    (systematic) total matrix — validated, shared by both engines."""
    from ..models.vandermonde import total_matrix as _regen_total

    k = meta.native_num
    mat = meta.total_mat
    if mat is None:
        mat = _regen_total(meta.parity_num, k, gf)
    mat = np.asarray(mat)
    if int(mat.max(initial=0)) >= (1 << meta.w):
        raise ValueError(
            f"metadata matrix entry {int(mat.max())} out of range for "
            f"GF(2^{meta.w}) — corrupt or foreign .METADATA"
        )
    if not np.array_equal(mat[:k], np.eye(k, dtype=mat.dtype)):
        raise UpdateError(
            "delta update needs a systematic total matrix (identity "
            "native block); this archive's metadata is foreign — "
            "re-encode instead"
        )
    return mat[k:].astype(gf.dtype)


def _open_chunks(file_name, all_idx, chunk_old, fps) -> None:
    """Open every chunk in ``all_idx`` r+b into the caller's ``fps`` dict
    (caller owns closing — including on partial failure here), refusing
    missing or truncated chunks with the actionable repair hint."""
    for idx in all_idx:
        path = chunk_file_name(file_name, idx)
        try:
            fps[idx] = open(path, "r+b")
        except FileNotFoundError:
            raise UpdateError(
                f"chunk {idx} ({path!r}) is missing — repair the "
                "archive (rs --repair -i) before updating it"
            ) from None
        size = os.fstat(fps[idx].fileno()).st_size
        if size < chunk_old:
            raise UpdateError(
                f"chunk {idx} ({path!r}) is truncated ({size} of "
                f"{chunk_old} bytes) — repair the archive first"
            )


def _apply(file_name, at, payload, *, grow, strategy, segment_bytes, timer):
    from ..ops.gf import get_field

    timer = timer or PhaseTimer(enabled=False)
    t_start = time.perf_counter()
    op = "append" if grow else "update"
    recovered = _journal.recover(file_name)

    meta_path = metadata_file_name(file_name)
    meta = read_archive_meta(meta_path)
    k, p, w = meta.native_num, meta.parity_num, meta.w
    _check_width(meta)
    sym = meta.sym
    total = meta.total_size
    L = int(payload.shape[0])
    if grow:
        at = total
    summary_base = {
        "op": op, "at": int(at), "bytes": L, "layout": meta.layout,
        "recovered": recovered,
    }
    if L == 0:
        return {
            **summary_base, "segments": 0, "chunks_touched": [],
            "total_size": total, "generation": meta.generation,
        }
    if not grow and (at < 0 or at + L > total):
        raise UpdateError(
            f"update range [{at}, {at + L}) falls outside the archive's "
            f"{total} bytes; use rs append to grow it"
        )

    gf = get_field(w)
    E = _parity_coeffs(meta, gf)

    chunk_old = meta.chunk
    new_total = total + L if grow else None
    if grow:
        chunk_new = chunk_size_for_layout(new_total, k, sym, meta.layout)
        if meta.layout == "row" and chunk_new != chunk_old:
            slack = k * chunk_old - total
            raise UpdateError(
                f"append of {L} bytes overflows the row-major archive's "
                f"{slack} byte(s) of tail-padding slack (growing the "
                "chunk size would re-stripe every byte); re-encode, or "
                "encode with --layout interleaved for unbounded appends"
            )
    else:
        chunk_new = chunk_old
        if chunk_old == 0:
            raise UpdateError("zero-size archive has nothing to update")

    windows = touched_windows(meta.layout, at, L, k, sym, chunk_new)
    rows = touched_rows(meta.layout, at, L, k, chunk_new)
    all_idx = rows + [i for i in range(k, k + p) if i not in rows]

    fps: dict[int, object] = {}
    try:
        _open_chunks(file_name, all_idx, chunk_old, fps)

        codec = RSCodec(k, p, w=w, strategy=strategy)
        crcs = dict(meta.crcs) if meta.crcs else None
        touched: set[int] = set()
        blocks = 0
        jr = _journal.Journal(
            file_name, meta.generation, op, {i: chunk_old for i in all_idx}
        )
        committed = False
        try:
            step = _block_bytes(k, sym, segment_bytes)
            spans = [(at, payload)]
            with DrainExecutor(ordered=True, name="rs-io-patch") as lane:
                for wlo, whi in windows:
                    for b0 in range(wlo, whi, step):
                        b1 = min(b0 + step, whi)
                        blocks += _patch_block(
                            b0, b1, step, rows, fps, spans,
                            chunk_old, k, p, sym, meta.layout, codec, E,
                            lane, jr, crcs, touched, timer,
                            first=blocks == 0, op=op,
                        )
                lane.flush()
            for fp in fps.values():
                os.fsync(fp.fileno())
            _crash_point("before_commit")
            with timer.phase("write metadata (io)"):
                new_gen = rewrite_metadata_lines(
                    meta_path, total_size=new_total, crcs=crcs,
                    bump_generation=True,
                )
            jr.close(commit=True)
            committed = True
        except SimulatedCrash:
            jr.close(commit=False)  # the disk stays torn; recover() heals
            raise
        except BaseException:
            if not committed:
                # In-process rollback from the DURABLE journal (its
                # records are a superset of everything patched so far,
                # already fsynced — no second in-memory copy needed, so
                # a multi-GB streamed delta never accumulates undo bytes
                # in RAM).  The metadata generation still matches the
                # journal's, so recover() restores and discards it —
                # the same machinery a hard crash would use.
                jr.close(commit=False)
                _journal.recover(file_name)
            raise
    finally:
        for fp in fps.values():
            if not fp.closed:
                fp.close()

    _metrics.counter(
        "rs_update_bytes_total",
        "payload bytes applied by delta update/append",
    ).labels(op=op).inc(L)
    _metrics.counter(
        "rs_update_segments_touched_total",
        "column segment blocks patched by update/append",
    ).inc(blocks)
    _metrics.quantile(
        "rs_update_wall_seconds",
        "update/append wall seconds (streaming quantiles)",
    ).labels(op=op).observe(time.perf_counter() - t_start)
    return {
        **summary_base,
        "segments": blocks,
        "chunks_touched": sorted(touched),
        "total_size": new_total if grow else total,
        "generation": new_gen,
    }


def _collect_block(
    b0, b1, delta, native_writes, pd, fps, chunk_old, k, p,
    layout, timer,
):
    """Finish one block's write set from its parity delta ``pd`` (the
    single-op engine's async ``E·Δ`` handle, or the group plane's slice
    of a stacked multi-window result): XOR the delta into the old parity
    bytes, drop untouched interleaved native rows.  Returns the ordered
    ``(idx, off, old, new)`` write list (natives first, then parity) and
    the native-write count."""
    with timer.phase("update compute"):
        pd_np = np.asarray(pd)
    if pd_np.dtype != np.uint8:
        pd_np = np.ascontiguousarray(pd_np).view(np.uint8)

    parity_writes = []
    ext = b1 > chunk_old  # this block extends the chunk files (append)
    with timer.phase("update stage (io)"):
        for j in range(p):
            if not ext and not pd_np[j].any():
                continue  # parity row provably unchanged in this block
            old = _pread(fps[k + j], b0, b1 - b0)
            new = (np.frombuffer(old, dtype=np.uint8) ^ pd_np[j]).tobytes()
            parity_writes.append((k + j, b0, old, new))
    if layout == "interleaved":
        # The assembler emits every row; rows the edit left untouched
        # (zero Δ, no extension) have nothing to write or re-checksum.
        native_writes = [
            wrt for r, wrt in enumerate(native_writes)
            if ext or delta[r].any()
        ]
    return native_writes + parity_writes, len(native_writes)


def _stage_block(
    b0, b1, cap_bytes, rows, fps, spans, chunk_old, k, p, sym,
    layout, codec, E, timer, *, op,
):
    """One column block's write set: assemble the block's Δ from the
    edit spans, dispatch ``E·Δ`` through the plan cache, and
    :func:`_collect_block` the result."""
    with timer.phase("update stage (io)"):
        if layout == "interleaved":
            delta, native_writes = _assemble_interleaved_block(
                b0, b1, fps, spans, k, sym
            )
        else:
            delta, native_writes = _assemble_row_block(
                b0, b1, rows, fps, spans, chunk_old, k
            )

    with timer.phase("update dispatch"), _tracing.span(
        "dispatch", lane="dispatch", op=op, off=int(b0), cols=int(b1 - b0)
    ):
        staged = codec.stage_segment(
            delta, cap=cap_bytes // sym, sym=sym, out_rows=p
        )
        pd = codec.update(E, staged)  # async E·Δ through the plan cache
    return _collect_block(
        b0, b1, delta, native_writes, pd, fps, chunk_old, k, p,
        layout, timer,
    )


def _patch_block(
    b0, b1, cap_bytes, rows, fps, spans, chunk_old, k, p, sym,
    layout, codec, E, lane, jr, crcs, touched, timer, *, first, op,
) -> int:
    """One column block: assemble Δ, dispatch E·Δ, journal, patch natives
    + parity, account CRCs.  Returns 1 (blocks counted by the caller)."""
    writes, n_native = _stage_block(
        b0, b1, cap_bytes, rows, fps, spans, chunk_old, k, p, sym,
        layout, codec, E, timer, op=op,
    )
    # Undo bytes FIRST, durably — only then may any region change
    # (the write-ahead discipline recovery depends on).
    for idx, off, old, _new in writes:
        jr.record(idx, off, old[: max(0, chunk_old - off)])
    jr.sync()
    if first:
        _crash_point("after_journal")
    for pos, (idx, off, old, new) in enumerate(writes):
        if first and pos == n_native:
            # Natives patched, parity not yet — the torn state the
            # journal exists for.
            lane.flush()
            _crash_point("mid_patch")
        lane.submit_pwrite(fps[idx].fileno(), new, off)
        touched.add(idx)
        if crcs is not None:
            _account_crc(crcs, idx, off, old, new, chunk_old)
    return 1


def _account_crc(crcs, idx, off, old, new, chunk_old) -> None:
    """Incremental CRC for one written region: seekable patch math below
    the chunk's pre-op length, streaming append past it (regions arrive
    in ascending offset order per chunk — the block loop's invariant)."""
    cut = max(0, min(len(new), chunk_old - off))
    if cut:
        delta = (
            np.frombuffer(old[:cut], dtype=np.uint8)
            ^ np.frombuffer(new[:cut], dtype=np.uint8)
        ).tobytes()
        crcs[idx] = crc32_patch(crcs.get(idx, 0), chunk_old, off, delta)
    if len(new) > cut:
        crcs[idx] = crc32_append(crcs.get(idx, 0), new[cut:])
