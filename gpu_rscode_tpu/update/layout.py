"""Chunk-layout geometry: file byte ranges ↔ (row, chunk offset).

Two layouts (``# layout`` metadata extension, docs/UPDATE.md):

* ``row`` — the reference's contiguous striping: chunk i holds file
  bytes [i*chunk, (i+1)*chunk).  Updates map an edit to per-row column
  ranges; appends are bounded by the tail-padding slack (growing the
  chunk size would re-stripe every byte).
* ``interleaved`` — file symbol s lives in row ``s % k``, column
  ``s // k``.  A contiguous edit of L bytes touches only
  ~``ceil(L/(k*sym))`` columns, and an append touches only the tail
  column block of every chunk — the append-mode layout.  The scan /
  repair / syndrome planes are layout-agnostic (column-wise linear
  algebra over whole chunk files); only the file↔chunk byte mapping
  here differs.

Pure NumPy reshapes/transposes; no I/O.
"""

from __future__ import annotations

import numpy as np


def interleave(file_bytes: np.ndarray, k: int, sym: int = 1) -> np.ndarray:
    """(k*cols*sym,) contiguous file bytes -> (k, cols*sym) chunk rows
    under the interleaved layout (symbol s -> row s % k, col s // k)."""
    n = file_bytes.shape[0]
    cols = n // (k * sym)
    assert n == cols * k * sym, (n, k, sym)
    return np.ascontiguousarray(
        file_bytes.reshape(cols, k, sym).transpose(1, 0, 2)
    ).reshape(k, cols * sym)


def deinterleave(rows: np.ndarray, sym: int = 1) -> np.ndarray:
    """(k, cols*sym) chunk rows -> (k*cols*sym,) contiguous file bytes —
    the inverse of :func:`interleave`."""
    k, width = rows.shape
    cols = width // sym
    assert width == cols * sym, (width, sym)
    return np.ascontiguousarray(
        rows.reshape(k, cols, sym).transpose(1, 0, 2)
    ).reshape(-1)


def _align_down(x: int, a: int) -> int:
    return (x // a) * a


def _align_up(x: int, a: int) -> int:
    return -(-x // a) * a


def touched_windows(
    layout: str, at: int, length: int, k: int, sym: int, chunk: int
) -> list[tuple[int, int]]:
    """Chunk-byte windows [lo, hi) (sym-aligned) an edit of file range
    [at, at+length) touches — the column footprint whose Δ must move.

    ``interleaved``: one window around the touched column range.  ``row``:
    the per-row union — exact for single-row and adjacent-disjoint edits,
    widening to the full chunk when three or more rows are crossed (every
    column is then touched by some row anyway)."""
    if length <= 0:
        return []
    if layout == "interleaved":
        lo = (at // (k * sym)) * sym
        hi = (-(-(at + length) // (k * sym))) * sym
        return [(lo, min(hi, chunk))]
    end = at + length - 1
    r0, r1 = at // chunk, end // chunk
    o0 = _align_down(at % chunk, sym)
    o1 = min(_align_up((end % chunk) + 1, sym), chunk)
    if r0 == r1:
        return [(o0, o1)]
    if r1 == r0 + 1 and o1 <= o0:
        # Two adjacent rows with disjoint column footprints: patch the
        # two real windows, not the dead columns between them.
        return [(0, o1), (o0, chunk)]
    return [(0, chunk)]


def touched_rows(
    layout: str, at: int, length: int, k: int, chunk: int
) -> list[int]:
    """Native chunk rows whose bytes an edit of [at, at+length) changes."""
    if length <= 0:
        return []
    if layout == "interleaved":
        return list(range(k))
    r0 = at // chunk
    r1 = (at + length - 1) // chunk
    return list(range(r0, min(r1, k - 1) + 1))


def row_file_range(
    layout: str, row: int, lo: int, hi: int, k: int, sym: int, chunk: int
) -> tuple[int, int] | None:
    """File byte range backing row ``row``'s chunk bytes [lo, hi) — or
    None when the mapping is not row-contiguous (interleaved)."""
    if layout == "interleaved":
        return None
    return row * chunk + lo, row * chunk + hi
