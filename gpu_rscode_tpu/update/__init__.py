"""Delta-parity partial-stripe updates and append-mode encoding.

RS over GF(2^w) is linear, so a byte-range edit of the original file
needs only the touched symbol columns to move: with ``E`` the parity
coefficient block of the archive's total matrix and ``Δ = new ⊕ old``
the native-symbol delta, ``parity' = parity ⊕ E·Δ`` — the XOR-patching
regime of the XOR-based erasure-coding literature (arXiv 2108.02692,
1701.07731).  This package is that capability end to end
(docs/UPDATE.md):

* :func:`~.engine.apply_update` / :func:`~.engine.apply_append` — the
  shared patch engine behind ``api.update_file`` / ``api.append_file``:
  byte range → touched column windows (both chunk layouts), Δ assembly,
  ``E·Δ`` as a plan-cached GF-GEMM (``codec.update``, op="update"),
  in-place parity XOR patches through an ordered pwrite lane, and
  incremental per-chunk CRC fix-up (:mod:`.crc` — no full-chunk
  re-hash).
* :mod:`.journal` — the undo journal that makes in-place mutation
  crash-atomic: old bytes of every region land (fsynced) in
  ``<archive>.rs_journal`` before any patch; the atomic .METADATA
  rewrite (generation bump) is the commit point; recovery rolls a torn
  update/append back to the pre-op archive.
* :mod:`.layout` — the ``interleaved`` chunk-layout extension (file
  symbol s → row ``s % k``, column ``s // k``): appends touch only the
  tail column block, so ``rs append`` grows an archive without reading
  a single cold byte.  Row-major (reference-layout) archives take delta
  updates too, and appends bounded by their tail-padding slack.
"""

from __future__ import annotations

from .crc import crc32_append, crc32_combine, crc32_patch, crc32_zeros
from .engine import (
    SimulatedCrash,
    UpdateError,
    apply_append,
    apply_update,
)
from .group import apply_update_many, group_stats, group_window
from .journal import journal_path, recover
from .layout import deinterleave, interleave

__all__ = [
    "SimulatedCrash",
    "UpdateError",
    "apply_append",
    "apply_update",
    "apply_update_many",
    "crc32_append",
    "crc32_combine",
    "crc32_patch",
    "crc32_zeros",
    "deinterleave",
    "group_stats",
    "group_window",
    "interleave",
    "journal_path",
    "recover",
]
