"""Group-commit write combining — one durability chain for many edits.

A burst of N small updates/appends through :func:`.engine.apply_update`
pays N× the per-op tax: N journal creates + fsyncs, N ``E·Δ`` GEMM
dispatches, N crash-safe .METADATA rewrites (temp + fsync + rename +
dir fsync) and N generation bumps — even when the edits land in the
same column windows.  This module is the classic group-commit answer
(docs/UPDATE.md "Group commit"): :func:`apply_update_many` takes an
ORDERED batch of edits/appends against one archive and

1. merges them last-writer-wins into a span overlay (sequential
   semantics: edit j sees the totals left by appends 1..j-1, and a later
   edit of the same bytes wins — byte-identical to applying the batch
   one op at a time);
2. maps the merged spans to their touched column windows, assembles ONE
   stacked Δ per window block and dispatches ONE ``E·Δ`` GEMM per block
   through the warm plan cache (the op-free plan key means every window
   shares encode's executable — docs/PLAN.md);
3. journals the old bytes of EVERY region in the group, then commits the
   whole window group under ONE journal fsync chain, ONE ordered patch
   drain, ONE .METADATA rewrite and ONE generation bump.

All-or-nothing: the single journal covers the whole group, so a torn
group (crash at any ``RS_UPDATE_CRASH`` stage) rolls back EVERY edit via
the existing :func:`.journal.recover` path, and no edit is acknowledged
before its window group is durable — acks follow the commit point, so no
REDO journal is needed.

``RS_UPDATE_GROUP_WINDOW`` caps how many edits one commit group may
coalesce (default 1024): a larger batch splits into consecutive window
groups, each individually all-or-nothing with its own generation bump.
``RS_UPDATE_GROUP_BYTES`` bounds the in-RAM staged write set of one
group; past it the engine interleaves extra journal-sync + patch-drain
cycles (still one commit — durability ordering is preserved, only the
"one fsync" amortization degrades, and the fsync counters say so).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..codec import RSCodec
from ..obs import metrics as _metrics, tracing as _tracing
from ..parallel.io_executor import DrainExecutor
from ..utils.env import int_env as _int_env
from ..utils.fileformat import (
    chunk_size_for_layout,
    metadata_file_name,
    read_archive_meta,
    rewrite_metadata_lines,
)
from ..utils.timing import PhaseTimer
from . import journal as _journal
from .engine import (
    SimulatedCrash,  # noqa: F401  (re-exported crash surface)
    UpdateError,
    _account_crc,
    _assemble_interleaved_block,
    _assemble_row_block,
    _block_bytes,
    _check_width,
    _collect_block,
    _crash_point,
    _load_payload,
    _open_chunks,
    _parity_coeffs,
)
from .layout import touched_rows, touched_windows

DEFAULT_GROUP_WINDOW = 1024
DEFAULT_GROUP_BYTES = 256 * 1024 * 1024


def group_window() -> int:
    """Max edits one commit group coalesces (``RS_UPDATE_GROUP_WINDOW``,
    >= 1; larger batches split into consecutive groups)."""
    return max(1, _int_env("RS_UPDATE_GROUP_WINDOW", DEFAULT_GROUP_WINDOW))


def _group_bytes_budget() -> int:
    return max(1 << 20, _int_env("RS_UPDATE_GROUP_BYTES",
                                 DEFAULT_GROUP_BYTES))


# Process-lifetime tallies (rs doctor / daemon GET /stats read these even
# with the metrics registry disabled).
_STATS_LOCK = threading.Lock()
_STATS = {
    "groups": 0,            # committed window groups
    "edits": 0,             # edits coalesced into those groups
    "bytes": 0,             # payload bytes through grouped commits
    "max_group_seen": 0,    # largest committed group
    "journal_fsyncs": 0,    # journal sync calls across all groups
    "metadata_commits": 0,  # .METADATA rewrites across all groups
}


def group_stats() -> dict:
    """Live group-commit tallies plus the effective config — the doctor /
    daemon introspection surface."""
    with _STATS_LOCK:
        out = dict(_STATS)
    out["window_max_edits"] = group_window()
    return out


def _tally(edits: int, nbytes: int, journal_fsyncs: int) -> None:
    with _STATS_LOCK:
        _STATS["groups"] += 1
        _STATS["edits"] += edits
        _STATS["bytes"] += nbytes
        _STATS["max_group_seen"] = max(_STATS["max_group_seen"], edits)
        _STATS["journal_fsyncs"] += journal_fsyncs
        _STATS["metadata_commits"] += 1


def _fsync_counter():
    return _metrics.counter(
        "rs_update_group_fsyncs_total",
        "fsync calls in grouped update commits, by chain stage",
    )


class _Overlay:
    """Last-writer-wins byte-span overlay: ascending, disjoint
    ``(at, payload)`` ranges.  A later write splits/trims whatever it
    overlaps — exactly the bytes a sequential application would leave."""

    def __init__(self):
        self.spans: list[tuple[int, np.ndarray]] = []

    def write(self, at: int, payload: np.ndarray) -> None:
        length = int(payload.shape[0])
        if length == 0:
            return
        end = at + length
        out = []
        for s_at, s_pl in self.spans:
            s_end = s_at + int(s_pl.shape[0])
            if s_end <= at or s_at >= end:
                out.append((s_at, s_pl))
                continue
            if s_at < at:
                out.append((s_at, s_pl[: at - s_at]))
            if s_end > end:
                out.append((end, s_pl[end - s_at :]))
        out.append((at, payload))
        out.sort(key=lambda t: t[0])
        self.spans = out


def _merge_windows(wins: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Union of column windows: sorted, overlapping/adjacent merged —
    the group's window set, each getting one Δ stack per block."""
    out: list[list[int]] = []
    for lo, hi in sorted(wins):
        if out and lo <= out[-1][1]:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [(lo, hi) for lo, hi in out]


def _parse_edits(edits) -> list[dict]:
    """Normalize the caller's edit list: each item is a dict with
    ``op`` ("update" | "append"), ``at`` (update only) and exactly one
    of ``data`` / ``src``.  Payloads load eagerly (``src`` memmaps, so a
    large file does not materialise)."""
    parsed = []
    for i, e in enumerate(edits):
        if not isinstance(e, dict):
            raise ValueError(
                f"edit {i}: want a dict with op/at/data|src, got "
                f"{type(e).__name__}"
            )
        op = e.get("op", "update")
        if op not in ("update", "append"):
            raise ValueError(
                f"edit {i}: op must be 'update' or 'append', got {op!r}"
            )
        try:
            payload = _load_payload(e.get("data"), e.get("src"))
        except ValueError as err:
            raise ValueError(f"edit {i}: {err}") from None
        if op == "update":
            if "at" not in e:
                raise ValueError(f"edit {i}: update needs an 'at' offset")
            at = int(e["at"])
            if at < 0:
                raise ValueError(f"edit {i}: negative offset {at}")
        else:
            at = None
        parsed.append({"op": op, "at": at, "payload": payload})
    return parsed


def apply_update_many(
    file_name: str,
    edits,
    *,
    strategy: str = "auto",
    segment_bytes: int = 64 * 1024 * 1024,
    timer: PhaseTimer | None = None,
    group_edits: int | None = None,
    group_tag: str | None = None,
    stage_hook=None,
) -> dict:
    """Apply an ordered batch of edits/appends as group-committed window
    groups — byte-identical to applying them sequentially, at a fraction
    of the durability/dispatch tax (module doc).  ``group_edits``
    overrides ``RS_UPDATE_GROUP_WINDOW`` for this call — the daemon's
    write combiner passes the whole batch so its harvest commits as ONE
    all-or-nothing group (its isolation fallback depends on a failed
    batch having committed nothing).  ``group_tag`` names the commit in
    the dispatch trace span and the returned summary (``group_id``) —
    the daemon's write combiner passes its group id here so one combined
    commit joins to the N request ids it acknowledges.  ``stage_hook``
    (a ``callable(stage_name)``) fires at the lifecycle boundaries the
    caller cannot observe from outside — currently ``"device_done"``,
    after the last ``E·Δ`` GEMM is collected and before the journal
    fsync chain begins (docs/SERVE.md "Request lifecycle").  Returns the
    aggregate summary dict (``edits``, ``groups``, ``windows``,
    ``segments``, ``chunks_touched``, ``total_size``, ``generation``)."""
    timer = timer or PhaseTimer(enabled=False)
    parsed = _parse_edits(edits)
    window = max(1, group_edits) if group_edits else group_window()
    summary: dict | None = None
    groups = 0
    for g0 in range(0, max(1, len(parsed)), window):
        part = _apply_group(
            file_name, parsed[g0 : g0 + window], base=g0,
            strategy=strategy, segment_bytes=segment_bytes, timer=timer,
            group_tag=group_tag, stage_hook=stage_hook,
        )
        groups += 1
        if summary is None:
            summary = part
        else:
            summary["edits"] += part["edits"]
            summary["bytes"] += part["bytes"]
            summary["windows"] += part["windows"]
            summary["segments"] += part["segments"]
            summary["journal_fsyncs"] += part["journal_fsyncs"]
            summary["chunks_touched"] = sorted(
                set(summary["chunks_touched"]) | set(part["chunks_touched"])
            )
            summary["total_size"] = part["total_size"]
            summary["generation"] = part["generation"]
    assert summary is not None
    summary["groups"] = groups
    if group_tag is not None:
        summary["group_id"] = group_tag
    return summary


def _apply_group(file_name, edits, *, base, strategy, segment_bytes,
                 timer, group_tag=None, stage_hook=None):
    from ..ops.gf import get_field

    t_start = time.perf_counter()
    recovered = _journal.recover(file_name)
    meta_path = metadata_file_name(file_name)
    meta = read_archive_meta(meta_path)
    k, p, w = meta.native_num, meta.parity_num, meta.w
    _check_width(meta)
    sym = meta.sym
    total0 = meta.total_size

    # Sequential-semantics validation + last-writer-wins merge: edit j
    # is validated against the running total its predecessors left.
    overlay = _Overlay()
    total = total0
    payload_bytes = 0
    for i, e in enumerate(edits):
        length = int(e["payload"].shape[0])
        if e["op"] == "update":
            at = e["at"]
            if length and at + length > total:
                raise UpdateError(
                    f"edit {base + i}: update range [{at}, {at + length}) "
                    f"falls outside the archive's {total} bytes at that "
                    "point in the batch; use an append edit to grow it"
                )
        else:
            at = total
            total += length
        payload_bytes += length
        overlay.write(at, e["payload"])
    grow = total > total0

    summary_base = {
        "op": "group", "edits": len(edits), "bytes": payload_bytes,
        "layout": meta.layout, "recovered": recovered,
    }
    if not overlay.spans:
        return {
            **summary_base, "windows": 0, "segments": 0,
            "chunks_touched": [], "journal_fsyncs": 0,
            "total_size": total0, "generation": meta.generation,
        }

    gf = get_field(w)
    E = _parity_coeffs(meta, gf)
    chunk_old = meta.chunk
    if grow:
        chunk_new = chunk_size_for_layout(total, k, sym, meta.layout)
        if meta.layout == "row" and chunk_new != chunk_old:
            slack = k * chunk_old - total0
            raise UpdateError(
                f"group appends {total - total0} byte(s), overflowing the "
                f"row-major archive's {slack} byte(s) of tail-padding "
                "slack (growing the chunk size would re-stripe every "
                "byte); re-encode, or encode with --layout interleaved "
                "for unbounded appends"
            )
    else:
        chunk_new = chunk_old
        if chunk_old == 0:
            raise UpdateError("zero-size archive has nothing to update")

    wins: list[tuple[int, int]] = []
    rows_set: set[int] = set()
    for at, pl in overlay.spans:
        length = int(pl.shape[0])
        wins += touched_windows(meta.layout, at, length, k, sym, chunk_new)
        rows_set |= set(touched_rows(meta.layout, at, length, k, chunk_new))
    windows = _merge_windows(wins)
    rows = sorted(rows_set)
    all_idx = rows + [i for i in range(k, k + p) if i not in rows]

    fps: dict[int, object] = {}
    try:
        _open_chunks(file_name, all_idx, chunk_old, fps)
        codec = RSCodec(k, p, w=w, strategy=strategy)
        crcs = dict(meta.crcs) if meta.crcs else None
        touched: set[int] = set()
        blocks = 0
        journal_fsyncs = 0
        jr = _journal.Journal(
            file_name, meta.generation, "group",
            {i: chunk_old for i in all_idx},
        )
        committed = False
        try:
            step = _block_bytes(k, sym, segment_bytes)
            budget = _group_bytes_budget()
            # Writes already journaled but not yet submitted to the lane:
            # the whole group's set in the common case — ONE journal sync
            # covers everything before the first chunk byte changes.
            pending: list[tuple[int, int, bytes]] = []
            pending_bytes = 0
            first_n_native = None

            with DrainExecutor(ordered=True, name="rs-io-patch") as lane:

                def drain_pending():
                    nonlocal pending, pending_bytes, journal_fsyncs
                    journal_fsyncs += jr.sync()
                    for idx, off, new in pending:
                        lane.submit_pwrite(fps[idx].fileno(), new, off)
                        touched.add(idx)
                    lane.flush()
                    pending = []
                    pending_bytes = 0

                # Small-window stacking: every window block shares the
                # op-free plan key, so adjacent small windows' deltas
                # concatenate into ONE staged segment and ONE E·Δ GEMM
                # up to the plan-bucket cap (the 64-scattered-4KiB burst
                # dispatches once, not 64 times); a full-width block
                # flushes alone, exactly like the single-op engine.
                batch: list[tuple] = []  # (b0, b1, delta, native_writes)
                batch_w = 0

                def flush_batch():
                    nonlocal batch, batch_w, blocks, pending_bytes
                    nonlocal first_n_native
                    if not batch:
                        return
                    stacked = (
                        batch[0][2] if len(batch) == 1
                        else np.hstack([blk[2] for blk in batch])
                    )
                    span_args = dict(
                        op="group", off=int(batch[0][0]),
                        cols=int(stacked.shape[1]),
                    )
                    if group_tag is not None:
                        # The group <-> request-id join's trace side: a
                        # daemon Perfetto timeline resolves this dispatch
                        # to the write group (and through it, via the
                        # rs_request events, to the member request ids).
                        span_args["group"] = group_tag
                    with timer.phase("update dispatch"), _tracing.span(
                        "dispatch", lane="dispatch", **span_args,
                    ):
                        staged = codec.stage_segment(
                            stacked, cap=step // sym, sym=sym, out_rows=p
                        )
                        pd = codec.update(E, staged)
                    with timer.phase("update compute"):
                        pd_np = np.asarray(pd)
                    if pd_np.dtype != np.uint8:
                        pd_np = np.ascontiguousarray(pd_np).view(np.uint8)
                    col = 0
                    for b0, b1, delta, nat in batch:
                        bw = b1 - b0
                        writes, n_native = _collect_block(
                            b0, b1, delta, nat,
                            pd_np[:, col : col + bw], fps, chunk_old,
                            k, p, meta.layout, timer,
                        )
                        col += bw
                        for idx, off, old, new in writes:
                            jr.record(
                                idx, off, old[: max(0, chunk_old - off)]
                            )
                            if crcs is not None:
                                _account_crc(
                                    crcs, idx, off, old, new, chunk_old
                                )
                            pending.append((idx, off, new))
                            pending_bytes += len(new) + len(old)
                        blocks += 1
                        if first_n_native is None:
                            first_n_native = n_native
                    batch = []
                    batch_w = 0
                    if pending_bytes > budget:
                        # RAM guard for huge groups: extra sync+drain
                        # cycles, still one commit (journal-before-
                        # patch ordering holds per cycle).
                        drain_pending()

                for wlo, whi in windows:
                    for b0 in range(wlo, whi, step):
                        b1 = min(b0 + step, whi)
                        if batch_w + (b1 - b0) > step:
                            flush_batch()
                        with timer.phase("update stage (io)"):
                            if meta.layout == "interleaved":
                                delta, nat = _assemble_interleaved_block(
                                    b0, b1, fps, overlay.spans, k, sym
                                )
                            else:
                                delta, nat = _assemble_row_block(
                                    b0, b1, rows, fps, overlay.spans,
                                    chunk_old, k
                                )
                        batch.append((b0, b1, delta, nat))
                        batch_w += b1 - b0
                flush_batch()
                if stage_hook is not None:
                    # Every E·Δ GEMM is collected; everything after this
                    # point is durability (journal sync chain, patch
                    # drain, chunk fsyncs, metadata commit) — the
                    # device/drain boundary of the lifecycle timeline.
                    stage_hook("device_done")

                journal_fsyncs += jr.sync()
                _crash_point("after_journal")
                cut = min(first_n_native or 0, len(pending)) or None
                for pos, (idx, off, new) in enumerate(pending):
                    if pos == cut:
                        # First block's natives patched, its parity and
                        # every later window not — the torn-group state
                        # recovery must undo in full.
                        lane.flush()
                        _crash_point("mid_patch")
                    lane.submit_pwrite(fps[idx].fileno(), new, off)
                    touched.add(idx)
                pending = []
                lane.flush()

            for fp in fps.values():
                os.fsync(fp.fileno())
            _fsync_counter().labels(stage="chunks").inc(len(fps))
            _crash_point("before_commit")
            with timer.phase("write metadata (io)"):
                new_gen = rewrite_metadata_lines(
                    meta_path, total_size=total if grow else None,
                    crcs=crcs, bump_generation=True,
                )
            jr.close(commit=True)
            committed = True
        except SimulatedCrash:
            jr.close(commit=False)  # disk stays torn; recover() heals
            raise
        except BaseException:
            if not committed:
                # All-or-nothing: roll the WHOLE group back from the
                # durable journal (same machinery a hard crash uses).
                jr.close(commit=False)
                _journal.recover(file_name)
            raise
    finally:
        for fp in fps.values():
            if not fp.closed:
                fp.close()

    _fsync_counter().labels(stage="journal").inc(journal_fsyncs)
    _fsync_counter().labels(stage="metadata").inc()
    _metrics.histogram(
        "rs_update_group_size",
        "edits coalesced per committed update group",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    ).observe(len(edits))
    _metrics.counter(
        "rs_update_group_coalesced_bytes_total",
        "payload bytes applied through grouped update commits",
    ).inc(payload_bytes)
    _metrics.counter(
        "rs_update_bytes_total",
        "payload bytes applied by delta update/append",
    ).labels(op="group").inc(payload_bytes)
    _metrics.counter(
        "rs_update_segments_touched_total",
        "column segment blocks patched by update/append",
    ).inc(blocks)
    _metrics.quantile(
        "rs_update_wall_seconds",
        "update/append wall seconds (streaming quantiles)",
    ).labels(op="group").observe(time.perf_counter() - t_start)
    _tally(len(edits), payload_bytes, journal_fsyncs)
    return {
        **summary_base,
        "windows": len(windows),
        "segments": blocks,
        "journal_fsyncs": journal_fsyncs,
        "chunks_touched": sorted(touched),
        "total_size": total,
        "generation": new_gen,
    }
