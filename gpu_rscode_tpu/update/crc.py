"""Seekable CRC32 arithmetic — incremental per-chunk checksum fix-up.

An in-place patch must refresh the chunk's ``# crc32`` metadata line
without re-reading the untouched prefix and suffix.  CRC32 (the zlib
polynomial) is affine over GF(2) in the message bits: for equal-length
messages, ``crc(x ⊕ y) = crc(x) ⊕ crc(y) ⊕ crc(0^n)`` (the init/xorout
constants cancel pairwise).  A patched chunk is
``new = old ⊕ pad(Δ)`` with ``pad(Δ)`` the edit delta zero-extended to
the chunk length, so

    crc(new) = crc(old) ⊕ crc(0^pre ‖ Δ ‖ 0^post) ⊕ crc(0^len)

and both zero-extension terms are O(log n) via the classic GF(2)
matrix-power shift (zlib's ``crc32_combine``, reimplemented here —
Python's :mod:`zlib` does not expose it).  Appended bytes are plain
streaming :func:`zlib.crc32` continuation.

Everything here is pure host math; property-tested against full
re-hashes in tests/test_update.py.
"""

from __future__ import annotations

import functools
import zlib

_POLY = 0xEDB88320  # CRC-32, reflected


def _gf2_matrix_times(mat: list[int], vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _gf2_matrix_square(mat) -> tuple[int, ...]:
    return tuple(_gf2_matrix_times(mat, mat[n]) for n in range(32))


@functools.lru_cache(maxsize=None)
def _operator(j: int) -> tuple[int, ...]:
    """Matrix for "advance the CRC register past 2^j zero bytes".

    Pure recursive construction over immutable tuples: lru_cache may
    race two first computations of the same ``j`` across threads (the
    serve daemon patches archives from a pool), but both produce the
    identical value and nothing shared is ever mutated.  j is bounded by
    the bit length of a chunk size (< 64)."""
    if j == 0:
        odd = [0] * 32
        odd[0] = _POLY          # one zero BIT
        row = 1
        for n in range(1, 32):
            odd[n] = row
            row <<= 1
        even = _gf2_matrix_square(odd)   # two zero bits
        op = _gf2_matrix_square(even)    # four bits
        return _gf2_matrix_square(op)    # one zero BYTE (2^0 bytes)
    return _gf2_matrix_square(_operator(j - 1))


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC32 of ``A ‖ B`` given ``crc32(A)``, ``crc32(B)`` and
    ``len(B)`` — zlib's crc32_combine, O(log len2)."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    crc1 &= 0xFFFFFFFF
    j = 0
    n = len2
    while n:
        if n & 1:
            crc1 = _gf2_matrix_times(_operator(j), crc1)
        n >>= 1
        j += 1
    return (crc1 ^ crc2) & 0xFFFFFFFF


@functools.lru_cache(maxsize=4096)
def crc32_zeros(n: int) -> int:
    """``crc32`` of ``n`` zero bytes, O(log n) (doubling via combine)."""
    if n <= 0:
        return 0
    if n == 1:
        return zlib.crc32(b"\x00")
    half = crc32_zeros(n // 2)
    crc = crc32_combine(half, half, n // 2)
    if n & 1:
        crc = zlib.crc32(b"\x00", crc)
    return crc & 0xFFFFFFFF


def crc32_patch(
    crc_old: int, chunk_len: int, off: int, delta: bytes | bytearray
) -> int:
    """CRC32 of a ``chunk_len``-byte message after XOR-ing ``delta`` in
    at byte offset ``off``, given only the old CRC — the seekable fix-up
    (no prefix/suffix re-read; O(log chunk_len))."""
    if not delta:
        return crc_old & 0xFFFFFFFF
    post = chunk_len - off - len(delta)
    assert off >= 0 and post >= 0, (off, len(delta), chunk_len)
    c = zlib.crc32(bytes(delta))
    c = crc32_combine(crc32_zeros(off), c, len(delta))
    c = crc32_combine(c, crc32_zeros(post), post)
    return (crc_old ^ c ^ crc32_zeros(chunk_len)) & 0xFFFFFFFF


def crc32_append(crc_old: int, tail: bytes | bytearray) -> int:
    """CRC32 after appending ``tail`` to the message (plain streaming
    continuation — named for symmetry with :func:`crc32_patch`)."""
    return zlib.crc32(bytes(tail), crc_old) & 0xFFFFFFFF
