"""Undo journal — crash atomicity for in-place archive mutation.

Update and append patch chunk files IN PLACE, the one thing the rest of
the stack never does (every other writer goes through ``.rs_tmp`` +
atomic rename).  The journal restores that safety: before any byte of
the archive is overwritten or extended, the OLD bytes of every region
about to change — plus each chunk file's pre-op length — are appended
to ``<archive>.rs_journal`` and fsynced.  The atomic .METADATA rewrite
(generation bump, :func:`..utils.fileformat.rewrite_metadata_lines`) is
the commit point; a successful commit unlinks the journal.

Recovery (:func:`recover`, run at the top of every update/append and on
demand via ``rs update --recover``):

* no journal → nothing pending;
* journal generation != the live metadata generation → the commit
  landed (or a later op superseded it): the journal is stale, discard;
* otherwise the op tore mid-patch: restore every journaled region,
  truncate each chunk back to its pre-op length (rolls back a torn
  APPEND's tail), fsync, discard — the archive is byte-identical to its
  pre-op state.

A torn JOURNAL (crash while writing it) is equally safe: regions are
length-prefixed and applied only when complete, and the engine never
patches a region before its journal record is on disk — an incomplete
tail record means its region was never touched.

On-disk format: line 1 is a JSON header
``{"kind": "rs_update_journal", "generation": G, "op": ..., "chunk_len":
{index: pre_bytes}}``; then per-region records — a 4-byte big-endian
length, a JSON record ``{"chunk": i, "off": o, "len": n}``, and ``n``
raw old bytes.
"""

from __future__ import annotations

import json
import os
import struct

from ..obs import metrics as _metrics
from ..utils.fileformat import (
    chunk_file_name,
    fsync_dir,
    metadata_file_name,
    read_archive_meta,
)


def journal_path(file_name: str) -> str:
    return file_name + ".rs_journal"


class Journal:
    """Writer side: opened by the engine before the first patch."""

    def __init__(self, file_name: str, generation: int, op: str,
                 chunk_len: dict[int, int]):
        self.file_name = file_name
        self.path = journal_path(file_name)
        self.chunk_len = dict(chunk_len)
        self._fp = open(self.path, "wb")
        header = {
            "kind": "rs_update_journal",
            "generation": int(generation),
            "op": op,
            "chunk_len": {str(i): int(n) for i, n in chunk_len.items()},
        }
        self._fp.write((json.dumps(header) + "\n").encode())
        # The journal's DIRENT must be durable before any chunk byte
        # changes: a crash that persisted patches but lost the journal's
        # creation would be unrecoverable.  (Record contents sync per
        # block via sync(); this covers the name itself.)
        fsync_dir(self.path)
        self._dirty = True

    def record(self, chunk: int, off: int, old: bytes) -> None:
        """Queue one region's undo bytes (regions wholly past the chunk's
        pre-op length need no record — truncation undoes them)."""
        if not old:
            return
        rec = json.dumps(
            {"chunk": int(chunk), "off": int(off), "len": len(old)}
        ).encode()
        self._fp.write(struct.pack(">I", len(rec)))
        self._fp.write(rec)
        self._fp.write(old)
        self._dirty = True

    def sync(self) -> bool:
        """Barrier: every queued record is durable before the engine may
        patch the regions it covers.  Returns True when an fsync was
        actually issued (the group-commit fsync accounting reads this)."""
        if self._dirty:
            self._fp.flush()
            os.fsync(self._fp.fileno())
            self._dirty = False
            return True
        return False

    def close(self, *, commit: bool) -> None:
        """``commit=True`` (metadata rename landed) discards the journal;
        ``commit=False`` leaves it for :func:`recover` (a crash path that
        could not roll back in-process)."""
        if not self._fp.closed:
            self._fp.close()
        if commit and os.path.exists(self.path):
            os.unlink(self.path)


def _read_records(path: str):
    """(header, [(chunk, off, old_bytes)]) — complete records only; a
    torn tail record is dropped (its region was never patched)."""
    with open(path, "rb") as fp:
        head_line = fp.readline()
        try:
            header = json.loads(head_line)
        except ValueError:
            return None, []
        if header.get("kind") != "rs_update_journal":
            return None, []
        records = []
        while True:
            raw = fp.read(4)
            if len(raw) < 4:
                break
            (n,) = struct.unpack(">I", raw)
            rec_raw = fp.read(n)
            if len(rec_raw) < n:
                break
            try:
                rec = json.loads(rec_raw)
            except ValueError:
                break
            old = fp.read(rec["len"])
            if len(old) < rec["len"]:
                break
            records.append((int(rec["chunk"]), int(rec["off"]), old))
        return header, records


def recover(file_name: str) -> str:
    """Resolve any pending journal next to ``file_name``; returns one of
    ``none`` / ``stale_discarded`` / ``invalid_discarded`` /
    ``rolled_back``."""
    path = journal_path(file_name)
    if not os.path.exists(path):
        return "none"
    header, records = _read_records(path)
    if header is None:
        os.unlink(path)
        return "invalid_discarded"
    meta_gen = read_archive_meta(metadata_file_name(file_name)).generation
    if int(header.get("generation", -1)) != meta_gen:
        # The op committed (metadata generation moved past the journal's
        # pre-op value) — the journal is a leftover, not a torn write.
        os.unlink(path)
        verdict = "stale_discarded"
    else:
        rollback(file_name, header, records)
        os.unlink(path)
        verdict = "rolled_back"
    _metrics.counter(
        "rs_update_recoveries_total",
        "pending update/append journals resolved at open",
    ).labels(verdict=verdict).inc()
    return verdict


def rollback(file_name: str, header: dict, records) -> None:
    """Apply undo records + pre-op truncation (shared by on-disk recovery
    and the engine's in-process failure path)."""
    by_chunk: dict[int, list] = {}
    for chunk, off, old in records:
        by_chunk.setdefault(chunk, []).append((off, old))
    pre_len = {int(i): int(n) for i, n in header.get("chunk_len", {}).items()}
    for idx in sorted(set(by_chunk) | set(pre_len)):
        path = chunk_file_name(file_name, idx)
        if not os.path.exists(path):
            continue  # damaged independently of the torn op: best effort
        with open(path, "r+b") as fp:
            for off, old in by_chunk.get(idx, ()):
                os.pwrite(fp.fileno(), old, off)
            if idx in pre_len:
                fp.truncate(pre_len[idx])
            fp.flush()
            os.fsync(fp.fileno())
