"""Per-backend GEMM-strategy autotuner — what ``strategy="auto"`` means.

Before this module, ``auto`` was a hard-coded branch (pallas on real TPU
hardware, bitplane elsewhere).  Now every ``auto`` resolution routes
through here, where the XOR-lowered strategy (docs/XOR.md) competes
against table/bitplane/pallas and the native host codec per backend:

* **prior mode** (the default): zero-cost resolution from the static
  per-backend ranking — identical behaviour to the old branch (pallas on
  TPU, bitplane elsewhere) unless a MEASURED decision for this (backend,
  k, p, w) class is already cached in-process, in which case the
  measured winner is used.
* **measure mode** (``RS_STRATEGY_AUTOTUNE=measure``): the first ``auto``
  resolution per (backend, k, p, w) class times every candidate on a
  synthetic encode-shaped stripe (warm-up pass absorbs compiles,
  best-of-reps measured) and caches the winner for the process.  This is
  seconds of one-time work per class — a resident daemon or bench run
  opts in; one-shot CLI invocations keep the free prior.
* ``RS_STRATEGY_AUTOTUNE=off``: always the static prior (escape hatch).

Decisions are process-cached and surfaced via :func:`decisions` (the
``rs doctor`` strategy section and ``rs stats`` read them).  Mesh
dispatches never autotune: the mesh path supports a fixed strategy set
and the collective executable is pinned by its own jit cache.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

__all__ = [
    "VALID_STRATEGIES", "candidate_strategies", "resolve_auto",
    "autotune_decision", "decisions", "clear_decisions", "mode",
    "static_choice",
]

# Every strategy the codec accepts ("auto" resolves to one of the rest).
VALID_STRATEGIES = ("auto", "bitplane", "table", "pallas", "xor", "cpu")

_DECISIONS: dict[tuple, dict] = {}
_LOCK = threading.Lock()
_MEASURE_LOCK = threading.Lock()  # serializes candidate sweeps

_MEASURE_COLS = 256 * 1024  # bytes per chunk in the probe stripe
_MEASURE_REPS = 3


def mode() -> str:
    """``prior`` (default) | ``measure`` | ``off`` from the env knob."""
    v = os.environ.get("RS_STRATEGY_AUTOTUNE", "prior").lower()
    if v in ("measure", "1", "on"):
        return "measure"
    if v in ("off", "0", "false", "no"):
        return "off"
    return "prior"


def _backend() -> str:
    # Through the codec's module-level alias, which is the documented
    # monkeypatch seam for steering strategy selection in tests.
    from .codec import _tpu_devices_present

    return "tpu" if _tpu_devices_present() else "other"


def static_choice(w: int = 8) -> str:
    """The zero-cost prior: the fused kernel on real TPU hardware (the
    reference runs its fast kernel unconditionally, decode.cu:335-378),
    the XLA bitplane path elsewhere."""
    return "pallas" if _backend() == "tpu" else "bitplane"


def candidate_strategies(w: int = 8, *, include_native: bool = True):
    """Strategies ``auto`` may pick on this backend, fastest-prior first."""
    if _backend() == "tpu":
        cands = ["pallas", "bitplane", "xor", "table"]
    else:
        cands = ["bitplane", "xor", "table"]
    if include_native and w == 8:
        from . import native

        if native.available():
            cands.append("cpu")
    return tuple(cands)


def decisions() -> dict:
    """Snapshot of cached autotune decisions (doctor/stats surface)."""
    with _LOCK:
        return {
            "|".join(map(str, key)): dict(val)
            for key, val in _DECISIONS.items()
        }


def clear_decisions() -> None:
    with _LOCK:
        _DECISIONS.clear()


def _measure_one(strategy: str, A, B, w: int) -> float:
    """Best-of-reps wall seconds for one warm strategy dispatch.

    ``B`` arrives where the strategy actually reads it (host array for
    the native codec, device array for the rest) so no arm's timed
    region includes a transfer the production path never pays.
    """
    import jax

    from .ops.gemm import gf_matmul_jit
    from .ops.xor_gemm import gf_matmul_xor

    if strategy == "cpu":
        from . import native

        Ah, Bh = np.asarray(A), np.asarray(B)

        def run():
            return native.gemm(Ah, Bh)

    elif strategy == "xor":

        def run():
            return gf_matmul_xor(A, B, w)

    elif strategy == "pallas":
        from .ops.pallas_gemm import gf_matmul_pallas

        def run():
            return gf_matmul_pallas(A, B, w)

    else:

        def run():
            return gf_matmul_jit(A, B, w=w, strategy=strategy)

    jax.block_until_ready(run())  # absorb compiles
    best = float("inf")
    for _ in range(_MEASURE_REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        best = min(best, time.perf_counter() - t0)
    return best


def autotune_decision(k: int, p: int, w: int = 8,
                      generator: str = "vandermonde") -> dict:
    """Measure every candidate on an encode-shaped stripe and cache the
    winner for this (backend, k, p, w) class.  Failing candidates (e.g.
    pallas off-TPU) are excluded with their error class recorded."""
    import jax

    from .models.vandermonde import generator_matrix
    from .ops.gf import get_field

    backend = _backend()
    key = (backend, k, p, w)
    with _LOCK:
        hit = _DECISIONS.get(key)
    if hit is not None:
        return hit
    # One sweep at a time, re-checked under the lock: concurrent first
    # resolutions of the same class (a daemon's worker pool) must not
    # each burn a multi-second candidate sweep to discard all but one.
    with _MEASURE_LOCK:
        with _LOCK:
            hit = _DECISIONS.get(key)
        if hit is not None:
            return hit
        gf = get_field(w)
        A = generator_matrix(generator, p, k, gf)
        m = max(1, _MEASURE_COLS // int(np.dtype(gf.dtype).itemsize))
        rng = np.random.default_rng(20260804)
        Bh = rng.integers(0, gf.size, size=(k, m)).astype(gf.dtype)
        Bd = jax.device_put(Bh)
        table: dict[str, float | None] = {}
        data_bytes = k * m * int(np.dtype(gf.dtype).itemsize)
        best_name, best_gbps = None, -1.0
        for name in candidate_strategies(w):
            try:
                dt = _measure_one(name, A, Bh if name == "cpu" else Bd, w)
                gbps = data_bytes / dt / 1e9 if dt > 0 else 0.0
                table[name] = round(gbps, 4)
                if gbps > best_gbps:
                    best_name, best_gbps = name, gbps
            except Exception as e:  # candidate unsupported here: skip it
                table[name] = None
                table[f"{name}_error"] = type(e).__name__
        if best_name is None:  # every candidate failed: keep the prior
            best_name = static_choice(w)
        decision = {
            "strategy": best_name,
            "source": "measured",
            "backend": backend,
            "k": k,
            "p": p,
            "w": w,
            "gbps": table,
            "ts": time.time(),
        }
        from .obs import metrics as _metrics

        _metrics.counter(
            "rs_strategy_autotune_total",
            "strategy-autotune measurements by backend and winner",
        ).labels(backend=backend, winner=best_name).inc()
        with _LOCK:
            return _DECISIONS.setdefault(key, decision)


def resolve_auto(k: int, p: int, w: int = 8, *, mesh=None,
                 generator: str = "vandermonde") -> str:
    """Resolve ``strategy="auto"`` for a codec of this shape.

    Mesh codecs and ``off`` mode take the static prior; otherwise a
    cached measured decision wins, and ``measure`` mode creates one on
    first use per (backend, k, p, w) class.
    """
    if mesh is not None or mode() == "off":
        return static_choice(w)
    backend = _backend()
    with _LOCK:
        hit = _DECISIONS.get((backend, k, p, w))
    if hit is not None:
        return hit["strategy"]
    if mode() == "measure":
        return autotune_decision(k, p, w, generator)["strategy"]
    return static_choice(w)
