"""Per-backend GEMM-strategy autotuner — what ``strategy="auto"`` means.

Before this module, ``auto`` was a hard-coded branch (pallas on real TPU
hardware, bitplane elsewhere).  Now every ``auto`` resolution routes
through here, where the XOR-lowered strategy (docs/XOR.md) competes
against table/bitplane/pallas and the native host codec per backend:

* **prior mode** (the default): zero-cost resolution from the static
  per-backend ranking — identical behaviour to the old branch (pallas on
  TPU, bitplane elsewhere) unless a MEASURED decision for this (backend,
  k, p, w) class is already cached in-process, in which case the
  measured winner is used.
* **measure mode** (``RS_STRATEGY_AUTOTUNE=measure``): the first ``auto``
  resolution per (backend, k, p, w) class times every candidate on a
  synthetic encode-shaped stripe (warm-up pass absorbs compiles,
  best-of-reps measured) and caches the winner for the process.  This is
  seconds of one-time work per class — a resident daemon or bench run
  opts in; one-shot CLI invocations keep the free prior.
* ``RS_STRATEGY_AUTOTUNE=off``: always the static prior (escape hatch).

**Persisted decisions** (docs/XOR.md "The persistent store"): measured
verdicts also append a ``kind: "rs_autotune"`` record — keyed (host,
backend, k, p, w) — to the schedule/autotune store
(:func:`..obs.runlog.store_path`, riding the PR 4 run ledger by
default).  A fresh process in the default ``prior`` mode resolves from
the store BEFORE falling back to the static prior, so a restarted
daemon or a new CLI invocation inherits the measured winner without
re-probing (``decisions()`` reports those with ``source: "ledger"``).
``measure`` mode deliberately ignores ledger entries: it re-probes and
overwrites, so a hardware change re-measures on demand.  Resolution
sources are counted in ``rs_autotune_source_total{source}``.

Decisions are process-cached and surfaced via :func:`decisions` (the
``rs doctor`` strategy section and ``rs stats`` read them).  Mesh
dispatches never autotune: the mesh path supports a fixed strategy set
and the collective executable is pinned by its own jit cache.
"""

from __future__ import annotations

import os
import socket
import threading
import time

import numpy as np

__all__ = [
    "VALID_STRATEGIES", "candidate_strategies", "resolve_auto",
    "autotune_decision", "decisions", "clear_decisions", "mode",
    "static_choice",
]

# Every strategy the codec accepts ("auto" resolves to one of the rest).
VALID_STRATEGIES = (
    "auto", "bitplane", "table", "pallas", "xor", "ring", "cpu"
)

_DECISIONS: dict[tuple, dict] = {}
_LOCK = threading.Lock()
_MEASURE_LOCK = threading.Lock()  # serializes candidate sweeps

# (backend, k, p, w) -> persisted rs_autotune record for THIS host, lazy-
# loaded from the store once per process (reset by clear_decisions()).
_LEDGER_INDEX: dict[tuple, dict] | None = None

_MEASURE_COLS = 256 * 1024  # bytes per chunk in the probe stripe
_MEASURE_REPS = 3


def mode() -> str:
    """``prior`` (default) | ``measure`` | ``off`` from the env knob."""
    v = os.environ.get("RS_STRATEGY_AUTOTUNE", "prior").lower()
    if v in ("measure", "1", "on"):
        return "measure"
    if v in ("off", "0", "false", "no"):
        return "off"
    return "prior"


def _backend() -> str:
    # Through the codec's module-level alias, which is the documented
    # monkeypatch seam for steering strategy selection in tests.
    from .codec import _tpu_devices_present

    return "tpu" if _tpu_devices_present() else "other"


def static_choice(w: int = 8) -> str:
    """The zero-cost prior: the fused kernel on real TPU hardware (the
    reference runs its fast kernel unconditionally, decode.cu:335-378),
    the XLA bitplane path elsewhere."""
    return "pallas" if _backend() == "tpu" else "bitplane"


def candidate_strategies(w: int = 8, *, include_native: bool = True):
    """Strategies ``auto`` may pick on this backend, fastest-prior first."""
    if _backend() == "tpu":
        cands = ["pallas", "bitplane", "xor", "table"]
    else:
        cands = ["bitplane", "xor", "table"]
    if w == 8:
        # The ring lowering's p/w plane expansion is 2.125x at w=8 but
        # 16x at w=16 (docs/XOR.md "Ring lowering") — w=16 ring is a
        # correctness rung, never an autotune candidate.
        cands.insert(cands.index("xor") + 1, "ring")
    if include_native and w == 8:
        from . import native

        if native.available():
            cands.append("cpu")
    return tuple(cands)


def decisions() -> dict:
    """Snapshot of cached autotune decisions (doctor/stats surface)."""
    with _LOCK:
        return {
            "|".join(map(str, key)): dict(val)
            for key, val in _DECISIONS.items()
        }


def clear_decisions() -> None:
    global _LEDGER_INDEX
    with _LOCK:
        _DECISIONS.clear()
        _LEDGER_INDEX = None  # re-read the store on next resolution


def _count_source(source: str) -> None:
    from .obs import metrics as _metrics

    _metrics.counter(
        "rs_autotune_source_total",
        "strategy-auto resolutions by decision source",
    ).labels(source=source).inc()


def _rec_ts(rec: dict) -> float:
    try:
        return float(rec.get("ts") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _ledger_decisions() -> dict[tuple, dict]:
    """Persisted autotune verdicts for THIS host, keyed by (backend, k,
    p, w) — the NEWEST timestamp wins (not file order: rotation carries
    old records forward and may interleave them after concurrent fresh
    appends), so a re-measure supersedes old lines.  Malformed records
    are skipped, never fatal (the store is a cache)."""
    global _LEDGER_INDEX
    with _LOCK:
        if _LEDGER_INDEX is not None:
            return _LEDGER_INDEX
    from .obs import runlog as _runlog

    p = _runlog.store_path()
    idx: dict[tuple, dict] = {}
    if p:
        host = socket.gethostname()
        for rec in _runlog.read_records(p):
            if rec.get("kind") != "rs_autotune" or rec.get("host") != host:
                continue
            try:
                key = (str(rec["backend"]), int(rec["k"]), int(rec["p"]),
                       int(rec["w"]))
                strategy = str(rec["strategy"])
            except (KeyError, TypeError, ValueError):
                continue
            if strategy not in VALID_STRATEGIES or strategy == "auto":
                continue
            cur = idx.get(key)
            if cur is None or _rec_ts(rec) >= _rec_ts(cur):
                idx[key] = rec
    with _LOCK:
        if _LEDGER_INDEX is None:
            _LEDGER_INDEX = idx
        return _LEDGER_INDEX


def _persist_decision(decision: dict) -> None:
    """Best-effort append of a measured verdict to the store."""
    from .obs import runlog as _runlog

    p = _runlog.store_path()
    if not p:
        return
    rec = {
        "kind": "rs_autotune",
        "schema": _runlog.SCHEMA_VERSION,
        "host": socket.gethostname(),
        "backend": decision["backend"],
        "k": decision["k"],
        "p": decision["p"],
        "w": decision["w"],
        "strategy": decision["strategy"],
        "gbps": decision["gbps"],
        "ts": decision["ts"],
        "run": _runlog.run_id(),
    }
    _runlog.append(rec, p)
    key = (decision["backend"], decision["k"], decision["p"],
           decision["w"])
    with _LOCK:
        if _LEDGER_INDEX is not None:
            _LEDGER_INDEX[key] = rec


def _measure_one(strategy: str, A, B, w: int) -> float:
    """Best-of-reps wall seconds for one warm strategy dispatch.

    ``B`` arrives where the strategy actually reads it (host array for
    the native codec, device array for the rest) so no arm's timed
    region includes a transfer the production path never pays.
    """
    import jax

    from .ops.gemm import gf_matmul_jit
    from .ops.xor_gemm import gf_matmul_xor

    if strategy == "cpu":
        from . import native

        Ah, Bh = np.asarray(A), np.asarray(B)

        def run():
            return native.gemm(Ah, Bh)

    elif strategy == "xor":

        def run():
            return gf_matmul_xor(A, B, w)

    elif strategy == "ring":
        from .ops.ring_gemm import gf_matmul_ring

        def run():
            return gf_matmul_ring(A, B, w)

    elif strategy == "pallas":
        from .ops.pallas_gemm import gf_matmul_pallas

        def run():
            return gf_matmul_pallas(A, B, w)

    else:

        def run():
            return gf_matmul_jit(A, B, w=w, strategy=strategy)

    jax.block_until_ready(run())  # absorb compiles
    best = float("inf")
    for _ in range(_MEASURE_REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        best = min(best, time.perf_counter() - t0)
    return best


def autotune_decision(k: int, p: int, w: int = 8,
                      generator: str = "vandermonde") -> dict:
    """Measure every candidate on an encode-shaped stripe and cache the
    winner for this (backend, k, p, w) class.  Failing candidates (e.g.
    pallas off-TPU) are excluded with their error class recorded."""
    import jax

    from .models.vandermonde import generator_matrix
    from .ops.gf import get_field

    backend = _backend()
    key = (backend, k, p, w)
    # A ledger-sourced cache entry never satisfies an explicit measure:
    # re-probing (and overwriting the persisted record) is the measure
    # contract — it is how a hardware change invalidates old verdicts.
    with _LOCK:
        hit = _DECISIONS.get(key)
    if hit is not None and hit.get("source") == "measured":
        return hit
    # One sweep at a time, re-checked under the lock: concurrent first
    # resolutions of the same class (a daemon's worker pool) must not
    # each burn a multi-second candidate sweep to discard all but one.
    with _MEASURE_LOCK:
        with _LOCK:
            hit = _DECISIONS.get(key)
        if hit is not None and hit.get("source") == "measured":
            return hit
        gf = get_field(w)
        A = generator_matrix(generator, p, k, gf)
        m = max(1, _MEASURE_COLS // int(np.dtype(gf.dtype).itemsize))
        rng = np.random.default_rng(20260804)
        Bh = rng.integers(0, gf.size, size=(k, m)).astype(gf.dtype)
        Bd = jax.device_put(Bh)
        table: dict[str, float | None] = {}
        data_bytes = k * m * int(np.dtype(gf.dtype).itemsize)
        best_name, best_gbps = None, -1.0
        for name in candidate_strategies(w):
            try:
                dt = _measure_one(name, A, Bh if name == "cpu" else Bd, w)
                gbps = data_bytes / dt / 1e9 if dt > 0 else 0.0
                table[name] = round(gbps, 4)
                if gbps > best_gbps:
                    best_name, best_gbps = name, gbps
            except Exception as e:  # candidate unsupported here: skip it
                table[name] = None
                table[f"{name}_error"] = type(e).__name__
        if best_name is None:  # every candidate failed: keep the prior
            best_name = static_choice(w)
        decision = {
            "strategy": best_name,
            "source": "measured",
            "backend": backend,
            "k": k,
            "p": p,
            "w": w,
            "gbps": table,
            "ts": time.time(),
        }
        from .obs import metrics as _metrics

        _metrics.counter(
            "rs_strategy_autotune_total",
            "strategy-autotune measurements by backend and winner",
        ).labels(backend=backend, winner=best_name).inc()
        _count_source("measured")
        _persist_decision(decision)
        with _LOCK:
            _DECISIONS[key] = decision  # overwrite a ledger-sourced entry
            return decision


def resolve_auto(k: int, p: int, w: int = 8, *, mesh=None,
                 generator: str = "vandermonde") -> str:
    """Resolve ``strategy="auto"`` for a codec of this shape.

    Mesh codecs and ``off`` mode take the static prior; otherwise a
    cached measured decision wins, then — in the default ``prior`` mode
    — a decision persisted in the schedule/autotune store for this
    (host, backend, k, p, w) class (``source: "ledger"``), then the
    static prior.  ``measure`` mode re-probes instead of trusting the
    ledger and overwrites its record.
    """
    if mesh is not None or mode() == "off":
        return static_choice(w)
    backend = _backend()
    key = (backend, k, p, w)
    with _LOCK:
        hit = _DECISIONS.get(key)
    if hit is not None and (
        mode() != "measure" or hit.get("source") == "measured"
    ):
        _count_source(hit.get("source") or "measured")
        return hit["strategy"]
    if mode() == "measure":
        return autotune_decision(k, p, w, generator)["strategy"]
    led = _ledger_decisions().get(key)
    if led is not None and led["strategy"] not in candidate_strategies(w):
        # The persisted winner is no longer runnable here (the native
        # codec was removed, a TPU host became CPU-only): a stale
        # verdict must not silently route every op onto a fallback
        # path.  Fall through to the static prior; measure mode
        # re-probes and overwrites when asked.
        led = None
    if led is not None:
        decision = {
            "strategy": led["strategy"],
            "source": "ledger",
            "backend": backend,
            "k": k,
            "p": p,
            "w": w,
            "gbps": led.get("gbps"),
            "ts": led.get("ts"),
        }
        with _LOCK:
            decision = _DECISIONS.setdefault(key, decision)
        _count_source("ledger")
        return decision["strategy"]
    _count_source("prior")
    return static_choice(w)
