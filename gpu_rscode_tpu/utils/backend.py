"""Backend identity helpers shared by strategy selection and kernels.

One definition of "are we on real TPU hardware": by DEVICE PLATFORM first,
backend name second.  A tunnel plugin (axon) may register under its own
backend name while serving genuine TPU chips; any code that gates on
``jax.default_backend() == "tpu"`` alone silently misroutes such hardware
(interpret-mode kernels, bitplane fallbacks).  Keep every TPU check on this
helper so the next tunnel quirk is fixed in exactly one place.
"""

from __future__ import annotations


def tpu_devices_present() -> bool:
    """True when the default backend's devices are real TPU chips."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    try:
        return any(d.platform.lower() == "tpu" for d in jax.devices())
    except Exception:  # uninitialisable backend: treat as no TPU
        return False


def backend_label() -> str:
    """Metric/artifact label for the current backend: "tpu" whenever the
    devices are real TPU chips (whatever name the plugin registered),
    else the backend's own name."""
    import jax

    return "tpu" if tpu_devices_present() else jax.default_backend()
