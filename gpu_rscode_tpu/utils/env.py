"""Tolerant numeric env parsing — the one copy of try/cast/default.

Every knob-reading module used to grow its own private ``_int_env`` /
``_float_env``; a malformed value must select the DEFAULT, never crash
an operation mid-flight (the same tolerance ``retry.int_env``
established).  Import cost: stdlib only.
"""

from __future__ import annotations

import os


def int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default
