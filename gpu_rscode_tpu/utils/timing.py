"""Phase timing / observability.

Capability parity with the reference's cudaEvent step timing + aggregate
"total computation" vs "total communication" report (encode.cu:111-163,
227-232, 254-277; cpu-rs.c:523-532) — reimagined for an async runtime:
device work is timed by bracketing ``block_until_ready`` fences around
phases, host IO by wall clock.  The report keeps the reference's
computation/communication split so numbers are comparable.

Phases integrate with the unified observability layer (``..obs``): when a
tracing session is active (``RS_TRACE``), every timed phase also lands as
a span on the ``phase`` lane of the exported Perfetto trace — the timer
stays the human-readable report, the trace the per-event timeline.

For deep profiling use ``jax.profiler.trace`` via the ``profile_dir``
option on the file APIs (the TPU-native answer to nvprof/ptxas stats).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager

from ..obs import tracing as _tracing


class PhaseTimer:
    """Accumulates named phase durations.

    Communication phases are identified by an explicit parenthesized tag
    suffix — ``"stage segment (io)"`` — checked against :data:`COMM_TAGS`
    exactly, never by substring (a phase merely *containing* "io", like
    "dispatch ratio" or "prioritize", must not silently count as
    communication).
    """

    # Comm-tag vocabulary: a phase named "... (<tag>)" with <tag> in this
    # set counts as communication; everything else is computation.
    COMM_TAGS = frozenset({"io", "transfer", "stage"})

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.acc: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.best: dict[str, float] = {}  # per-phase minimum duration
        # Phases land from three threads at once (dispatch, prefetch
        # worker, write-behind drain); += on the dicts is read-modify-write.
        self._rec_lock = threading.Lock()
        self._t0 = time.perf_counter()

    @classmethod
    def is_comm(cls, name: str) -> bool:
        """Exact comm-tag classification (see class docstring)."""
        if not name.endswith(")") or "(" not in name:
            return False
        return name[name.rfind("(") + 1 : -1] in cls.COMM_TAGS

    def _record(self, name: str, dt: float) -> None:
        with self._rec_lock:
            self.acc[name] += dt
            self.counts[name] += 1
            prev = self.best.get(name)
            if prev is None or dt < prev:
                self.best[name] = dt

    @contextmanager
    def phase(self, name: str):
        if not self.enabled:
            # A disabled timer never accumulates, but an active RS_TRACE
            # session still gets the phase span — the file APIs default to
            # a disabled timer, and the trace must not go blind there.
            if _tracing.active() is None:
                yield
                return
            with _tracing.span(
                name, lane="phase:" + threading.current_thread().name
            ):
                yield
            return
        t = time.perf_counter()
        try:
            # Lane per thread: the prefetch worker's IO phases overlap the
            # consumer's compute phases; same-lane X events must nest.
            with _tracing.span(
                name, lane="phase:" + threading.current_thread().name
            ):
                yield
        finally:
            self._record(name, time.perf_counter() - t)

    def add(self, name: str, seconds: float) -> None:
        """Record an externally measured duration (same accounting as a
        :meth:`phase` block).  Honours ``enabled`` — a disabled timer must
        never mutate its accumulators."""
        if not self.enabled:
            return
        self._record(name, seconds)

    @property
    def total(self) -> float:
        return time.perf_counter() - self._t0

    def phase_report(self) -> dict[str, float]:
        """Accumulated seconds per phase, snapshotted under the recording
        lock — the per-phase decomposition the run ledger (obs/runlog.py)
        embeds in each record."""
        with self._rec_lock:
            return {name: round(v, 6) for name, v in self.acc.items()}

    def summary(self, data_bytes: int | None = None) -> str:
        comm = sum(v for k, v in self.acc.items() if self.is_comm(k))
        comp = sum(v for k, v in self.acc.items() if not self.is_comm(k))
        lines = [
            f"  {name}: {1e3 * v:.3f} ms  (x{self.counts[name]})"
            for name, v in sorted(self.acc.items())
        ]
        lines.append(f"  total computation: {1e3 * comp:.3f} ms")
        lines.append(f"  total communication: {1e3 * comm:.3f} ms")
        lines.append(f"  total wall: {1e3 * self.total:.3f} ms")
        if data_bytes is not None and self.total > 0:
            lines.append(f"  throughput: {data_bytes / self.total / 1e9:.3f} GB/s")
        return "\n".join(lines)
