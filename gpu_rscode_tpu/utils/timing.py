"""Phase timing / observability.

Capability parity with the reference's cudaEvent step timing + aggregate
"total computation" vs "total communication" report (encode.cu:111-163,
227-232, 254-277; cpu-rs.c:523-532) — reimagined for an async runtime:
device work is timed by bracketing ``block_until_ready`` fences around
phases, host IO by wall clock.  The report keeps the reference's
computation/communication split so numbers are comparable.

For deep profiling use ``jax.profiler.trace`` via the ``profile_dir``
option on the file APIs (the TPU-native answer to nvprof/ptxas stats).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class PhaseTimer:
    """Accumulates named phase durations; phases tagged 'io'/'transfer' count
    as communication, the rest as computation."""

    COMM_PHASES = ("read", "write", "transfer", "io", "stage")

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.acc: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self._t0 = time.perf_counter()

    @contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        t = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t
            self.acc[name] += dt
            self.counts[name] += 1

    def add(self, name: str, seconds: float) -> None:
        self.acc[name] += seconds
        self.counts[name] += 1

    @property
    def total(self) -> float:
        return time.perf_counter() - self._t0

    def summary(self, data_bytes: int | None = None) -> str:
        comm = sum(v for k, v in self.acc.items() if any(t in k for t in self.COMM_PHASES))
        comp = sum(v for k, v in self.acc.items() if not any(t in k for t in self.COMM_PHASES))
        lines = [
            f"  {name}: {1e3 * v:.3f} ms  (x{self.counts[name]})"
            for name, v in sorted(self.acc.items())
        ]
        lines.append(f"  total computation: {1e3 * comp:.3f} ms")
        lines.append(f"  total communication: {1e3 * comm:.3f} ms")
        lines.append(f"  total wall: {1e3 * self.total:.3f} ms")
        if data_bytes is not None and self.total > 0:
            lines.append(f"  throughput: {data_bytes / self.total / 1e9:.3f} GB/s")
        return "\n".join(lines)
