"""On-disk formats: chunk files, .METADATA, conf files.

Byte-compatible with the reference formats (the durable state the encode and
decode *processes* exchange — SURVEY/reference: ``encode.cu:61-101`` writes
METADATA, ``encode.cu:434-465`` writes chunks, ``decode.cu:257-319`` parses
both plus the conf file):

* chunk file ``_<i>_<fileName>``, i in [0, n): i < k natives, i >= k parity;
  each holds exactly ``chunk_size = ceil(total_size / k)`` bytes (tail chunk
  zero-padded — deterministic, unlike the reference's uninitialised-heap
  padding, encode.cu:325-330).
* ``<fileName>.METADATA`` text: line 1 ``totalSize``; line 2
  ``parityBlockNum nativeBlockNum``; then (k+p) rows x k cols of the total
  encoding matrix, identity block first, each entry "%d " and "\n" per row.
* conf file: k lines, each a surviving chunk filename; the row index is the
  integer parsed from the digits immediately after the FIRST character
  (the reference does ``atoi(name + 1)``, decode.cu:305).
"""

from __future__ import annotations

import os
import re

import numpy as np


def chunk_file_name(file_name: str, index: int) -> str:
    """``_<i>_<basename>`` next to ``file_name``."""
    d, base = os.path.split(file_name)
    return os.path.join(d, f"_{index}_{base}")


def metadata_file_name(file_name: str) -> str:
    return file_name + ".METADATA"


def chunk_size_for(total_size: int, native_num: int) -> int:
    return -(-total_size // native_num)  # ceil


def write_metadata(path: str, total_size: int, parity_num: int, native_num: int, total_mat: np.ndarray) -> None:
    rows = native_num + parity_num
    assert total_mat.shape == (rows, native_num), total_mat.shape
    with open(path, "w") as fp:
        fp.write(f"{total_size}\n")
        fp.write(f"{parity_num} {native_num}\n")
        for i in range(rows):
            fp.write("".join(f"{int(v)} " for v in total_mat[i]) + "\n")


def read_metadata(path: str) -> tuple[int, int, int, np.ndarray]:
    """Returns (total_size, parity_num, native_num, total_matrix)."""
    with open(path) as fp:
        tokens = fp.read().split()
    if len(tokens) < 3:
        raise ValueError(f"malformed metadata file {path!r}")
    total_size, parity_num, native_num = int(tokens[0]), int(tokens[1]), int(tokens[2])
    want = (native_num + parity_num) * native_num
    mat_tokens = tokens[3 : 3 + want]
    if len(mat_tokens) != want:
        raise ValueError(
            f"metadata matrix truncated: expected {want} entries, got {len(mat_tokens)}"
        )
    mat = np.array([int(t) for t in mat_tokens], dtype=np.uint8).reshape(
        native_num + parity_num, native_num
    )
    return total_size, parity_num, native_num, mat


def parse_chunk_index(name: str) -> int:
    """Row index from a chunk file name: integer digits right after the first
    character (reference semantics: ``atoi(name + 1)``)."""
    base = os.path.basename(name)
    m = re.match(r"\d+", base[1:])
    if not m:
        raise ValueError(f"cannot parse chunk index from {name!r}")
    return int(m.group(0))


def write_conf(path: str, chunk_names: list[str]) -> None:
    with open(path, "w") as fp:
        for name in chunk_names:
            fp.write(name + "\n")


def read_conf(path: str) -> list[str]:
    with open(path) as fp:
        return [line.strip() for line in fp if line.strip()]
