"""On-disk formats: chunk files, .METADATA, conf files.

Byte-compatible with the reference formats (the durable state the encode and
decode *processes* exchange — SURVEY/reference: ``encode.cu:61-101`` writes
METADATA, ``encode.cu:434-465`` writes chunks, ``decode.cu:257-319`` parses
both plus the conf file):

* chunk file ``_<i>_<fileName>``, i in [0, n): i < k natives, i >= k parity;
  each holds exactly ``chunk_size = ceil(total_size / k)`` bytes (tail chunk
  zero-padded — deterministic, unlike the reference's uninitialised-heap
  padding, encode.cu:325-330).
* ``<fileName>.METADATA`` text: line 1 ``totalSize``; line 2
  ``parityBlockNum nativeBlockNum``; then (k+p) rows x k cols of the total
  encoding matrix, identity block first, each entry "%d " and "\n" per row.
* conf file: k lines, each a surviving chunk filename; the row index is the
  integer parsed from the digits immediately after the FIRST character
  (the reference does ``atoi(name + 1)``, decode.cu:305).
"""

from __future__ import annotations

import os
import re

import numpy as np


def chunk_file_name(file_name: str, index: int) -> str:
    """``_<i>_<basename>`` next to ``file_name``."""
    d, base = os.path.split(file_name)
    return os.path.join(d, f"_{index}_{base}")


def metadata_file_name(file_name: str) -> str:
    return file_name + ".METADATA"


def chunk_size_for(total_size: int, native_num: int) -> int:
    return -(-total_size // native_num)  # ceil


def write_metadata(path: str, total_size: int, parity_num: int, native_num: int, total_mat: np.ndarray) -> None:
    rows = native_num + parity_num
    assert total_mat.shape == (rows, native_num), total_mat.shape
    with open(path, "w") as fp:
        fp.write(f"{total_size}\n")
        fp.write(f"{parity_num} {native_num}\n")
        for i in range(rows):
            fp.write("".join(f"{int(v)} " for v in total_mat[i]) + "\n")


def read_metadata(path: str) -> tuple[int, int, int, np.ndarray]:
    """Returns (total_size, parity_num, native_num, total_matrix)."""
    with open(path) as fp:
        tokens = fp.read().split()
    if len(tokens) < 3:
        raise ValueError(f"malformed metadata file {path!r}")
    total_size, parity_num, native_num = int(tokens[0]), int(tokens[1]), int(tokens[2])
    want = (native_num + parity_num) * native_num
    mat_tokens = tokens[3 : 3 + want]
    if len(mat_tokens) != want:
        raise ValueError(
            f"metadata matrix truncated: expected {want} entries, got {len(mat_tokens)}"
        )
    mat = np.array([int(t) for t in mat_tokens], dtype=np.uint8).reshape(
        native_num + parity_num, native_num
    )
    return total_size, parity_num, native_num, mat


def append_checksums(path: str, crcs: dict[int, int]) -> None:
    """Append per-chunk CRC32 lines to an existing .METADATA file.

    Extension over the reference format (it has no integrity checking —
    SURVEY §5 "failure detection"): lines ``# crc32 <chunk_index> <8-hex>``
    AFTER the matrix block.  Backwards/forwards compatible both ways: the
    reference's parser (decode.cu:257-282) reads a fixed token count and
    never reaches these lines, and :func:`read_metadata` slices exactly the
    matrix tokens.
    """
    with open(path, "a") as fp:
        for i in sorted(crcs):
            fp.write(f"# crc32 {i} {crcs[i] & 0xFFFFFFFF:08x}\n")


def read_checksums(path: str) -> dict[int, int]:
    """Parse ``# crc32`` extension lines from .METADATA ({} if absent).

    Malformed extension lines (bit-rot, foreign comments starting with
    ``# crc32``) are skipped rather than fatal: a broken checksum LINE must
    not make decode harder than a broken chunk — the corresponding chunk
    simply goes unverified.
    """
    crcs: dict[int, int] = {}
    with open(path) as fp:
        for line in fp:
            parts = line.split()
            if (
                len(parts) == 4
                and parts[:2] == ["#", "crc32"]
                and parts[2].isdigit()
                and len(parts[3]) == 8
                and all(c in "0123456789abcdefABCDEF" for c in parts[3])
            ):
                crcs[int(parts[2])] = int(parts[3], 16)
    return crcs


def crc32_of(buf, crc: int = 0) -> int:
    """Incremental CRC32 (zlib polynomial) over bytes-like / ndarray data."""
    import zlib

    if isinstance(buf, (bytes, bytearray, memoryview)):
        return zlib.crc32(buf, crc)  # no copy; also correct for b""
    return zlib.crc32(memoryview(np.ascontiguousarray(buf)).cast("B"), crc)


def parse_chunk_index(name: str) -> int:
    """Row index from a chunk file name: integer digits right after the first
    character (reference semantics: ``atoi(name + 1)``)."""
    base = os.path.basename(name)
    m = re.match(r"\d+", base[1:])
    if not m:
        raise ValueError(f"cannot parse chunk index from {name!r}")
    return int(m.group(0))


def write_conf(path: str, chunk_names: list[str]) -> None:
    with open(path, "w") as fp:
        for name in chunk_names:
            fp.write(name + "\n")


def read_conf(path: str) -> list[str]:
    with open(path) as fp:
        return [line.strip() for line in fp if line.strip()]
