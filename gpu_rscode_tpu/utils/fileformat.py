"""On-disk formats: chunk files, .METADATA, conf files.

Byte-compatible with the reference formats (the durable state the encode and
decode *processes* exchange — SURVEY/reference: ``encode.cu:61-101`` writes
METADATA, ``encode.cu:434-465`` writes chunks, ``decode.cu:257-319`` parses
both plus the conf file):

* chunk file ``_<i>_<fileName>``, i in [0, n): i < k natives, i >= k parity;
  each holds exactly ``chunk_size = ceil(total_size / k)`` bytes (tail chunk
  zero-padded — deterministic, unlike the reference's uninitialised-heap
  padding, encode.cu:325-330).
* ``<fileName>.METADATA`` text: line 1 ``totalSize``; line 2
  ``parityBlockNum nativeBlockNum``; then (k+p) rows x k cols of the total
  encoding matrix, identity block first, each entry "%d " and "\n" per row.
* conf file: k lines, each a surviving chunk filename; the row index is the
  integer parsed from the digits immediately after the FIRST character
  (the reference does ``atoi(name + 1)``, decode.cu:305).
"""

from __future__ import annotations

import os
import re

import numpy as np


def chunk_file_name(file_name: str, index: int) -> str:
    """``_<i>_<basename>`` next to ``file_name``."""
    d, base = os.path.split(file_name)
    return os.path.join(d, f"_{index}_{base}")


def metadata_file_name(file_name: str) -> str:
    return file_name + ".METADATA"


def chunk_size_for(total_size: int, native_num: int, sym: int = 1) -> int:
    """Bytes per chunk: ceil(total/k), rounded up to the symbol size
    (``sym`` = 2 for GF(2^16) file coding so every chunk holds whole
    symbols; 1 = reference-compatible GF(2^8) layout)."""
    chunk = -(-total_size // native_num)  # ceil
    return -(-chunk // sym) * sym


def chunk_size_for_layout(
    total_size: int, native_num: int, sym: int = 1, layout: str = "row"
) -> int:
    """Bytes per chunk under either chunk layout.

    ``row`` (reference-compatible): chunk i holds the contiguous file
    range [i*chunk, (i+1)*chunk).  ``interleaved`` (extension, recorded
    as ``# layout interleaved`` in .METADATA): file symbol s lives in row
    ``s % k`` at column ``s // k``, so every chunk holds
    ``ceil(total / (k*sym))`` symbols and APPENDING to the file only
    touches the tail column block of every chunk — the append-mode
    layout (docs/UPDATE.md)."""
    if layout == "interleaved":
        if total_size == 0:
            return 0
        cols = -(-total_size // (native_num * sym))  # ceil, in symbols
        return cols * sym
    return chunk_size_for(total_size, native_num, sym)


def fsync_dir(path: str) -> None:
    """fsync the directory CONTAINING ``path`` (best-effort: some
    filesystems refuse O_RDONLY dir fds).  POSIX gives renames/unlinks
    no durability ordering without it — the update/append commit
    protocol needs the .METADATA rename on disk before the undo journal
    may disappear (docs/UPDATE.md)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_metadata(
    path: str,
    total_size: int,
    parity_num: int,
    native_num: int,
    total_mat: np.ndarray,
    w: int = 8,
    layout: str = "row",
) -> None:
    rows = native_num + parity_num
    assert total_mat.shape == (rows, native_num), total_mat.shape
    with open(path, "w") as fp:
        fp.write(f"{total_size}\n")
        fp.write(f"{parity_num} {native_num}\n")
        for i in range(rows):
            fp.write("".join(f"{int(v)} " for v in total_mat[i]) + "\n")
        if w != 8:
            # Wide-symbol extension line (same trailing-comment scheme as the
            # CRC32 lines: invisible to the fixed-token reference parser).
            fp.write(f"# gfwidth {w}\n")
        if layout != "row":
            # Chunk-layout extension (docs/UPDATE.md): interleaved archives
            # support unbounded `rs append`.  Absent == the reference's
            # row-contiguous striping, keeping base encodes byte-identical.
            fp.write(f"# layout {layout}\n")


def _parse_field_width(text: str) -> int:
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[:2] == ["#", "gfwidth"] and parts[2].isdigit():
            return int(parts[2])
    return 8


def _parse_layout(text: str) -> str:
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[:2] == ["#", "layout"]:
            if parts[2] not in ("row", "interleaved"):
                raise ValueError(
                    f"unsupported chunk layout {parts[2]!r} "
                    "(this build handles row and interleaved)"
                )
            return parts[2]
    return "row"


def _parse_generation(text: str) -> int:
    for line in text.splitlines():
        parts = line.split()
        if (
            len(parts) == 3
            and parts[:2] == ["#", "generation"]
            and parts[2].isdigit()
        ):
            return int(parts[2])
    return 0


def read_layout(path: str) -> str:
    """Chunk layout of a metadata file: the ``# layout`` extension line,
    or ``row`` (the reference's only layout) when absent."""
    with open(path) as fp:
        return _parse_layout(fp.read())


class ArchiveMeta:
    """One-read view of an archive's .METADATA including every extension
    line — the object the update/append subsystem (and layout-aware
    decode paths) work from.  ``read_metadata_ext`` keeps its 6-tuple
    surface for the base-format callers."""

    __slots__ = (
        "path", "total_size", "parity_num", "native_num", "total_mat",
        "w", "crcs", "layout", "generation",
    )

    def __init__(self, path, total_size, parity_num, native_num, total_mat,
                 w, crcs, layout, generation):
        self.path = path
        self.total_size = total_size
        self.parity_num = parity_num
        self.native_num = native_num
        self.total_mat = total_mat
        self.w = w
        self.crcs = crcs
        self.layout = layout
        self.generation = generation

    @property
    def sym(self) -> int:
        return self.w // 8

    @property
    def chunk(self) -> int:
        return chunk_size_for_layout(
            self.total_size, self.native_num, self.sym, self.layout
        )


def read_archive_meta(path: str) -> ArchiveMeta:
    """Parse .METADATA into an :class:`ArchiveMeta` (base fields plus the
    ``# gfwidth`` / ``# crc32`` / ``# layout`` / ``# generation``
    extension lines)."""
    with open(path) as fp:
        text = fp.read()
    total_size, parity_num, native_num, mat = _parse_metadata(text, path)
    w = _parse_field_width(text)
    # Width-aware chunk cap (the parse-time cap only enforces the widest
    # field's 65536): a w=8 header declaring n > 256 would regenerate a
    # Vandermonde with repeated evaluation points — singular submatrices
    # and wrong recoveries, not a clear error.
    if native_num + parity_num > (1 << w):
        raise ValueError(
            f"metadata declares n={native_num + parity_num} chunks in "
            f"{path!r} but GF(2^{w}) supports at most {1 << w}"
        )
    return ArchiveMeta(
        path, total_size, parity_num, native_num, mat, w,
        _parse_checksums(text), _parse_layout(text), _parse_generation(text),
    )


def read_field_width(path: str) -> int:
    """GF width of a metadata file: the ``# gfwidth`` extension line, or 8
    (the reference's only width) when absent."""
    with open(path) as fp:
        return _parse_field_width(fp.read())


def read_metadata_ext(path: str):
    """One-read parse of .METADATA including extension lines.

    Returns ``(total_size, parity_num, native_num, total_matrix, w, crcs)``
    — the base-format fields plus the ``# gfwidth`` width (8 when absent)
    and the ``# crc32`` checksum dict ({} when absent).  Thin 6-tuple shim
    over :func:`read_archive_meta` (the one parse pipeline) for callers
    that predate the layout/generation extensions."""
    m = read_archive_meta(path)
    return (m.total_size, m.parity_num, m.native_num, m.total_mat, m.w,
            m.crcs)


def read_metadata(path: str) -> tuple[int, int, int, np.ndarray | None]:
    """Returns (total_size, parity_num, native_num, total_matrix).

    ``total_matrix`` is None for the reference's sizes-only CPU-RS
    metadata dialect (the caller regenerates the canonical Vandermonde
    total matrix — see :func:`_parse_metadata`)."""
    with open(path) as fp:
        return _parse_metadata(fp.read(), path)


def _parse_metadata(text: str, path: str):
    # Base tokens exclude extension/comment lines ("#"-prefixed) wherever
    # they appear.
    tokens: list[str] = []
    for line in text.splitlines():
        if line.lstrip().startswith("#"):
            continue
        tokens += line.split()
    if len(tokens) < 3:
        raise ValueError(f"malformed metadata file {path!r}")
    total_size, parity_num, native_num = int(tokens[0]), int(tokens[1]), int(tokens[2])
    # A corrupt or hostile header must fail HERE with a clear message, not
    # as a ZeroDivisionError in chunk sizing or a bogus reshape later.
    # total_size == 0 is a VALID foreign archive: the reference encoder
    # sizes by ftell with no empty-file guard (cpu-rs.c:492-495,
    # encode.cu's analogous stat), so an empty input yields totalSize=0
    # metadata with zero-byte chunks; decode has a zero-size fast path.
    if total_size < 0 or parity_num <= 0 or native_num <= 0:
        raise ValueError(
            f"metadata fields out of range in {path!r}: size={total_size} "
            f"p={parity_num} k={native_num} (size >= 0, p/k > 0)"
        )
    if native_num + parity_num > 65536:
        raise ValueError(
            f"metadata declares n={native_num + parity_num} chunks in "
            f"{path!r}; the widest supported field (GF(2^16)) caps n at 65536"
        )
    want = (native_num + parity_num) * native_num
    if len(tokens) == 3:
        # The reference's CPU-RS dialect: sizes only, no matrix — decode
        # regenerates the canonical [I; Vandermonde] deterministically
        # (cpu-rs.c write_metadata:465-476 / gen_total_encoding_matrix:621).
        return total_size, parity_num, native_num, None
    mat_tokens = tokens[3 : 3 + want]
    if len(mat_tokens) != want:
        raise ValueError(
            f"metadata matrix truncated: expected {want} entries, got {len(mat_tokens)}"
        )
    vals = [int(t) for t in mat_tokens]
    if min(vals) < 0 or max(vals) > 65535:
        raise ValueError(
            f"metadata matrix entry out of range in {path!r}: "
            f"[{min(vals)}, {max(vals)}] outside [0, 65535]"
        )
    # uint16 when any entry exceeds a byte (GF(2^16) extension metadata);
    # the reference's GF(2^8) files always fit uint8.
    dtype = np.uint16 if max(vals) > 255 else np.uint8
    mat = np.array(vals, dtype=dtype).reshape(
        native_num + parity_num, native_num
    )
    return total_size, parity_num, native_num, mat


def append_checksums(path: str, crcs: dict[int, int]) -> None:
    """Append per-chunk CRC32 lines to an existing .METADATA file.

    Extension over the reference format (it has no integrity checking —
    SURVEY §5 "failure detection"): lines ``# crc32 <chunk_index> <8-hex>``
    AFTER the matrix block.  Backwards/forwards compatible both ways: the
    reference's parser (decode.cu:257-282) reads a fixed token count and
    never reaches these lines, and :func:`read_metadata` slices exactly the
    matrix tokens.
    """
    with open(path, "a") as fp:
        for i in sorted(crcs):
            fp.write(f"# crc32 {i} {crcs[i] & 0xFFFFFFFF:08x}\n")


def rewrite_metadata_lines(
    path: str,
    *,
    total_size: int | None = None,
    crcs: dict[int, int] | None = None,
    bump_generation: bool = False,
) -> int:
    """Crash-safe in-place .METADATA mutation: write-temp + fsync + atomic
    rename (docs/UPDATE.md).  Optionally replaces the totalSize line
    (append grows it), replaces ALL ``# crc32`` lines with ``crcs``
    (None keeps the existing lines), and bumps the monotonic
    ``# generation`` counter (update/append commits).  Every other line —
    the base format, ``# gfwidth``, ``# layout`` — is preserved
    byte-for-byte.  Returns the generation recorded.

    The fsync-before-rename is the fix for the wholesale-rewrite torn-
    metadata window: a crash between write and rename leaves either the
    complete old file or the complete new one, never a torn .METADATA —
    and decode/scrub never read the ``.tmp`` name, so a stale temp from
    a crashed rewrite is inert until the next rewrite replaces it.
    """
    with open(path) as fp:
        lines = fp.readlines()
    generation = _parse_generation("".join(lines))
    if bump_generation:
        generation += 1
    kept = []
    for ln in lines:
        head = ln.split()[:2]
        if head == ["#", "generation"]:
            continue
        if crcs is not None and head == ["#", "crc32"]:
            continue
        kept.append(ln)
    if total_size is not None:
        kept[0] = f"{total_size}\n"
    tmp = path + ".tmp"
    with open(tmp, "w") as fp:
        fp.writelines(kept)
        if crcs is not None:
            for i in sorted(crcs):
                fp.write(f"# crc32 {i} {crcs[i] & 0xFFFFFFFF:08x}\n")
        if generation:
            fp.write(f"# generation {generation}\n")
        fp.flush()
        os.fsync(fp.fileno())
    os.replace(tmp, path)
    # Make the rename itself durable: the caller's next step may unlink
    # the undo journal, and a power cut must never persist that unlink
    # while losing this rename (the torn state recovery couldn't see).
    fsync_dir(path)
    return generation


def rewrite_checksums(path: str, crcs: dict[int, int]) -> None:
    """Replace ALL ``# crc32`` lines of a metadata file with ``crcs``
    (repair refreshes rebuilt chunks' CRCs; other extension lines and the
    base format are preserved byte-for-byte).  Routes through the
    crash-safe :func:`rewrite_metadata_lines` path (fsync + atomic
    rename; generation preserved, not bumped — repair restores state, it
    does not advance it)."""
    rewrite_metadata_lines(path, crcs=crcs)


def _parse_checksums(text: str) -> dict[int, int]:
    crcs: dict[int, int] = {}
    for line in text.splitlines():
        parts = line.split()
        if (
            len(parts) == 4
            and parts[:2] == ["#", "crc32"]
            and parts[2].isdigit()
            and len(parts[3]) == 8
            and all(c in "0123456789abcdefABCDEF" for c in parts[3])
        ):
            crcs[int(parts[2])] = int(parts[3], 16)
    return crcs


def read_checksums(path: str) -> dict[int, int]:
    """Parse ``# crc32`` extension lines from .METADATA ({} if absent).

    Malformed extension lines (bit-rot, foreign comments starting with
    ``# crc32``) are skipped rather than fatal: a broken checksum LINE must
    not make decode harder than a broken chunk — the corresponding chunk
    simply goes unverified.
    """
    with open(path) as fp:
        return _parse_checksums(fp.read())


def chunk_crc32(mm, chunk: int, step: int) -> int:
    """CRC32 of ``mm[:chunk]`` computed in bounded ``step``-byte slices (the
    single definition of per-chunk checksum semantics: whole chunk,
    padding included)."""
    crc = 0
    step = max(1, step)
    for s in range(0, chunk, step):
        crc = crc32_of(mm[s : min(s + step, chunk)], crc)
    return crc


def crc32_of(buf, crc: int = 0) -> int:
    """Incremental CRC32 (zlib polynomial) over bytes-like / ndarray data."""
    import zlib

    if isinstance(buf, (bytes, bytearray, memoryview)):
        return zlib.crc32(buf, crc)  # no copy; also correct for b""
    return zlib.crc32(memoryview(np.ascontiguousarray(buf)).cast("B"), crc)


def parse_chunk_index(name: str) -> int:
    """Row index from a chunk file name: integer digits right after the first
    character (reference semantics: ``atoi(name + 1)``)."""
    base = os.path.basename(name)
    m = re.match(r"\d+", base[1:])
    if not m:
        raise ValueError(f"cannot parse chunk index from {name!r}")
    return int(m.group(0))


def write_conf(path: str, chunk_names: list[str]) -> None:
    with open(path, "w") as fp:
        for name in chunk_names:
            fp.write(name + "\n")


def read_conf(path: str) -> list[str]:
    with open(path) as fp:
        return [line.strip() for line in fp if line.strip()]
