"""``rs loadgen`` — open-loop load harness for the serve daemon.

Serving performance needs a generator that does NOT slow down when the
server does: arrivals follow a seeded Poisson process (exponential
inter-arrival gaps at ``--rate`` requests/s), each fired on its own
thread at its scheduled instant regardless of how many predecessors are
still in flight — the open-loop discipline that exposes queueing
collapse, which closed-loop (wait-for-response) drivers mask.  Offered
vs achieved throughput plus client-side latency percentiles
(obs/percentile.py estimators — the same math as the Quantile metric
kind) land in a ``bench_captures/serve_*.jsonl`` capture via the shared
``capture_header`` identity envelope, so serving joins the BENCH
trajectory (``rs history`` reads it like any other capture).

Per-tenant mixes: ``--tenants alpha:3,beta:1`` weights arrivals; each
tenant alternates encode and decode-of-what-it-encoded per ``--mix``.

``--ab`` mode answers the residency question directly: encode the same
``--files`` small files once through a warm resident daemon and once as
one CLI subprocess per file (process start + jax import + cold plan
cache every time — today's deployment model), and records the margin.

``--spawn`` runs an in-process daemon on an ephemeral port (CI smoke,
A/B resident arm); ``--url`` points at an external one.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from ..obs import runlog as _runlog
from ..obs.percentile import QuantileEstimator

_PKG_PARENT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# Per-request rows retained for the capture (request ids + stage
# breakdowns): bounded so a long run cannot balloon the capture file.
_MAX_REQUEST_ROWS = 5000


class _Recorder:
    """Thread-safe per-(tenant, op) outcome and latency accumulator,
    plus the per-request id/stage rows the capture commits."""

    def __init__(self):
        self._lock = threading.Lock()
        self.cells: dict[tuple, dict] = {}
        self.requests: list[dict] = []
        self.request_rows_dropped = 0

    def _cell(self, tenant: str, op: str) -> dict:
        key = (tenant, op)
        cell = self.cells.get(key)
        if cell is None:
            cell = self.cells[key] = {
                "sent": 0, "ok": 0, "rejected": 0, "failed": 0,
                "bytes": 0, "lat": QuantileEstimator(),
            }
        return cell

    def record(self, tenant: str, op: str, status: int | None,
               wall_s: float, nbytes: int,
               detail: dict | None = None) -> None:
        with self._lock:
            cell = self._cell(tenant, op)
            cell["sent"] += 1
            if status == 200:
                cell["ok"] += 1
                cell["bytes"] += nbytes
                cell["lat"].observe(wall_s)
            elif status in (429, 503):
                cell["rejected"] += 1
            else:
                cell["failed"] += 1
            if len(self.requests) < _MAX_REQUEST_ROWS:
                self.requests.append({
                    "kind": "serve_request", "tenant": tenant, "op": op,
                    "status": status, "wall_s": round(wall_s, 6),
                    **(detail or {}),
                })
            else:
                self.request_rows_dropped += 1

    def rows(self) -> list[dict]:
        from ..obs.percentile import state_quantiles

        out = []
        with self._lock:
            for (tenant, op), cell in sorted(self.cells.items()):
                q = state_quantiles(cell["lat"].state())
                out.append({
                    "kind": "serve_tenant", "tenant": tenant, "op": op,
                    "sent": cell["sent"], "ok": cell["ok"],
                    "rejected": cell["rejected"],
                    "failed": cell["failed"], "bytes": cell["bytes"],
                    "latency_s": {
                        key: round(val, 6) if val is not None else None
                        for key, val in q.items()
                    },
                })
        return out

    def totals(self) -> dict:
        with self._lock:
            agg = {"sent": 0, "ok": 0, "rejected": 0, "failed": 0,
                   "bytes": 0}
            for cell in self.cells.values():
                for key in agg:
                    agg[key] += cell[key]
        return agg


def _post(url: str, tenant: str, body: bytes | None = None,
          timeout: float = 120.0, method: str = "POST",
          ) -> tuple[int | None, bytes, dict]:
    req = urllib.request.Request(
        url, data=body if body is not None else b"", method=method,
        headers={"X-RS-Tenant": tenant})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        payload = e.read()
        headers = dict(e.headers or {})
        e.close()
        return e.code, payload, headers
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        # transport failure — counted failed
        return None, str(e).encode(), {}


def _request_detail(payload: bytes, headers: dict,
                    json_body: bool) -> dict:
    """Request id + stage breakdown for the capture's per-request row:
    JSON responses carry ``req_id``/``stages_ms``/``group_id`` in the
    body, decode streams carry ``X-RS-Request-Id``/``X-RS-Stages``
    headers (stage offsets in seconds since admission)."""
    out: dict = {}
    rid = headers.get("X-RS-Request-Id")
    if rid:
        out["req_id"] = rid
    # object_get read-plane verdicts (serve/objcache.py): which lane
    # served the bytes — the zipf cache A/B validator reads hit-rate
    # straight from these capture rows.
    cache = headers.get("X-RS-Cache")
    if cache:
        out["cache"] = cache
    path = headers.get("X-RS-Read-Path")
    if path:
        out["path"] = path
    if json_body:
        try:
            doc = json.loads(payload)
        except ValueError:
            return out
        if isinstance(doc, dict):
            out.setdefault("req_id", doc.get("req_id"))
            if isinstance(doc.get("stages_ms"), dict):
                out["stages"] = {s: round(v / 1e3, 6)
                                 for s, v in doc["stages_ms"].items()}
            upd = doc.get("update")
            if isinstance(upd, dict) and upd.get("group_id"):
                out["group_id"] = upd["group_id"]
            obj = doc.get("object")
            if isinstance(obj, dict) and obj.get("group_id"):
                # Object PUT write-combining join (og-* ids).
                out["group_id"] = obj["group_id"]
    else:
        stages = headers.get("X-RS-Stages")
        if stages:
            try:
                out["stages"] = json.loads(stages)
            except ValueError:
                pass
    return out


def _scrape_json(base_url: str, path: str) -> dict:
    """One GET of a daemon introspection endpoint as JSON."""
    with urllib.request.urlopen(
            base_url.rstrip("/") + path, timeout=30) as resp:
        return json.loads(resp.read())


def _parse_tenants(spec: str) -> list[tuple[str, float]]:
    out = []
    for token in spec.split(","):
        name, _, weight = token.partition(":")
        out.append((name.strip() or "default",
                    float(weight) if weight else 1.0))
    if not out or any(w <= 0 for _, w in out):
        raise ValueError(f"bad --tenants spec {spec!r}")
    return out


def _schedule(duration_s: float, rate: float, tenants, decode_frac: float,
              seed: int, update_frac: float = 0.0,
              object_frac: float = 0.0) -> list:
    """The full open-loop arrival plan, drawn up front (seeded — the same
    offered load replays exactly).  ``update_frac`` mixes in partial-
    stripe writes (``POST /update`` of a small random range of an
    archive the tenant already encoded); ``object_frac`` mixes in
    object-façade traffic (``PUT``/``GET /o/<bucket>/<key>`` against a
    zipf-hot key space — docs/STORE.md) — the millions-of-small-objects
    workload class."""
    rng = random.Random(seed)
    names = [t for t, _ in tenants]
    weights = [w for _, w in tenants]
    plan = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            return plan
        tenant = rng.choices(names, weights)[0]
        roll = rng.random()
        if roll < object_frac:
            op = "object"
        elif roll < object_frac + decode_frac:
            op = "decode"
        elif roll < object_frac + decode_frac + update_frac:
            op = "update"
        else:
            op = "encode"
        plan.append((t, tenant, op))


def _zipf_weights(keys: int, s: float) -> list[float]:
    """Unnormalized zipf(s) rank weights over ``keys`` keys — the
    classic hot-key object workload (a few keys take most traffic)."""
    return [1.0 / (r + 1) ** s for r in range(keys)]


def run_open_loop(base_url: str, *, duration_s: float, rate: float,
                  tenants: list[tuple[str, float]], size_bytes: int,
                  k: int, p: int, w: int = 8, decode_frac: float = 0.3,
                  update_frac: float = 0.0, edit_burst: int = 1,
                  object_frac: float = 0.0, object_bytes: int = 4096,
                  object_keys: int = 256, object_zipf: float = 1.1,
                  object_burst: int = 1,
                  seed: int = 0, quiet: bool = False) -> dict:
    """Drive the daemon at ``base_url``; returns the summary document.

    ``edit_burst`` > 1 fires that many concurrent small ``/update``
    requests per update arrival, all against the same archive — they land
    inside one ``RS_SERVE_BATCH_MS`` harvest window, so the daemon's
    write-combining path (docs/UPDATE.md "Group commit") executes them as
    one group-committed batch and the per-request p50/p99 shows the
    amortized durability chain."""
    plan = _schedule(duration_s, rate, tenants, decode_frac, seed,
                     update_frac, object_frac)
    rec = _Recorder()
    # One shared payload buffer per size (arrival threads must not spend
    # their schedule slot generating bytes); per-request uniqueness comes
    # from the name, and decode correctness is the daemon tests' job —
    # the harness measures.
    body = random.Random(seed ^ 0x5EED).randbytes(size_bytes)
    encoded: dict[str, list[str]] = {t: [] for t, _ in tenants}
    enc_lock = threading.Lock()

    delta_len = max(1, min(4096, size_bytes))
    delta_body = random.Random(seed ^ 0xDE17A).randbytes(delta_len)

    # Object workload state: a zipf-hot key space per tenant; a key's
    # first arrival PUTs it, later arrivals GET (mostly) or re-PUT.
    # Payload is keyed so a GET's bytes are verifiable regardless of
    # how many re-PUTs raced: rows record status only.
    obj_weights = _zipf_weights(object_keys, object_zipf)
    obj_put: dict[tuple, bool] = {}
    obj_lock = threading.Lock()
    obj_body = random.Random(seed ^ 0x0B1EC7).randbytes(
        max(1, object_bytes))

    def fire(i: int, tenant: str, op: str) -> None:
        if op == "object":
            # Deterministic per-arrival key draw from the zipf weights.
            krng = random.Random((seed << 20) ^ i)
            kidx = krng.choices(range(object_keys), obj_weights)[0]
            key = f"k{kidx:05d}"
            with obj_lock:
                seen = obj_put.get((tenant, key), False)
            do_put = (not seen) or krng.random() < 0.3
            t0 = time.monotonic()
            if do_put:
                def one_put(j: int, pkey: str) -> None:
                    t1 = time.monotonic()
                    status, payload, hdrs = _post(
                        f"{base_url}/o/lg{seed}/{pkey}", tenant,
                        obj_body, method="PUT")
                    rec.record(tenant, "object_put", status,
                               time.monotonic() - t1, len(obj_body),
                               detail=_request_detail(payload, hdrs,
                                                      True))
                    if status == 200:
                        with obj_lock:
                            obj_put[(tenant, pkey)] = True
                if object_burst <= 1:
                    one_put(0, key)
                else:
                    # The salvo lands in one daemon harvest window, so
                    # the bucket's write combining commits it as ONE
                    # grouped stripe append (docs/STORE.md).
                    burst = [threading.Thread(
                        target=one_put,
                        args=(j, f"k{(kidx + j) % object_keys:05d}"),
                        daemon=True) for j in range(object_burst)]
                    for th in burst:
                        th.start()
                    for th in burst:
                        th.join(timeout=180)
            else:
                status, payload, hdrs = _post(
                    f"{base_url}/o/lg{seed}/{key}", tenant, None,
                    method="GET")
                rec.record(tenant, "object_get", status,
                           time.monotonic() - t0,
                           len(payload) if status == 200 else 0,
                           detail=_request_detail(payload, hdrs,
                                                  status != 200))
            return
        if op in ("decode", "update"):
            with enc_lock:
                pool = encoded[tenant]
                name = pool[i % len(pool)] if pool else None
            if name is None:
                op = "encode"  # nothing of ours to write against yet
        if op == "encode":
            name = f"lg{seed}_{tenant}_{i}.bin"
            t0 = time.monotonic()
            status, payload, hdrs = _post(
                f"{base_url}/encode?name={name}&k={k}&n={k + p}&w={w}",
                tenant, body)
            rec.record(tenant, "encode", status,
                       time.monotonic() - t0, size_bytes,
                       detail=_request_detail(payload, hdrs, True))
            if status == 200:
                with enc_lock:
                    encoded[tenant].append(name)
        elif op == "update":
            # A small hot write against a large cold archive — the
            # workload class rs update exists for.  Deterministic offset
            # per (arrival, burst) index keeps the run replayable.
            def one_edit(j: int) -> None:
                at = ((i * 7919) + j * 4099) % max(
                    1, size_bytes - delta_len + 1)
                t0 = time.monotonic()
                status, payload, hdrs = _post(
                    f"{base_url}/update?name={name}&at={at}", tenant,
                    delta_body)
                rec.record(tenant, "update", status,
                           time.monotonic() - t0, delta_len,
                           detail=_request_detail(payload, hdrs, True))
            if edit_burst <= 1:
                one_edit(0)
            else:
                # The burst fires concurrently so the whole salvo lands
                # in one daemon harvest window (write combining).
                burst = [threading.Thread(target=one_edit, args=(j,),
                                          daemon=True)
                         for j in range(edit_burst)]
                for th in burst:
                    th.start()
                for th in burst:
                    th.join(timeout=180)
        else:
            t0 = time.monotonic()
            status, payload, hdrs = _post(f"{base_url}/decode?name={name}",
                                          tenant)
            rec.record(tenant, "decode", status,
                       time.monotonic() - t0,
                       len(payload) if status == 200 else 0,
                       detail=_request_detail(payload, hdrs,
                                              status != 200))

    threads = []
    t_start = time.monotonic()
    for i, (t_arr, tenant, op) in enumerate(plan):
        delay = t_start + t_arr - time.monotonic()
        if delay > 0:
            time.sleep(delay)  # open loop: fire on schedule, never on
            # completion — laggards pile up in flight instead of
            # throttling the offered load
        th = threading.Thread(target=fire, args=(i, tenant, op),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=180)
    wall = time.monotonic() - t_start
    totals = rec.totals()
    summary = {
        "kind": "serve_summary",
        "duration_s": round(wall, 3),
        "offered_rps": round(len(plan) / duration_s, 3),
        "achieved_rps": round(totals["ok"] / wall, 3) if wall else None,
        "achieved_gbps": round(totals["bytes"] / wall / 1e9, 6)
        if wall else None,
        **totals,
        "config": {"k": k, "n": k + p, "w": w,
                   "size_bytes": size_bytes, "rate": rate,
                   "decode_frac": decode_frac,
                   "update_frac": update_frac,
                   "edit_burst": edit_burst,
                   "object_frac": object_frac,
                   "object_bytes": object_bytes,
                   "object_keys": object_keys,
                   "object_zipf": object_zipf,
                   "object_burst": object_burst, "seed": seed,
                   "tenants": dict(tenants)},
    }
    if rec.request_rows_dropped:
        # No silent caps: the capture must say when per-request rows
        # were bounded away.
        summary["request_rows_dropped"] = rec.request_rows_dropped
    if not quiet:
        print(f"loadgen: offered {summary['offered_rps']} rps -> "
              f"achieved {summary['achieved_rps']} rps "
              f"({totals['ok']} ok / {totals['rejected']} rejected / "
              f"{totals['failed']} failed)", file=sys.stderr)
    return {"summary": summary, "tenants": rec.rows(),
            "requests": rec.requests}


# -- A/B: resident daemon vs CLI-subprocess-per-file --------------------------

def _clean_cpu_env() -> dict:
    """Subprocess env for the per-file CLI arm: CPU backend, no plugin
    search path (the axon sitecustomize would wedge on a busy tunnel)."""
    return {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
    }


def run_ab(*, files: int, size_bytes: int, k: int, p: int, w: int = 8,
           workdir: str, quiet: bool = False) -> list[dict]:
    """Encode ``files`` small files through (a) one warm resident daemon
    and (b) one CLI subprocess per file; returns the two arm rows plus
    the margin row."""
    from .daemon import ServeDaemon

    rng = random.Random(20260804)
    paths = []
    for i in range(files):
        path = os.path.join(workdir, f"ab_{i}.bin")
        with open(path, "wb") as fp:
            fp.write(rng.randbytes(size_bytes))
        paths.append(path)

    rows = []

    # Arm A — resident: spawn, warm the shape bucket, then time the
    # whole run of sequential HTTP encodes (spool upload included; the
    # daemon pays its compile during warm(), like any long-lived server).
    daemon = ServeDaemon(os.path.join(workdir, "serve_root"), port=0)
    daemon.start()
    try:
        daemon.warm(k, p, w=w, file_bytes=size_bytes)
        base = f"http://127.0.0.1:{daemon.port}"
        per_file = []
        t0 = time.monotonic()
        for i, path in enumerate(paths):
            with open(path, "rb") as fp:
                body = fp.read()
            t1 = time.monotonic()
            status, _, _ = _post(
                f"{base}/encode?name=ab_{i}.bin&k={k}&n={k + p}&w={w}",
                "ab", body)
            per_file.append(time.monotonic() - t1)
            if status != 200:
                raise RuntimeError(f"resident encode {i} failed: {status}")
        wall_a = time.monotonic() - t0
    finally:
        daemon.close(drain=True, timeout=60)
    rows.append(_ab_row("resident", files, size_bytes, wall_a, per_file,
                        k, p, w))

    # Arm B — today's model: a fresh `rs` CLI process per file (process
    # start + jax import + cold plan cache, every single file).
    per_file = []
    t0 = time.monotonic()
    for path in paths:
        t1 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "gpu_rscode_tpu", "-k", str(k),
             "-n", str(k + p), "--width", str(w), "--checksum",
             "--quiet", "-e", path],
            env=_clean_cpu_env(), cwd=_PKG_PARENT,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        per_file.append(time.monotonic() - t1)
        if proc.returncode != 0:
            raise RuntimeError(
                f"subprocess encode failed: {proc.stderr.decode()[-500:]}")
    wall_b = time.monotonic() - t0
    rows.append(_ab_row("subprocess", files, size_bytes, wall_b, per_file,
                        k, p, w))

    margin = wall_b / wall_a if wall_a else None
    rows.append({
        "kind": "serve_ab_margin", "files": files,
        "size_bytes": size_bytes,
        "resident_wall_s": round(wall_a, 3),
        "subprocess_wall_s": round(wall_b, 3),
        "speedup": round(margin, 3) if margin else None,
    })
    if not quiet:
        print(f"loadgen A/B: resident {wall_a:.2f}s vs subprocess "
              f"{wall_b:.2f}s over {files} files -> "
              f"{margin:.1f}x", file=sys.stderr)
    return rows


def _ab_row(arm: str, files: int, size_bytes: int, wall: float,
            per_file: list[float], k: int, p: int, w: int) -> dict:
    from ..obs.percentile import quantile_of

    return {
        "kind": "serve_ab", "arm": arm, "files": files,
        "size_bytes": size_bytes, "wall_s": round(wall, 3),
        "files_per_s": round(files / wall, 3) if wall else None,
        "per_file_p50_s": round(quantile_of(per_file, 0.5), 4),
        "per_file_p99_s": round(quantile_of(per_file, 0.99), 4),
        "config": {"k": k, "n": k + p, "w": w},
    }


# -- A/B: object façade vs one-archive-per-object ------------------------------

def run_object_ab(*, files: int, object_bytes: int, k: int, p: int,
                  w: int = 8, batch: int = 64, trials: int = 3,
                  workdir: str, quiet: bool = False) -> list[dict]:
    """The façade's raison d'être, measured: store ``files`` small
    objects once through one bucket (PUT batches of ``batch`` — the
    write-combining unit a daemon harvest forms) and once as one
    archive per object (today's model: per-object metadata, k+p chunk
    files, its own commit).  Paired best-of-``trials`` per arm (the
    repo's A/B idiom — fs/scheduler noise at 4 KiB op sizes swings
    single runs ±40%), EVERY object of EVERY trial byte-verified
    outside the timed regions; the per-archive arm's file-count
    amplification is recorded alongside the walls."""
    from .. import api
    from .. import store as _store

    rng = random.Random(20260804)
    payloads = [rng.randbytes(max(1, object_bytes)) for _ in range(files)]

    # Warm the plan cache OUTSIDE both timed regions (a resident
    # process pays its compiles once; the A/B measures steady state):
    # one per-archive-shaped encode for arm B, one same-batch-shaped
    # put_many for arm A (stripe-create encode + grouped-append E·Δ
    # both hit their real plan buckets).
    warmdir = os.path.join(workdir, "warm")
    os.makedirs(warmdir, exist_ok=True)
    wseed = os.path.join(warmdir, "warm.bin")
    with open(wseed, "wb") as fp:
        fp.write(rng.randbytes(max(1, object_bytes)))
    api.encode_file(wseed, k, p, w=w, checksums=True,
                    layout="interleaved")
    wb = _store.open_bucket(
        warmdir, "warmbkt", create=True, k=k, p=p, w=w,
        stripe_bytes=max(1 << 20, 16 * object_bytes * batch))
    wpay = [(f"w{j}", rng.randbytes(max(1, object_bytes)))
            for j in range(batch)]
    wb.put_many(wpay)  # stripe create (encode lane)
    wb.put_many(wpay)  # grouped append (E*delta lane)

    walls_a, walls_b = [], []
    files_a = files_b = 0
    for trial in range(max(1, trials)):
        # Arm A — the façade: batched PUTs into shared stripes.
        root = os.path.join(workdir, f"store_root_{trial}")
        t0 = time.monotonic()
        bucket = _store.open_bucket(
            root, "ab", create=True, k=k, p=p, w=w,
            stripe_bytes=max(1 << 20, 16 * object_bytes * batch))
        for lo in range(0, files, batch):
            bucket.put_many([
                (f"o{i:06d}", payloads[i])
                for i in range(lo, min(lo + batch, files))
            ])
        walls_a.append(time.monotonic() - t0)
        for i in range(files):  # byte-verify OUTSIDE the timed region
            if bucket.get(f"o{i:06d}") != payloads[i]:
                raise RuntimeError(
                    f"facade arm verification failed at {i}")
        files_a = sum(len(fs) for _, _, fs in os.walk(root))

        # Arm B — one archive per object.
        perdir = os.path.join(workdir, f"per_archive_{trial}")
        os.makedirs(perdir, exist_ok=True)
        t0 = time.monotonic()
        for i in range(files):
            path = os.path.join(perdir, f"o{i:06d}.bin")
            with open(path, "wb") as fp:
                fp.write(payloads[i])
            api.encode_file(path, k, p, w=w, checksums=True,
                            layout="interleaved")
            os.unlink(path)  # the archive stores it now, like arm A
        walls_b.append(time.monotonic() - t0)
        files_b = sum(len(fs) for _, _, fs in os.walk(perdir))
        for i in range(files):  # byte-verify EVERY archive, like arm A
            probe = os.path.join(perdir, f"o{i:06d}.bin")
            out = api.auto_decode_file(probe, probe + ".dec")
            ok = open(out, "rb").read() == payloads[i]
            os.unlink(out)
            if not ok:
                raise RuntimeError(
                    f"per-archive arm verification failed at {i}")

    wall_a, wall_b = min(walls_a), min(walls_b)
    rows = [
        {
            "kind": "object_ab", "arm": "facade", "files": files,
            "object_bytes": object_bytes, "batch": batch,
            "wall_s": round(wall_a, 4),
            "trial_walls_s": [round(wl, 4) for wl in walls_a],
            "objects_per_s": round(files / wall_a, 2) if wall_a
            else None,
            "disk_files": files_a, "verified": True,
            "config": {"k": k, "n": k + p, "w": w},
        },
        {
            "kind": "object_ab", "arm": "per_archive", "files": files,
            "object_bytes": object_bytes,
            "wall_s": round(wall_b, 4),
            "trial_walls_s": [round(wl, 4) for wl in walls_b],
            "objects_per_s": round(files / wall_b, 2) if wall_b
            else None,
            "disk_files": files_b, "verified": True,
            "config": {"k": k, "n": k + p, "w": w},
        },
    ]
    margin = wall_b / wall_a if wall_a else None
    rows.append({
        "kind": "object_ab_margin", "files": files,
        "object_bytes": object_bytes, "batch": batch,
        "trials": max(1, trials),
        "facade_wall_s": round(wall_a, 4),
        "per_archive_wall_s": round(wall_b, 4),
        "speedup": round(margin, 2) if margin else None,
        "disk_files_facade": files_a,
        "disk_files_per_archive": files_b,
    })
    if not quiet:
        print(f"loadgen object A/B: facade {wall_a:.2f}s vs "
              f"per-archive {wall_b:.2f}s over {files} x "
              f"{object_bytes} B (best of {max(1, trials)}) -> "
              f"{margin:.1f}x ({files_a} vs {files_b} files on disk)",
              file=sys.stderr)
    return rows


# -- A/B: zipf GETs with vs without the daemon object cache --------------------

def run_object_cache_ab(*, objects: int, object_bytes: int, gets: int,
                        k: int, p: int, w: int = 8, zipf: float = 1.1,
                        trials: int = 3, cache_bytes: int | None = None,
                        workdir: str, quiet: bool = False) -> list[dict]:
    """The hot-object read cache, measured end to end: the SAME seeded
    zipf GET stream over the SAME PUT corpus through two daemons — one
    with the cache at its configured capacity, one with it disabled
    (``obj_cache_bytes=0``, every GET pays the windowed read lane).
    Best-of-``trials`` walls per arm (the repo's paired A/B idiom);
    EVERY GET of EVERY trial is byte-verified against a local mirror,
    so a wrong cached byte cannot hide inside a fast number.  A third
    small-cap pass (capacity = 4 objects) proves the LRU actually
    evicts under pressure.  Per-arm rows carry the verdict counts from
    the ``X-RS-Cache`` header, the hot-key (top-decile rank) read-lane
    avoidance rate, and the daemon's own ``objcache`` stats block."""
    from .daemon import ServeDaemon
    from ..obs.percentile import quantile_of

    rng = random.Random(20260806)
    payloads = {f"c{r:05d}": rng.randbytes(max(1, object_bytes))
                for r in range(objects)}
    weights = _zipf_weights(objects, zipf)
    draw_rng = random.Random(20260806 ^ 0x21BF)
    draws = [f"c{r:05d}"
             for r in draw_rng.choices(range(objects), weights, k=gets)]
    hot = {f"c{r:05d}" for r in range(max(1, objects // 10))}

    def run_arm(arm: str, cap: int | None, arm_gets: list[str],
                arm_trials: int) -> dict:
        # batch_ms=0: object GETs are solo batches (queue.py shape_key),
        # so the coalescing window is a flat latency tax on BOTH arms
        # that drowns the read-lane delta this A/B exists to measure.
        daemon = ServeDaemon(os.path.join(workdir, f"cab_{arm}"),
                             port=0, obj_cache_bytes=cap, batch_ms=0)
        daemon.start()
        try:
            base = f"http://127.0.0.1:{daemon.port}"
            for key, data in payloads.items():  # corpus load — untimed
                status, payload, _ = _post(f"{base}/o/cab/{key}", "cab",
                                           data, method="PUT")
                if status != 200:
                    raise RuntimeError(
                        f"{arm} corpus PUT {key} failed: {status} "
                        f"{payload[-200:]!r}")
            verdicts = {"hit": 0, "miss": 0, "bypass": 0}
            hot_gets = hot_hits = 0
            walls, trial_lats = [], []
            for _ in range(max(1, arm_trials)):
                lats = []
                t0 = time.monotonic()
                for key in arm_gets:
                    t1 = time.monotonic()
                    status, payload, hdrs = _post(
                        f"{base}/o/cab/{key}", "cab", None, method="GET")
                    lats.append(time.monotonic() - t1)
                    if status != 200 or payload != payloads[key]:
                        raise RuntimeError(
                            f"{arm} GET {key} wrong: status {status}")
                    verdict = hdrs.get("X-RS-Cache", "bypass")
                    verdicts[verdict] = verdicts.get(verdict, 0) + 1
                    if key in hot:
                        hot_gets += 1
                        hot_hits += verdict == "hit"
                walls.append(time.monotonic() - t0)
                trial_lats.append(lats)
            lats = trial_lats[walls.index(min(walls))]
            stats = daemon.stats().get("objcache", {})
        finally:
            daemon.close(drain=True, timeout=60)
        return {
            "kind": "object_cache_ab", "arm": arm, "objects": objects,
            "object_bytes": object_bytes, "gets": len(arm_gets),
            "wall_s": round(min(walls), 4),
            "trial_walls_s": [round(wl, 4) for wl in walls],
            "get_p50_s": round(quantile_of(lats, 0.5), 6),
            "get_p99_s": round(quantile_of(lats, 0.99), 6),
            "verdicts": verdicts,
            "hot_gets": hot_gets, "hot_hits": hot_hits,
            "hot_avoid_rate": round(hot_hits / hot_gets, 4)
            if hot_gets else None,
            "verified": True, "objcache": stats,
            "config": {"k": k, "n": k + p, "w": w, "zipf": zipf,
                       "trials": max(1, arm_trials),
                       "cap_bytes": cap},
        }

    row_on = run_arm("cache_on", cache_bytes, draws, trials)
    row_off = run_arm("cache_off", 0, draws, trials)

    # Eviction proof: capacity for only 4 objects, one pass over a
    # cold-heavy draw (uniform — maximal churn) MUST evict.
    small_cap = max(1, 4 * max(1, object_bytes))
    ev_rng = random.Random(20260806 ^ 0xE71C)
    ev_draws = [f"c{r:05d}" for r in
                (ev_rng.randrange(objects)
                 for _ in range(min(gets, 4 * objects)))]
    row_small = run_arm("cache_small", small_cap, ev_draws, 1)
    if row_small["objcache"].get("evictions", 0) <= 0:
        raise RuntimeError(
            "small-cap arm recorded no evictions — LRU cap not enforced")

    p50_speedup = (row_off["get_p50_s"] / row_on["get_p50_s"]
                   if row_on["get_p50_s"] else None)
    p99_speedup = (row_off["get_p99_s"] / row_on["get_p99_s"]
                   if row_on["get_p99_s"] else None)
    total_on = row_on["verdicts"]["hit"] + row_on["verdicts"]["miss"]
    margin = {
        "kind": "object_cache_ab_margin", "objects": objects,
        "object_bytes": object_bytes, "gets": gets, "zipf": zipf,
        "trials": max(1, trials),
        "cache_on_p50_s": row_on["get_p50_s"],
        "cache_off_p50_s": row_off["get_p50_s"],
        "p50_speedup": round(p50_speedup, 2) if p50_speedup else None,
        "p99_speedup": round(p99_speedup, 2) if p99_speedup else None,
        "hit_rate": round(row_on["verdicts"]["hit"] / total_on, 4)
        if total_on else None,
        "hot_avoid_rate": row_on["hot_avoid_rate"],
        "dispatches_avoided": row_on["objcache"].get("hits"),
        "small_cap_evictions": row_small["objcache"].get("evictions"),
    }
    if not quiet:
        print(f"loadgen cache A/B: p50 {row_off['get_p50_s'] * 1e3:.2f}ms "
              f"(off) vs {row_on['get_p50_s'] * 1e3:.2f}ms (on) -> "
              f"{p50_speedup:.1f}x, hit rate {margin['hit_rate']}, "
              f"hot-key avoidance {margin['hot_avoid_rate']}, "
              f"{margin['small_cap_evictions']} evictions under the "
              f"small cap", file=sys.stderr)
    return [row_on, row_off, row_small, margin]


def _fg_latency(report: dict, tenants: set, q: str) -> float | None:
    """Worst quantile ``q`` (a ``latency_s`` key, e.g. ``"0.99"``)
    across the foreground tenants' encode/decode cells — the latency
    the maint A/B gates on."""
    worst = None
    for row in report["tenants"]:
        if row["tenant"] not in tenants or row["op"] not in ("encode",
                                                            "decode"):
            continue
        val = (row.get("latency_s") or {}).get(q)
        if val is not None and (worst is None or val > worst):
            worst = val
    return worst


def run_maint_ab(*, archives: int, size_bytes: int, k: int, p: int,
                 w: int = 8, duration_s: float = 10.0, rate: float = 8.0,
                 p99_ratio_max: float = 1.25, workdir: str,
                 quiet: bool = False) -> list[dict]:
    """The maintenance-plane margin (docs/MAINT.md): identical damaged
    fleets + identical foreground traffic through two daemons — one
    with ``rs serve --maint`` on, one off.

    Each arm seeds ``archives`` archives with one bit-rotted chunk each
    (scanned into a private damage ledger), fires a sacrificial
    ``monkey`` tenant salvo of guaranteed-expiring decodes — a 1 ms
    ``X-RS-Deadline-Ms`` against the daemon's 5 ms batch window, so
    every one admits, expires with 504, and burns the deliberately
    fragile ``monkey:decode:avail=50`` objective over one short
    ``RS_SLO_WINDOWS`` window (pre-queue 404s never reach the SLO
    plane; expired admissions do) — making the burn-rate governor
    demonstrably PAUSE maintenance mid-run, then drives the alpha/beta
    open loop.
    The ON arm must converge — burn decays as the monkey samples age
    out, the governor resumes, every repair drains, and the rotted
    chunk bytes are byte-verified restored — while the OFF arm proves
    the damage does NOT self-heal (every repair still queued) and
    provides the foreground latency baseline: the ON arm's worst
    foreground encode/decode p99 must stay within ``p99_ratio_max`` of
    it, and the governor must have logged at least one pause event.
    """
    from .daemon import ServeDaemon
    from .. import api
    from ..obs import health as _health
    from ..utils.fileformat import chunk_file_name

    fg = {"alpha", "beta"}
    monkey_n = 12

    def run_arm(arm: str, maint_on: bool) -> dict:
        arm_dir = os.path.join(workdir, arm)
        root = os.path.join(arm_dir, "root")
        ledger = os.path.join(arm_dir, "ledger.jsonl")
        os.makedirs(os.path.join(root, "alpha"), exist_ok=True)
        saved = {kk: os.environ.get(kk)
                 for kk in ("RS_RUNLOG", "RS_RUNLOG_MAX_BYTES",
                            "RS_SLO_WINDOWS", "RS_MAINT_INTERVAL_S",
                            "RS_HEALTH_SCRUB_MAX_AGE_S")}
        daemon = None
        try:
            os.environ["RS_RUNLOG"] = ledger
            os.environ.pop("RS_RUNLOG_MAX_BYTES", None)
            os.environ.pop("RS_HEALTH_SCRUB_MAX_AGE_S", None)
            # One SHORT SLO window: the monkey burn must both fire the
            # pause AND age out mid-run so the resume half of the
            # hysteresis is exercised too (a long window would hold the
            # burn for the whole run and starve the ON arm's repairs).
            os.environ["RS_SLO_WINDOWS"] = "6"
            os.environ["RS_MAINT_INTERVAL_S"] = "0.2"

            # Seeded damage, identical per arm: encode, clean scan,
            # rot 16 bytes of chunk 1, damage scan.
            pristine: dict[str, bytes] = {}
            victims = []
            rng = random.Random(20260807)
            body = rng.randbytes(size_bytes)
            for a in range(archives):
                fname = os.path.join(root, "alpha", f"maintab_{a}.bin")
                with open(fname, "wb") as fp:
                    fp.write(body)
                api.encode_file(fname, k, p, checksums=True, w=w)
                api.scan_file(fname)
                cf = chunk_file_name(fname, 1)
                pristine[fname] = open(cf, "rb").read()
                with open(cf, "r+b") as fp:
                    fp.seek(64)
                    fp.write(rng.randbytes(16))
                api.scan_file(fname)
                victims.append(fname)

            # The monkey's own (healthy, unscanned) archive: its decodes
            # must ADMIT to be observed, then expire on the deadline.
            os.makedirs(os.path.join(root, "monkey"), exist_ok=True)
            burn_f = os.path.join(root, "monkey", "burn.bin")
            with open(burn_f, "wb") as fp:
                fp.write(rng.randbytes(8192))
            api.encode_file(burn_f, k, p, checksums=True, w=w)

            daemon = ServeDaemon(root, port=0,
                                 slo_spec="monkey:decode:avail=50",
                                 maint=maint_on)
            daemon.start()
            daemon.warm(k, p, w=w, file_bytes=size_bytes)
            base = f"http://127.0.0.1:{daemon.port}"

            # The sacrificial burn: a 1 ms deadline cannot survive the
            # 5 ms harvest window — every salvo member admits, expires
            # with 504, and burns avail=50 at 2x budget.
            for _ in range(monkey_n):
                mreq = urllib.request.Request(
                    f"{base}/decode?name=burn.bin", data=b"",
                    method="POST",
                    headers={"X-RS-Tenant": "monkey",
                             "X-RS-Deadline-Ms": "1"})
                try:
                    with urllib.request.urlopen(mreq, timeout=30) as rr:
                        rr.read()
                except urllib.error.HTTPError as e:
                    e.read()
                    e.close()

            report = run_open_loop(
                base, duration_s=duration_s, rate=rate,
                tenants=[("alpha", 3.0), ("beta", 1.0)],
                size_bytes=size_bytes, k=k, p=p, w=w,
                decode_frac=0.3, seed=20260807, quiet=quiet)

            # ON arm: wait for the queue to drain (the monkey window
            # must age out first — resume, then repairs).
            maint_doc: dict = {}
            converge_s = None
            if maint_on:
                t0 = time.monotonic()
                while time.monotonic() - t0 < 120.0:
                    maint_doc = _scrape_json(base, "/maint")
                    q = maint_doc.get("queue") or {}
                    if (q.get("repair", 0) == 0 and q.get("scrub", 0) == 0
                            and q.get("compact", 0) == 0
                            and not maint_doc.get("paused")):
                        converge_s = round(time.monotonic() - t0, 3)
                        break
                    time.sleep(0.25)
            else:
                maint_doc = _scrape_json(base, "/maint")
        finally:
            if daemon is not None:
                daemon.close(drain=True, timeout=120)
            for kk, vv in saved.items():
                if vv is None:
                    os.environ.pop(kk, None)
                else:
                    os.environ[kk] = vv

        state = _health.load(ledger)
        repairs_left = len([it for it in _health.work_queue(state)
                            if it["action"] == "repair"])
        restored = all(
            open(chunk_file_name(f, 1), "rb").read() == pristine[f]
            for f in victims)
        return {
            "kind": "maint_ab", "arm": arm, "archives": archives,
            "size_bytes": size_bytes, "damaged": archives,
            "repairs_left": repairs_left, "chunks_restored": restored,
            "converge_wait_s": converge_s,
            "pause_events": maint_doc.get("pause_events"),
            "resume_events": maint_doc.get("resume_events"),
            "maint_enabled": bool(maint_doc.get("enabled")),
            "maint_jobs": maint_doc.get("jobs"),
            "fg_p50_s": _fg_latency(report, fg, "0.5"),
            "fg_p99_s": _fg_latency(report, fg, "0.99"),
            "summary": report["summary"],
            "tenants": report["tenants"],
            "config": {"k": k, "n": k + p, "w": w,
                       "duration_s": duration_s, "rate": rate,
                       "monkey_decodes": monkey_n,
                       "slo": "monkey:decode:avail=50", "windows_s": [6]},
        }

    row_off = run_arm("maint_off", False)
    row_on = run_arm("maint_on", True)

    # The contract, checked loudly (a capture that silently records a
    # broken run would read as a blessing):
    if row_off["repairs_left"] != archives or row_off["chunks_restored"]:
        raise RuntimeError(
            f"off arm self-healed? {row_off['repairs_left']} of "
            f"{archives} repairs left, restored="
            f"{row_off['chunks_restored']}")
    if row_on["repairs_left"] != 0 or not row_on["chunks_restored"]:
        raise RuntimeError(
            f"maint arm did not converge: {row_on['repairs_left']} "
            f"repair(s) left, restored={row_on['chunks_restored']}")
    if not row_on["pause_events"]:
        raise RuntimeError(
            "burn-rate governor never paused — the monkey burn did not "
            "register")
    ratio = (row_on["fg_p99_s"] / row_off["fg_p99_s"]
             if row_on["fg_p99_s"] and row_off["fg_p99_s"] else None)
    margin = {
        "kind": "maint_ab_margin", "archives": archives,
        "size_bytes": size_bytes,
        "fg_p99_off_s": row_off["fg_p99_s"],
        "fg_p99_on_s": row_on["fg_p99_s"],
        "p99_ratio": round(ratio, 3) if ratio is not None else None,
        "p99_ratio_max": p99_ratio_max,
        "repairs_converged": True,
        "repairs_left_off": row_off["repairs_left"],
        "pause_events": row_on["pause_events"],
        "resume_events": row_on["resume_events"],
        "converge_wait_s": row_on["converge_wait_s"],
    }
    if ratio is not None and ratio > p99_ratio_max:
        raise RuntimeError(
            f"maint arm foreground p99 {row_on['fg_p99_s']}s is "
            f"{ratio:.2f}x the off arm's {row_off['fg_p99_s']}s "
            f"(max {p99_ratio_max}x)")
    if not quiet:
        print(f"loadgen maint A/B: {archives} repairs converged under "
              f"load (wait {row_on['converge_wait_s']}s, "
              f"{row_on['pause_events']} governor pause(s)); foreground "
              f"p99 {row_off['fg_p99_s']}s (off) vs "
              f"{row_on['fg_p99_s']}s (on) -> "
              f"{margin['p99_ratio']}x", file=sys.stderr)
    return [row_off, row_on, margin]


# -- CLI ----------------------------------------------------------------------

def main(argv=None) -> int:
    """The ``rs loadgen`` subcommand."""
    import argparse
    import tempfile

    ap = argparse.ArgumentParser(
        prog="rs loadgen",
        description="Open-loop (Poisson) load generator for rs serve: "
        "per-tenant mixes, offered/achieved throughput, latency "
        "percentiles, bench_captures capture (docs/SERVE.md).",
    )
    ap.add_argument("--url", default=None,
                    help="daemon base URL (e.g. http://127.0.0.1:9470)")
    ap.add_argument("--spawn", action="store_true",
                    help="spawn an in-process daemon on an ephemeral "
                    "port for the run")
    ap.add_argument("--root", default=None,
                    help="--spawn daemon root (default: a temp dir)")
    ap.add_argument("--duration", type=float, default=15.0,
                    help="offered-load window in seconds (default 15)")
    ap.add_argument("--rate", type=float, default=8.0,
                    help="mean arrival rate, requests/s (default 8)")
    ap.add_argument("--tenants", default="alpha:3,beta:1",
                    help="weighted tenant mix, name:weight,... "
                    "(default alpha:3,beta:1)")
    ap.add_argument("--size-kb", type=int, default=64,
                    help="encode payload size (default 64)")
    ap.add_argument("--decode-frac", type=float, default=0.3,
                    help="fraction of arrivals that decode (default 0.3)")
    ap.add_argument("--update-frac", type=float, default=0.0,
                    help="fraction of arrivals that POST /update a small "
                    "byte range of an archive the tenant already encoded "
                    "(mixed read/write traffic; default 0)")
    ap.add_argument("--edit-burst", type=int, default=1,
                    help="small /update requests fired CONCURRENTLY per "
                    "update arrival against the same archive — lands the "
                    "salvo in one batch window so the daemon's write "
                    "combining groups it (default 1 = no burst)")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--n", type=int, default=6)
    ap.add_argument("--w", type=int, default=8, choices=(8, 16))
    ap.add_argument("--seed", type=int, default=0,
                    help="arrival-process seed (default 0)")
    ap.add_argument("--object-frac", type=float, default=0.0,
                    help="fraction of arrivals hitting the object "
                    "facade (PUT/GET /o/<bucket>/<key>, zipf-hot keys; "
                    "docs/STORE.md; default 0)")
    ap.add_argument("--object-bytes", type=int, default=4096,
                    help="object payload size (default 4096)")
    ap.add_argument("--object-keys", type=int, default=256,
                    help="object key-space size (default 256)")
    ap.add_argument("--object-zipf", type=float, default=1.1,
                    help="zipf skew of the key draw (default 1.1)")
    ap.add_argument("--object-burst", type=int, default=1,
                    help="object PUTs fired CONCURRENTLY per object-put "
                    "arrival (distinct keys, same bucket) — the salvo "
                    "lands in one batch window so the daemon commits it "
                    "as ONE grouped stripe append (default 1)")
    ap.add_argument("--ab", action="store_true",
                    help="A/B mode instead of open-loop: resident daemon "
                    "vs CLI subprocess per file on --files encodes")
    ap.add_argument("--object-ab", action="store_true",
                    help="A/B mode: --files small objects through the "
                    "store facade (PUT batches of --object-batch) vs "
                    "one archive per object — the per-object metadata "
                    "amortization margin (docs/STORE.md)")
    ap.add_argument("--object-batch", type=int, default=64,
                    help="--object-ab facade PUT batch size (default 64 "
                    "— the write-combining unit)")
    ap.add_argument("--object-trials", type=int, default=3,
                    help="--object-ab / --object-cache-ab paired trials "
                    "per arm, best wall wins (default 3)")
    ap.add_argument("--object-cache-ab", action="store_true",
                    help="A/B mode: the SAME seeded zipf GET stream "
                    "through a daemon with the hot-object cache on vs "
                    "off (RS_OBJ_CACHE_BYTES=0) — every GET "
                    "byte-verified, plus a small-cap eviction proof "
                    "(docs/SERVE.md)")
    ap.add_argument("--object-gets", type=int, default=600,
                    help="--object-cache-ab GETs per trial (default 600)")
    ap.add_argument("--cache-bytes", type=int, default=None,
                    help="--object-cache-ab cache-on arm capacity in "
                    "bytes (default: RS_OBJ_CACHE_BYTES or 64 MiB)")
    ap.add_argument("--maint-ab", action="store_true",
                    help="A/B mode: identical damaged fleets + identical "
                    "foreground traffic through a daemon with the "
                    "background-maintenance plane on vs off — repairs "
                    "must converge under load with the burn-rate "
                    "governor demonstrably pausing at least once, and "
                    "the foreground p99 must stay within "
                    "--maint-p99-max of the off arm (docs/MAINT.md)")
    ap.add_argument("--maint-archives", type=int, default=4,
                    help="--maint-ab damaged archives per arm (default 4)")
    ap.add_argument("--maint-p99-max", type=float, default=1.25,
                    help="--maint-ab foreground p99 ratio gate "
                    "(default 1.25)")
    ap.add_argument("--files", type=int, default=100,
                    help="--ab / --object-ab item count (default 100)")
    ap.add_argument("--faults", metavar="SPEC", default=None,
                    help="with --spawn: activate the fault plane in the "
                    "daemon for the run (bounded-error demonstration)")
    ap.add_argument("--slo", metavar="SPEC", default=None,
                    help="SLO objectives (RS_SLO grammar, e.g. "
                    "'*:encode:p99=250ms,avail=99.9'): configures the "
                    "spawned daemon, scrapes GET /slo + /debug/requests "
                    "into the capture, and EXITS 4 when any window "
                    "misses its objective — open-loop runs double as "
                    "SLO gates")
    ap.add_argument("--capture", default=None,
                    help="capture JSONL path (default bench_captures/"
                    "serve_<mode>_<backend>_<ts>.jsonl; '-' disables)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary document as JSON on stdout")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    if args.n <= args.k or args.k <= 0:
        print(f"rs loadgen: need n > k > 0 (got k={args.k} n={args.n})",
              file=sys.stderr)
        return 2
    ab_modes = sum((args.ab, args.object_ab, args.object_cache_ab,
                    args.maint_ab))
    if ab_modes > 1:
        print("rs loadgen: --ab, --object-ab, --object-cache-ab and "
              "--maint-ab conflict; pick one", file=sys.stderr)
        return 2
    if not ab_modes and not args.spawn and not args.url:
        print("rs loadgen: pass --url or --spawn", file=sys.stderr)
        return 2
    if args.slo and ab_modes:
        print("rs loadgen: --slo gates open-loop runs, not --ab/"
              "--object-ab/--object-cache-ab", file=sys.stderr)
        return 2
    if args.slo:
        from ..obs import slo as _slo

        try:  # fail before any daemon spawns, naming the bad token
            _slo.parse_slo(args.slo)
        except _slo.SLOSpecError as e:
            print(f"rs loadgen: bad --slo spec: {e}", file=sys.stderr)
            return 2

    p = args.n - args.k
    rows: list[dict] = []
    fault_ctx = None
    if args.faults:
        if not (args.spawn or args.ab):
            print("rs loadgen: --faults needs --spawn (the plane lives "
                  "in the daemon process)", file=sys.stderr)
            return 2
        from ..resilience import faults as _faults

        try:
            plan = _faults.parse_plan(args.faults,
                                      seed=_faults.env_seed())
        except ValueError as e:
            print(f"rs loadgen: bad --faults spec: {e}", file=sys.stderr)
            return 2
        fault_ctx = _faults.activate(plan)
        fault_ctx.__enter__()

    tmp = None
    daemon = None
    slo_report = None
    try:
        with tempfile.TemporaryDirectory(prefix="rs_loadgen_") as tmp:
            if args.ab:
                rows = run_ab(
                    files=args.files, size_bytes=args.size_kb * 1024,
                    k=args.k, p=p, w=args.w, workdir=tmp,
                    quiet=args.json)
                mode = "ab"
            elif args.object_ab:
                rows = run_object_ab(
                    files=args.files, object_bytes=args.object_bytes,
                    k=args.k, p=p, w=args.w,
                    batch=max(1, args.object_batch),
                    trials=max(1, args.object_trials), workdir=tmp,
                    quiet=args.json)
                mode = "object_ab"
            elif args.maint_ab:
                rows = run_maint_ab(
                    archives=max(1, args.maint_archives),
                    size_bytes=args.size_kb * 1024,
                    k=args.k, p=p, w=args.w,
                    duration_s=args.duration, rate=args.rate,
                    p99_ratio_max=args.maint_p99_max, workdir=tmp,
                    quiet=args.json)
                mode = "maint_ab"
            elif args.object_cache_ab:
                rows = run_object_cache_ab(
                    objects=max(1, args.object_keys),
                    object_bytes=args.object_bytes,
                    gets=max(1, args.object_gets),
                    k=args.k, p=p, w=args.w, zipf=args.object_zipf,
                    trials=max(1, args.object_trials),
                    cache_bytes=args.cache_bytes, workdir=tmp,
                    quiet=args.json)
                mode = "object_cache_ab"
            else:
                url = args.url
                if args.spawn:
                    from .daemon import ServeDaemon

                    daemon = ServeDaemon(
                        args.root or os.path.join(tmp, "serve_root"),
                        port=0, slo_spec=args.slo)
                    daemon.start()
                    daemon.warm(args.k, p, w=args.w,
                                file_bytes=args.size_kb * 1024)
                    url = f"http://127.0.0.1:{daemon.port}"
                report = run_open_loop(
                    url.rstrip("/"), duration_s=args.duration,
                    rate=args.rate,
                    tenants=_parse_tenants(args.tenants),
                    size_bytes=args.size_kb * 1024, k=args.k, p=p,
                    w=args.w, decode_frac=args.decode_frac,
                    update_frac=args.update_frac,
                    edit_burst=max(1, args.edit_burst),
                    object_frac=args.object_frac,
                    object_bytes=args.object_bytes,
                    object_keys=max(1, args.object_keys),
                    object_zipf=args.object_zipf,
                    object_burst=max(1, args.object_burst),
                    seed=args.seed, quiet=args.json)
                if args.faults:
                    # Self-describing capture: a faulted run's error rows
                    # must not read as a regression.
                    report["summary"]["config"]["faults"] = args.faults
                if args.slo:
                    report["summary"]["config"]["slo"] = args.slo
                rows = [report["summary"], *report["tenants"],
                        *report["requests"]]
                if args.slo:
                    # Scrape the daemon's own lifecycle surfaces while it
                    # is still alive: the SLO report (attainment + burn
                    # rates) and its view of the recent requests — the
                    # capture carries both sides of the id join.
                    slo_report = _scrape_json(url, "/slo")
                    if not slo_report.get("configured"):
                        # A gate over zero objectives passes forever —
                        # refuse loudly instead (an external --url
                        # daemon must be started with --slo/RS_SLO;
                        # --spawn configures its own).
                        print("rs loadgen: --slo gate is vacuous: the "
                              "daemon reports no SLO objectives "
                              "configured (start it with rs serve "
                              "--slo or RS_SLO)", file=sys.stderr)
                        return 2
                    rows.append({**slo_report, "kind": "serve_slo"})
                    debug = _scrape_json(url, "/debug/requests?n=200")
                    rows.append({**debug, "kind": "serve_debug_requests"})
                if daemon is not None:
                    stats = daemon.stats()
                    rows.append({"kind": "serve_daemon_stats", **stats})
                    if args.object_frac > 0:
                        # Dedicated rs_obj_cache_* summary row: the zipf
                        # cache validator reads hit-rate from the capture
                        # alone, no /stats scrape of its own.
                        rows.append({"kind": "obj_cache_summary",
                                     **stats.get("objcache", {})})
                mode = ("faulted" if args.faults
                        else "object" if args.object_frac > 0
                        else "openloop")
    finally:
        if daemon is not None:
            daemon.close(drain=True, timeout=120)
        if fault_ctx is not None:
            fault_ctx.__exit__(None, None, None)

    capture = args.capture
    if capture is None:
        os.makedirs("bench_captures", exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        capture = os.path.join(
            "bench_captures",
            f"serve_{mode}_{_runlog.backend_name() or 'cpu'}_"
            f"{stamp}.jsonl")
    if capture != "-":
        with open(capture, "w") as fp:
            fp.write(json.dumps(_runlog.capture_header("serve_loadgen"))
                     + "\n")
            for row in rows:
                fp.write(json.dumps(row) + "\n")
        print(f"rs loadgen: capture -> {capture}", file=sys.stderr)
    if args.json:
        print(json.dumps({"rows": rows, "capture": capture}))
    if args.slo:
        # The SLO gate (docs/SERVE.md "Request lifecycle"): the run
        # fails loudly when any rolling window missed its objective —
        # the capture above still records everything, so a gating CI
        # leg keeps its artifact.
        from ..obs import slo as _slo

        bad = _slo.breaches(slo_report or {})
        if bad:
            for b in bad:
                print(f"rs loadgen: SLO BREACH {b['tenant']}/{b['op']} "
                      f"{b['objective']} @{b['window']}s: attainment "
                      f"{b['attainment']}, burn {b['burn_rate']}",
                      file=sys.stderr)
            return 4
        print("rs loadgen: SLO attained across all windows",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
