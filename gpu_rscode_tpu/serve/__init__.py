"""``rs serve`` — resident multi-tenant encode/decode daemon.

The ROADMAP's residency item: every CLI op pays process start, plan-cache
warmup and staging-ring setup per file; at heavy multi-tenant traffic the
wins come from keeping one process resident and batching concurrent small
requests through the warm AOT executables (docs/SERVE.md).

Modules:

* :mod:`.queue`   — bounded admission queue: reject past ``RS_SERVE_DEPTH``,
  per-tenant deficit-round-robin fairness, deadline-aware ordering;
* :mod:`.batcher` — cross-request batching by (k, n, w, strategy) shape
  bucket under the ``RS_SERVE_BATCH_MS`` coalescing window;
* :mod:`.daemon`  — the HTTP front end (`rs serve`): POST /encode /decode
  /scrub with streaming bodies, graceful drain on SIGTERM;
* :mod:`.loadgen` — open-loop (Poisson) load harness (`rs loadgen`) with
  per-tenant mixes, latency percentiles and bench captures.

Import cost: stdlib only at package level; the daemon imports the jax
stack lazily when it starts serving.
"""

from __future__ import annotations

__all__ = ["queue", "batcher", "daemon", "loadgen"]
