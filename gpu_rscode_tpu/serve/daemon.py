"""``rs serve`` — the resident encode/decode daemon (HTTP front end).

One long-lived process in front of the warm plan cache (docs/SERVE.md):

* ``POST /encode?name=N&k=K&n=TOTAL[&w=8|16][&strategy=S][&generator=G]
  [&checksum=0|1][&keep=1]`` — request body is the file bytes, streamed
  to a per-tenant spool under the daemon root and encoded into an
  archive there; JSON response lists the chunk files written.  The spool
  is unlinked after a successful encode unless ``keep=1`` — the daemon
  stores the ARCHIVE, so a later /decode is a real reconstruction.
* ``POST /decode?name=N`` — auto-decode (survivor discovery, CRC
  verification, degraded-decode ladder — docs/RESILIENCE.md) of the
  named archive; the response body streams the rebuilt file bytes.
* ``POST /scrub?name=N[&syndrome=1]`` — read-only health report
  (``api.scan_file``) as JSON.
* ``POST /update?name=N&at=OFF`` / ``POST /append?name=N`` — delta-
  parity partial-stripe writes against a stored archive
  (docs/UPDATE.md): the body is the replacement/append bytes, applied
  via ``api.update_file`` / ``api.append_file`` (only the touched
  segment columns move; crash-atomic journal + generation commit).
  They ride the same admission/DRR/deadline plane, costed by payload
  size; encode accepts ``layout=interleaved`` to create archives that
  take unbounded appends.
* ``PUT/GET/DELETE /o/<bucket>/<key>`` + ``GET /o/<bucket>?list`` —
  the object-store façade (docs/STORE.md): objects pack into shared
  stripe archives under the tenant's namespace, DRR-costed by object
  bytes.  Same-bucket PUTs harvested in one ``RS_SERVE_BATCH_MS``
  window commit as ONE grouped stripe append + ONE index fsync (the
  PR 13 write-combining path), GET reconstructs just the object's
  byte range, DELETE tombstones + zeroes.  ``GET /o/<bucket>`` lists
  (``?stats=1`` for the space-accounting report).
* ``GET /healthz`` ``/metrics`` ``/stats`` — liveness JSON, Prometheus
  exposition of the live registry, queue/batcher introspection.
* ``GET /perf`` — per-cell throughput baseline/drift report
  (obs/perfbase.py) over the run ledger; the scrape also refreshes the
  ``rs_perf_baseline_*`` gauges.

Tenancy: ``X-RS-Tenant`` header (or ``?tenant=``) names the tenant —
its own namespace directory under the root AND its own fairness queue
(serve/queue.py).  ``X-RS-Deadline-Ms`` bounds how long the request may
wait+run; expired requests fail with 504 before touching the device.

Request flow: handler threads stream the body, admit into the bounded
:class:`~.queue.AdmissionQueue` (429 past ``RS_SERVE_DEPTH``, 503 while
draining), and block on the request future.  One scheduler thread pulls
fairness-ordered work through the :class:`~.batcher.Batcher` and hands
each shape-bucketed batch to a small executor pool
(``RS_SERVE_WORKERS``); batches run as fleets (shared warm executable +
one write-behind lane), falling back to per-request execution when a
fleet fails so one poisoned request — injected faults included — cannot
take its batchmates down or wedge the queue.  Graceful drain (SIGTERM /
SIGINT): stop admitting, flush the queue, let in-flight fleets commit
their ordered writes, then close the listener.

Security note: no authentication — bind loopback (the default) or
front with a real gateway.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import re
import signal
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..obs import (
    health as _health,
    metrics as _metrics,
    reqtrace as _reqtrace,
    runlog as _runlog,
    slo as _slo,
    tracing as _tracing,
)
from ..maint import controller as _maint
from ..utils.timing import PhaseTimer
from . import objcache as _objcache
from .batcher import Batcher
from .queue import AdmissionQueue, Draining, QueueFull, Request

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,199}$")
_COPY_CHUNK = 1024 * 1024

DEFAULT_PORT = 9470
DEFAULT_REQUEST_TIMEOUT_S = 300.0
DEFAULT_MAX_BODY = 1 << 30
DEFAULT_LIST_LIMIT = 1000


def list_limit_env() -> int:
    """Hard cap on one ``GET /o/<bucket>?list`` page
    (``RS_STORE_LIST_LIMIT``, default 1000, min 1): a 10⁷-key bucket
    never serializes whole into one response — pages chain through the
    opaque ``next`` cursor."""
    from ..utils.env import int_env

    return max(1, int_env("RS_STORE_LIST_LIMIT", DEFAULT_LIST_LIMIT))


def _safe_name(text: str | None, what: str) -> str:
    """One path component, no traversal: the only way request input ever
    reaches the filesystem."""
    if not text or not _NAME_RE.match(text) or ".." in text:
        raise ValueError(f"bad {what} {text!r}: want [A-Za-z0-9._-]+")
    return text


def _q1(query: dict, key: str, default: str | None = None) -> str | None:
    vals = query.get(key)
    return vals[0] if vals else default


class _ServeHTTPServer(ThreadingHTTPServer):
    # Explicit (HTTPServer already defaults this on): restart/drain paths
    # must rebind through TIME_WAIT without EADDRINUSE.
    allow_reuse_address = True
    daemon_threads = True
    rs_daemon: "ServeDaemon"


class _Handler(BaseHTTPRequestHandler):
    server_version = "rs-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # loadgen hammers this — stay quiet
        pass

    @property
    def daemon(self) -> "ServeDaemon":
        return self.server.rs_daemon  # type: ignore[attr-defined]

    # -- response helpers ----------------------------------------------------

    def _send_json(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # EVERY response of a request-bearing path echoes the request id
        # (docs/SERVE.md "Request lifecycle") — 400/404/429/503/504/500
        # rejections included, so client logs stay joinable.
        rid = getattr(self, "_rs_req_id", None)
        if rid:
            self.send_header("X-RS-Request-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, code: int, msg: str,
                         headers: dict | None = None, **extra) -> None:
        # Several error paths answer BEFORE consuming the request body;
        # under HTTP/1.1 keep-alive the unread bytes would be parsed as
        # the next request line.  Errors are rare — close the connection
        # rather than track which paths drained.
        self.close_connection = True
        self._send_json(code, {"ok": False, "error": msg, **extra},
                        headers)

    # -- GET -----------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server naming)
        url = urlparse(self.path)
        if url.path.startswith("/o/"):
            # Object reads ARE requests in the lifecycle sense: DRR
            # cost = object bytes, queued like any other op.
            return self._object_request("GET", url)
        # Introspection GETs are not: clear any id a previous request
        # on this keep-alive connection left behind.
        self._rs_req_id = None
        try:
            if url.path == "/healthz":
                self._send_json(200, self.daemon.health())
            elif url.path == "/health":
                # Fleet durability report (obs/health.py): replay the
                # damage ledger, rank by stripe risk.  Distinct from
                # /healthz — that answers "is the daemon up", this
                # answers "which archives are closest to data loss".
                self._send_json(200, self.daemon.fleet_health())
            elif url.path == "/maint":
                # Maintenance-plane state (docs/MAINT.md): governor
                # pause/resume, job tallies, and a fresh work-queue
                # snapshot replayed from the damage ledger.
                self._send_json(200, self.daemon.maint_report())
            elif url.path == "/perf":
                # Perf-baseline drift report (obs/perfbase.py): the
                # same per-cell table `rs perf` renders, replayed from
                # the run ledger's rs_perf/op evidence.
                self._send_json(200, self.daemon.perf_report())
            elif url.path == "/metrics":
                # Rolling SLO windows age out without new traffic, so
                # the rs_slo_* gauges refresh at scrape time — and so do
                # scrub ages: the rs_durability_* gauges re-export too.
                self.daemon.slo.export_gauges()
                self.daemon.export_fleet_health()
                self.daemon.export_perf_gauges()
                body = _metrics.REGISTRY.render_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif url.path == "/stats":
                self._send_json(200, self.daemon.stats())
            elif url.path == "/slo":
                # Per-tenant attainment + burn rates (obs/slo.py); the
                # export also refreshes the rs_slo_* gauges.
                self._send_json(200, self.daemon.slo.export_gauges())
            elif url.path == "/debug/requests":
                query = parse_qs(url.query)
                try:
                    n = int(_q1(query, "n", "50") or 50)
                except ValueError:
                    n = 50
                self._send_json(200, {
                    "ring": _reqtrace.ring_capacity(),
                    "requests": _reqtrace.recent(n),
                })
            else:
                self._send_error_json(404, f"no such path {url.path}")
        except BrokenPipeError:
            pass

    # -- POST ----------------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        query = parse_qs(url.query)
        # Request identity FIRST: the client's X-RS-Request-Id (when it
        # validates) or a minted one — echoed on every outcome path,
        # before any parsing can fail (obs/reqtrace.py).
        self._rs_req_id = _reqtrace.accept_request_id(
            self.headers.get("X-RS-Request-Id"))
        try:
            if url.path not in (
                "/encode", "/decode", "/scrub", "/update", "/append"
            ):
                self._send_error_json(404, f"no such path {url.path}")
                return
            try:
                req = self._admit(url.path[1:], query)
            except ValueError as e:  # bad name/tenant/params/body
                self._send_error_json(400, str(e))
                return
            if req is None:
                return  # error response already sent
            status = None
            try:
                status = self._respond(req)
            finally:
                # Ack boundary: response bytes written (or the client
                # went away — status None); fold the lifecycle event.
                self.daemon.finish_request(req, status)
        except BrokenPipeError:
            pass
        except Exception as e:  # defense: a handler bug must answer 500
            try:
                self._send_error_json(500, f"{type(e).__name__}: {e}")
            except Exception:
                pass

    # -- object façade (/o/<bucket>[/<key>]) ---------------------------------

    def do_PUT(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        if not url.path.startswith("/o/"):
            self._rs_req_id = _reqtrace.accept_request_id(
                self.headers.get("X-RS-Request-Id"))
            self._send_error_json(404, f"no such path {url.path}")
            return
        self._object_request("PUT", url)

    def do_DELETE(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        if not url.path.startswith("/o/"):
            self._rs_req_id = _reqtrace.accept_request_id(
                self.headers.get("X-RS-Request-Id"))
            self._send_error_json(404, f"no such path {url.path}")
            return
        self._object_request("DELETE", url)

    def _object_request(self, method: str, url) -> None:
        """One /o/ request end to end: mint the id, admit (or answer the
        metadata paths inline), block on execution, respond, ack."""
        query = parse_qs(url.query)
        self._rs_req_id = _reqtrace.accept_request_id(
            self.headers.get("X-RS-Request-Id"))
        try:
            try:
                req = self._admit_object(method, url, query)
            except ValueError as e:
                self._send_error_json(400, str(e))
                return
            if req is None:
                return  # answered inline (list/stat) or error sent
            status = None
            try:
                status = self._respond(req)
            finally:
                self.daemon.finish_request(req, status)
        except BrokenPipeError:
            pass
        except Exception as e:  # defense: a handler bug must answer 500
            try:
                self._send_error_json(500, f"{type(e).__name__}: {e}")
            except Exception:
                pass

    def _admit_object(self, method: str, url, query) -> Request | None:
        from .. import store as _store

        daemon = self.daemon
        parts = [p for p in url.path.split("/") if p]  # ["o", bucket, key?]
        if len(parts) < 2 or len(parts) > 3:
            self._send_error_json(
                404, f"want /o/<bucket>[/<key>], got {url.path}")
            return None
        tenant = _safe_name(
            self.headers.get("X-RS-Tenant") or _q1(query, "tenant")
            or "default", "tenant")
        bucket = _safe_name(parts[1], "bucket")
        key = _safe_name(parts[2], "key") if len(parts) == 3 else None
        tenant_root = os.path.join(daemon.root, tenant)
        spool = daemon.tenant_path(tenant, bucket)  # the bucket dir
        deadline = None
        dl_ms = self.headers.get("X-RS-Deadline-Ms")
        if dl_ms is not None:
            deadline = time.monotonic() + max(0.0, float(dl_ms)) / 1000.0

        if method == "GET" and key is None:
            # Bucket listing/report: metadata only, answered inline —
            # it never touches the device or the stripe bytes.  Listing
            # is always paginated: one page caps at RS_STORE_LIST_LIMIT
            # (tighter with limit=), and ``next`` carries the opaque
            # cursor for the following page.
            try:
                b = _store.open_bucket(tenant_root, bucket)
                if _q1(query, "stats") == "1":
                    self._send_json(200, {"ok": True,
                                          "stats": b.stats()})
                else:
                    cap = list_limit_env()
                    raw_limit = _q1(query, "limit")
                    if raw_limit is not None and not raw_limit.isdigit():
                        self._send_error_json(
                            400, f"limit= must be an integer, got "
                            f"{raw_limit!r}")
                        return None
                    limit = min(cap, int(raw_limit)) if raw_limit \
                        else cap
                    page = b.list_page(
                        prefix=_q1(query, "prefix") or "",
                        limit=max(1, limit),
                        cursor=_q1(query, "cursor"),
                    )
                    self._send_json(200, {
                        "ok": True, "bucket": bucket,
                        "objects": page["objects"],
                        "truncated": page["truncated"],
                        "next": page["next"],
                    })
            except _store.ObjectNotFound as e:
                self._send_error_json(404, str(e))
            except (_store.ObjectStoreError, OSError, ValueError) as e:
                self._send_error_json(400, f"{type(e).__name__}: {e}")
            return None
        if key is None:
            self._send_error_json(
                404, f"{method} needs /o/<bucket>/<key>")
            return None

        if method == "PUT":
            for knob in ("k", "n", "w", "stripe_kb"):
                val = _q1(query, knob)
                if val is not None and not val.isdigit():
                    self._send_error_json(
                        400, f"{knob}= must be an integer, got {val!r}")
                    return None
            # Bucket-shape params validate at admission like /encode's:
            # a bad shape must 400 here, not 500 in the executor (or
            # silently create a default-shaped bucket from half a pair).
            k = int(_q1(query, "k", "0"))
            n = int(_q1(query, "n", "0"))
            if (k > 0) != (n > 0):
                self._send_error_json(
                    400, "pass k= and n= together (bucket shape at "
                    f"creation), got k={k or None} n={n or None}")
                return None
            if n and not n > k > 0:
                self._send_error_json(
                    400, f"need n > k > 0, got k={k} n={n}")
                return None
            w_q = int(_q1(query, "w", "0") or 0)
            if w_q not in (0, 8, 16):
                self._send_error_json(
                    400, f"w must be 8 or 16, got {w_q}")
                return None
            upload = f"{spool}.up.{daemon.next_upload_id()}"
            os.makedirs(os.path.dirname(upload), exist_ok=True)
            nbytes = self._read_body_to(upload)
            if nbytes == 0:
                os.unlink(upload)
                self._send_error_json(
                    400, "refusing an empty object body")
                return None
            req = Request(
                "object_put", tenant, bucket, spool, key=key,
                k=k, p=max(0, n - k), w=w_q,
                stripe_bytes=(int(_q1(query, "stripe_kb", "0")) * 1024
                              or None),
                cost=nbytes, deadline=deadline,
                req_id=self._rs_req_id,
            )
            req.upload = upload
        else:  # GET / DELETE of one object: cost = the object's bytes
            try:
                stat = _store.open_bucket(tenant_root, bucket).stat(key)
            except _store.ObjectNotFound as e:
                self._send_error_json(404, str(e))
                return None
            except (_store.ObjectStoreError, OSError, ValueError) as e:
                self._send_error_json(400, f"{type(e).__name__}: {e}")
                return None
            req = Request(
                "object_get" if method == "GET" else "object_delete",
                tenant, bucket, spool, key=key, cost=stat["bytes"],
                deadline=deadline, req_id=self._rs_req_id,
            )

        _reqtrace.begin(req)
        try:
            daemon.queue.submit(req)
        except QueueFull as e:
            daemon.discard_upload(req)
            self._send_error_json(429, str(e), {"Retry-After": "1"})
            daemon.finish_request(req, 429)
            return None
        except Draining as e:
            daemon.discard_upload(req)
            self._send_error_json(503, str(e), {"Retry-After": "5"})
            daemon.finish_request(req, 503)
            return None
        return req

    def _read_body_to(self, spool: str) -> int:
        """Stream the request body to the spool file; returns byte count."""
        length = self.headers.get("Content-Length")
        if length is None:
            raise ValueError("Content-Length required (no chunked bodies)")
        remaining = int(length)
        if remaining > self.daemon.max_body:
            raise ValueError(
                f"body of {remaining} bytes exceeds RS_SERVE_MAX_BYTES="
                f"{self.daemon.max_body}")
        with open(spool, "wb") as fp:
            while remaining:
                block = self.rfile.read(min(_COPY_CHUNK, remaining))
                if not block:
                    raise ValueError("request body truncated")
                fp.write(block)
                remaining -= len(block)
        return int(length)

    def _admit(self, op: str, query: dict) -> Request | None:
        daemon = self.daemon
        tenant = _safe_name(
            self.headers.get("X-RS-Tenant") or _q1(query, "tenant")
            or "default", "tenant")
        name = _safe_name(_q1(query, "name"), "name")
        spool = daemon.tenant_path(tenant, name)
        deadline = None
        dl_ms = self.headers.get("X-RS-Deadline-Ms")
        if dl_ms is not None:
            deadline = time.monotonic() + max(0.0, float(dl_ms)) / 1000.0

        if op == "encode":
            k = int(_q1(query, "k", "0"))
            n = int(_q1(query, "n", "0"))
            if k <= 0 or n <= k:
                self._send_error_json(400, f"need n > k > 0, got k={k} n={n}")
                return None
            w = int(_q1(query, "w", "8"))
            if w not in (8, 16):
                self._send_error_json(400, f"w must be 8 or 16, got {w}")
                return None
            enc_layout = _q1(query, "layout", "row")
            if enc_layout not in ("row", "interleaved"):
                self._send_error_json(
                    400, f"layout must be row or interleaved, "
                    f"got {enc_layout!r}")
                return None
            # Per-request temp: concurrent same-name uploads must never
            # interleave bytes in one file.  The executor promotes it
            # onto the spool path under the per-name lock.
            upload = f"{spool}.up.{daemon.next_upload_id()}"
            nbytes = self._read_body_to(upload)
            if nbytes == 0:
                os.unlink(upload)
                self._send_error_json(400, "refusing to encode empty body")
                return None
            req = Request(
                "encode", tenant, name, spool, k=k, p=n - k, w=w,
                strategy=_q1(query, "strategy", "auto"),
                generator=_q1(query, "generator", "vandermonde"),
                checksums=_q1(query, "checksum", "1") != "0",
                keep=_q1(query, "keep", "0") == "1",
                layout=enc_layout, cost=nbytes, deadline=deadline,
                req_id=self._rs_req_id,
            )
            req.upload = upload
        elif op in ("update", "append"):
            # Partial-stripe write traffic (docs/UPDATE.md): the body is
            # the delta/append payload, spooled to a per-request temp;
            # shape key + DRR cost come from the body size and the
            # archive's own metadata (404s garbage names pre-queue).
            try:
                k, p, w, _total = daemon.archive_shape(spool)
            except FileNotFoundError:
                self._send_error_json(
                    404, f"no archive {name!r} for tenant {tenant!r}")
                return None
            except (OSError, ValueError) as e:
                self._send_error_json(400, f"unreadable archive: {e}")
                return None
            at = 0
            if op == "update":
                try:
                    at = int(_q1(query, "at", ""))
                except (TypeError, ValueError):
                    self._send_error_json(
                        400, "update needs an integer at= byte offset")
                    return None
            upload = f"{spool}.up.{daemon.next_upload_id()}"
            nbytes = self._read_body_to(upload)
            if nbytes == 0:
                os.unlink(upload)
                self._send_error_json(
                    400, f"refusing a zero-byte {op} payload")
                return None
            req = Request(
                op, tenant, name, spool, k=k, p=p, w=w,
                strategy=_q1(query, "strategy", "auto"),
                at=at, cost=nbytes, deadline=deadline,
                req_id=self._rs_req_id,
            )
            req.upload = upload
        else:
            # Drain any (bogus) body so the connection stays usable.
            length = int(self.headers.get("Content-Length") or 0)
            while length > 0:
                block = self.rfile.read(min(_COPY_CHUNK, length))
                if not block:
                    break
                length -= len(block)
            # Shape key + DRR cost from the archive's own metadata: tiny
            # read, and it 404s garbage names before they queue.
            try:
                k, p, w, total = daemon.archive_shape(spool)
            except FileNotFoundError:
                self._send_error_json(
                    404, f"no archive {name!r} for tenant {tenant!r}")
                return None
            except (OSError, ValueError) as e:
                self._send_error_json(400, f"unreadable archive: {e}")
                return None
            req = Request(
                op, tenant, name, spool, k=k, p=p, w=w,
                strategy=_q1(query, "strategy", "auto"),
                syndrome=_q1(query, "syndrome", "0") == "1",
                cost=total, deadline=deadline,
                req_id=self._rs_req_id,
            )

        _reqtrace.begin(req)  # lifecycle timeline anchored at admission
        try:
            daemon.queue.submit(req)
        except QueueFull as e:
            daemon.discard_upload(req)
            self._send_error_json(429, str(e), {"Retry-After": "1"})
            daemon.finish_request(req, 429)
            return None
        except Draining as e:
            daemon.discard_upload(req)
            self._send_error_json(503, str(e), {"Retry-After": "5"})
            daemon.finish_request(req, 503)
            return None
        return req

    def _respond(self, req: Request) -> int | None:
        """Send the response for an executed request; returns the HTTP
        status written (the ack-boundary emit's outcome field)."""
        if not req.done.wait(self.daemon.request_timeout_s):
            self._send_error_json(
                500, f"request timed out after "
                f"{self.daemon.request_timeout_s}s in the daemon")
            return 500
        base = {
            "ok": req.outcome == "ok",
            "op": req.op, "tenant": req.tenant, "name": req.name,
            "req_id": req.req_id,
            "batch": req.batch_size,
            "queue_wait_ms": round(req.queue_wait_s * 1e3, 3),
            "service_ms": round(req.service_s * 1e3, 3),
        }
        stages = _reqtrace.stage_offsets(req)
        if stages is not None:
            # The stage timeline so far (ack lands after this write):
            # offsets in ms since admission, consecutive and summing to
            # the request wall (docs/SERVE.md "Request lifecycle").
            base["stages_ms"] = {
                s: round(v * 1e3, 3) for s, v in stages.items()}
        if req.outcome == "expired":
            self._send_json(504, {
                **base, "error": "deadline exceeded before execution"})
            return 504
        elif req.outcome != "ok":
            from ..store import ObjectNotFound

            if isinstance(req.error, ObjectNotFound):
                # Raced by a DELETE between admission and execution:
                # a clean 404, not a daemon error.
                self._send_json(404, {**base, "error": str(req.error)})
                return 404
            self._send_json(500, {
                **base,
                "error": str(req.error),
                "error_type": type(req.error).__name__
                if req.error else None,
            })
            return 500
        elif req.op == "object_get":
            data: bytes = req.result
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-RS-Request-Id", req.req_id)
            # Read-plane verdicts (serve/objcache.py): which lane served
            # the bytes — loadgen captures read these per request.
            if req.cache is not None:
                self.send_header("X-RS-Cache", req.cache)
            if req.path is not None:
                self.send_header("X-RS-Read-Path", req.path)
            if stages is not None:
                self.send_header("X-RS-Stages", json.dumps(stages))
            self.end_headers()
            self.wfile.write(data)
            return 200
        elif req.op == "decode":
            out_path = req.result
            try:
                size = os.path.getsize(out_path)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(size))
                self.send_header("X-RS-Batch", str(req.batch_size))
                self.send_header("X-RS-Request-Id", req.req_id)
                if stages is not None:
                    # Decode streams bytes, not JSON — the breakdown
                    # rides a header so loadgen captures stay complete.
                    self.send_header("X-RS-Stages", json.dumps(stages))
                self.end_headers()
                with open(out_path, "rb") as fp:
                    while True:
                        block = fp.read(_COPY_CHUNK)
                        if not block:
                            break
                        self.wfile.write(block)
            finally:
                # The streamed copy is the response; the daemon keeps the
                # archive, not decode outputs.
                try:
                    os.unlink(out_path)
                except OSError:
                    pass
            return 200
        else:
            payload = dict(base)
            if req.op == "encode":
                payload["bytes"] = req.cost
                payload["files"] = [
                    os.path.basename(f) for f in (req.result or [])]
            elif req.op in ("update", "append"):
                payload["update"] = req.result  # the engine's op summary
            elif req.op in ("object_put", "object_delete"):
                payload["object"] = req.result  # location / tombstone
                payload["key"] = req.key
            else:  # scrub
                payload["report"] = req.result
            self._send_json(200, payload)
            return 200


class ServeDaemon:
    """The resident daemon: queue + batcher + scheduler + HTTP listener.

    Library surface (tests, loadgen --spawn): construct, :meth:`start`,
    talk HTTP to ``self.port``, then :meth:`close` (drains by default).
    """

    def __init__(self, root: str, *, port: int = 0, addr: str | None = None,
                 depth: int | None = None, quantum: int | None = None,
                 batch_ms: float | None = None, max_batch: int | None = None,
                 workers: int | None = None,
                 request_timeout_s: float | None = None,
                 max_body: int | None = None,
                 slo_spec: str | None = None,
                 obj_cache_bytes: int | None = None,
                 maint: bool | None = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.addr = addr if addr is not None else os.environ.get(
            "RS_SERVE_ADDR", "127.0.0.1")
        self.queue = AdmissionQueue(depth=depth, quantum=quantum)
        self.batcher = Batcher(self.queue, batch_ms=batch_ms,
                               max_batch=max_batch)
        self.workers = max(1, workers if workers is not None else int(
            os.environ.get("RS_SERVE_WORKERS", "2") or 2))
        self.request_timeout_s = (
            float(os.environ.get("RS_SERVE_TIMEOUT_S",
                                 DEFAULT_REQUEST_TIMEOUT_S))
            if request_timeout_s is None else request_timeout_s)
        self.max_body = (
            int(os.environ.get("RS_SERVE_MAX_BYTES", DEFAULT_MAX_BODY))
            if max_body is None else max_body)
        self._server = _ServeHTTPServer((self.addr, port), _Handler)
        self._server.rs_daemon = self
        self.port = self._server.server_address[1]
        self._pool: ThreadPoolExecutor | None = None
        self._serve_thread: threading.Thread | None = None
        self._sched_thread: threading.Thread | None = None
        # One slot per worker: the scheduler may not pop work out of the
        # admission queue faster than workers consume it — otherwise
        # requests pile invisibly in the executor's internal queue and
        # admission control (the 429 depth bound) never fires.
        self._slots = threading.Semaphore(self.workers)
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        # Per-(tenant, name) mutexes: all executor work on one archive
        # name serializes (concurrent same-name encodes would interleave
        # chunk .rs_tmp writes; a decode mid-encode would read a half-
        # committed archive).  Locks are never dropped — bounded by name
        # cardinality, two objects each.
        self._name_locks: dict[tuple, threading.Lock] = {}
        self._name_locks_guard = threading.Lock()
        self._upload_ids = itertools.count(1)
        # Per-tenant SLO objectives (obs/slo.py): RS_SLO by default,
        # --slo / slo_spec= override.  An empty engine costs nothing.
        self.slo = _slo.SLOEngine(spec=slo_spec)
        # Hot-object read cache (serve/objcache.py): consulted before
        # the windowed read lane on GET /o/; RS_OBJ_CACHE_BYTES caps it
        # (0 disables — every GET reports cache=bypass).
        self.objcache = _objcache.ObjectCache(obj_cache_bytes)
        # Background-maintenance plane (docs/MAINT.md): repair/scrub/
        # compaction jobs admitted through THIS queue as the maint
        # tenant, paced by the SLO burn-rate governor.  Off unless
        # RS_MAINT is set or the caller passes maint=True (`rs serve
        # --maint`); disabled means no controller object, zero threads.
        self.maint = None
        maint_on = _maint.enabled() if maint is None else bool(maint)
        if maint_on:
            self.maint = _maint.MaintController(
                store_roots=self._maint_store_roots,
                # Restart-stable owner: a daemon that died mid-job and
                # came back on the same root reclaims its own leases
                # immediately instead of waiting them out.
                owner=f"{os.uname().nodename}:serve:{self.root}",
                slo_report=self.slo.export_gauges
                if self.slo.objectives else None,
                submit=self._submit_maint_job,
            )
        self._trace_cm = None  # daemon-lifetime RS_TRACE session
        self._started = time.time()
        self._closed = False
        self.requests_done = 0
        self.requests_failed = 0

    # -- paths / metadata ----------------------------------------------------

    def tenant_path(self, tenant: str, name: str) -> str:
        d = os.path.join(self.root, tenant)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def next_upload_id(self) -> int:
        return next(self._upload_ids)

    def _name_lock(self, key: tuple) -> threading.Lock:
        with self._name_locks_guard:
            lock = self._name_locks.get(key)
            if lock is None:
                lock = self._name_locks[key] = threading.Lock()
            return lock

    @contextlib.contextmanager
    def _locked_names(self, reqs: list[Request]):
        """Hold every distinct (tenant, name) lock of ``reqs`` — acquired
        in SORTED key order so concurrent fleets can never deadlock."""
        keys = sorted({(r.tenant, r.name) for r in reqs})
        with contextlib.ExitStack() as stack:
            for key in keys:
                stack.enter_context(self._name_lock(key))
            yield

    # -- maintenance plane (docs/MAINT.md) -----------------------------------

    def _maint_store_roots(self) -> list[str]:
        """Per-tenant dirs under the data root — where object-store
        buckets live (`/o/` routes open buckets at root/tenant/name)."""
        try:
            return [os.path.join(self.root, t)
                    for t in sorted(os.listdir(self.root))
                    if os.path.isdir(os.path.join(self.root, t))]
        except OSError:
            return []

    def _maint_lock_key(self, target: str) -> tuple:
        """The FOREGROUND (tenant, name) lock a maintenance job must
        hold: a repair of tenant alpha's archive excludes alpha's own
        writes to it, not just other maint jobs.  Targets outside the
        data root key on their absolute path."""
        rel = os.path.relpath(os.path.abspath(target), self.root)
        parts = rel.split(os.sep)
        if not rel.startswith("..") and len(parts) >= 2:
            return (parts[0], parts[1])
        return ("rs-maint", os.path.abspath(target))

    def _submit_maint_job(self, job, *, name: str, cost: int):
        """The controller's dispatch hook: wrap the job closure as an
        op="maint" request, admit it through the DRR queue (tenant =
        the maint tenant, cost pre-inflated by the controller), block
        until the executor ran it.  QueueFull/Draining surface as
        backpressure — the controller's pass stops and retries next
        interval instead of overwhelming a loaded daemon."""
        req = Request("maint", self.maint.tenant, name, "", cost=cost)
        req.job = job
        req.lock_key = self._maint_lock_key(name)
        try:
            self.queue.submit(req)
        except (QueueFull, Draining) as e:
            raise _maint.MaintBackpressure(str(e)) from e
        if not req.done.wait(timeout=600.0):
            raise TimeoutError(f"maint job on {name!r} did not finish")
        if req.outcome == "ok":
            return req.result
        if isinstance(req.error, BaseException):
            raise req.error
        raise RuntimeError(f"maint job outcome {req.outcome!r}")

    def maint_report(self) -> dict:
        """``GET /maint``: controller state + a fresh work-queue
        snapshot (the queue block replays the damage ledger per call —
        the same freshness contract as ``GET /health``)."""
        if self.maint is None:
            return {
                "kind": "rs_maint", "enabled": False,
                "error": "maintenance plane off (start with --maint or "
                "RS_MAINT=1)",
            }
        return {"kind": "rs_maint", "enabled": True,
                **self.maint.stats(include_queue=True)}

    @staticmethod
    def _promote_upload(req: Request) -> None:
        """Move the request's consistent upload temp onto the spool path
        (caller holds the name lock).  Idempotent — a fleet that failed
        after promotion reruns solo without an upload left to promote."""
        if req.upload is not None:
            os.replace(req.upload, req.spool)
            req.upload = None

    @staticmethod
    def discard_upload(req: Request) -> None:
        """Drop an upload temp that will never execute (admission reject,
        expired deadline)."""
        if req.upload is not None:
            try:
                os.unlink(req.upload)
            except OSError:
                pass
            req.upload = None

    @staticmethod
    def archive_shape(spool: str) -> tuple[int, int, int, int]:
        """(k, p, w, total_size) from the archive's .METADATA — the shape
        bucket and DRR cost of a decode/scrub request."""
        from ..utils.fileformat import metadata_file_name, read_metadata_ext

        meta = metadata_file_name(spool)
        total_size, p, k, _mat, w, _crcs = read_metadata_ext(meta)
        return k, p, w, max(1, int(total_size))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeDaemon":
        # A daemon without metrics would serve an empty /metrics forever.
        _metrics.force_enable()
        # With RS_TRACE set, the daemon OWNS one lifetime trace session
        # (exported at close): per-op sessions join it (sessions are
        # reentrant), so one Perfetto file covers the whole serving run
        # and the ack-time request spans (obs/reqtrace.py) always find
        # an active tracer — per-op sessions would already be closed.
        if os.environ.get("RS_TRACE"):
            self._trace_cm = _tracing.session()
            self._trace_cm.__enter__()
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="rs-serve-exec")
        self._sched_thread = threading.Thread(
            target=self._schedule, name="rs-serve-sched", daemon=True)
        self._sched_thread.start()
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="rs-serve-http",
            daemon=True)
        self._serve_thread.start()
        if self.maint is not None:
            self.maint.start()
        return self

    def warm(self, k: int, p: int, *, w: int = 8, strategy: str = "auto",
             generator: str = "vandermonde",
             file_bytes: int | None = None) -> dict:
        """Pre-compile the encode executable for a shape bucket so the
        first real request doesn't pay the compile (api.warm_plan).
        ``file_bytes`` sizes the bucket like the expected requests will
        (small-file workloads hit small column buckets)."""
        from .. import api

        return api.warm_plan(k, p, w=w, strategy=strategy,
                             generator=generator, file_bytes=file_bytes)

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful drain: stop admitting, flush the queue, wait for
        in-flight batches to commit their ordered writes.  Returns True
        when everything flushed inside ``timeout``."""
        _metrics.gauge("rs_serve_draining",
                       "1 while the daemon refuses new work").set(1)
        self.queue.drain()
        deadline = (time.monotonic() + timeout) if timeout else None
        if self._sched_thread is not None:
            self._sched_thread.join(
                None if deadline is None
                else max(0.0, deadline - time.monotonic()))
            if self._sched_thread.is_alive():
                return False
        with self._inflight_cond:
            while self._inflight:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
        return True

    def close(self, drain: bool = True,
              timeout: float | None = None) -> None:
        """Shut down: optional graceful drain, then stop the listener and
        join every thread (the restart path must be able to rebind)."""
        if self._closed:
            return
        self._closed = True
        if self.maint is not None:
            # Stop sourcing new maintenance jobs BEFORE the drain; an
            # in-flight job finishes inside it like any other request.
            self.maint.stop(wait=False)
        if drain:
            self.drain(timeout)
        else:
            self.queue.drain()
        if self._pool is not None:
            self._pool.shutdown(wait=drain)
        if self._serve_thread is not None:
            # shutdown() handshakes with a RUNNING serve_forever loop —
            # on a bound-but-never-started daemon it would block forever.
            self._server.shutdown()
        self._server.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(5)
        if self._sched_thread is not None:
            self._sched_thread.join(5)
        if self._trace_cm is not None:
            # Export the daemon-lifetime trace after every thread that
            # could still be recording spans has joined.
            self._trace_cm.__exit__(None, None, None)
            self._trace_cm = None

    # -- introspection -------------------------------------------------------

    def health(self) -> dict:
        return {
            "ok": True,
            "role": "rs-serve",
            "uptime_s": round(time.time() - self._started, 3),
            "host": os.uname().nodename,
            "run": _runlog.run_id(),
            "backend": _runlog.backend_name(),
            "root": self.root,
            "draining": self.queue.draining,
            "queue_depth": self.queue.depth(),
            "inflight": self._inflight,
            "requests_done": self.requests_done,
            "requests_failed": self.requests_failed,
        }

    def fleet_health(self) -> dict:
        """``GET /health``: the risk-ranked fleet durability report
        (obs/health.py) replayed from the damage ledger.  Each call
        replays the current ledger — concurrent scrub appends are safe
        to read mid-write (whole-line O_APPEND records; the reader skips
        a torn tail) — and refreshes the ``rs_durability_*`` gauges."""
        if not _runlog.enabled():
            return {
                "kind": "rs_health", "enabled": False,
                "error": "no damage ledger (start the daemon with "
                "RS_RUNLOG set)",
            }
        state = _health.load()
        report = _health.fleet_report(state)
        report["enabled"] = True
        _health.export_metrics(report)
        return report

    def export_fleet_health(self) -> None:
        """Scrape-time refresh of the ``rs_durability_*`` gauges (the
        same pattern as the rs_slo_* export: scrub ages advance without
        new damage traffic, so /metrics re-derives them)."""
        if _runlog.enabled():
            try:
                _health.export_metrics(
                    _health.fleet_report(_health.load()))
            except Exception:
                pass  # exposition must not fail the scrape

    def perf_report(self) -> dict:
        """``GET /perf``: the per-cell throughput baseline/drift report
        (obs/perfbase.py) replayed from the run ledger — the daemon's
        own profiled dispatches (``RS_PROF`` sampled) feed the same
        cells ``rs perf --check`` gates on."""
        from ..obs import perfbase as _perfbase

        if not _runlog.enabled():
            return {
                "kind": "rs_perf_report", "enabled": False,
                "error": "no run ledger (start the daemon with "
                "RS_RUNLOG set)",
            }
        report = _perfbase.report(_runlog.read_records(_runlog.path()))
        report["enabled"] = True
        _perfbase.export_gauges(report)
        return report

    def export_perf_gauges(self) -> None:
        """Scrape-time refresh of the ``rs_perf_baseline_*`` gauges
        (same pattern as the rs_slo_*/rs_durability_* exports: current
        medians move as sampled dispatches land, so /metrics
        re-derives the ratio against the blessed baseline)."""
        if _runlog.enabled():
            from ..obs import perfbase as _perfbase

            try:
                _perfbase.export_gauges(
                    _perfbase.report(
                        _runlog.read_records(_runlog.path())))
            except Exception:
                pass  # exposition must not fail the scrape

    def stats(self) -> dict:
        # Warm-path facts next to the queue counters: which strategy
        # decisions this daemon is running on (measured / ledger-loaded)
        # and whether schedule compiles are being served by the
        # persistent store — the restart-latency story in one scrape
        # (docs/XOR.md "The persistent store").
        from .. import tune as _tune
        from ..ops import xor_gemm as _xg
        from ..update import group_stats as _group_stats

        return {
            "queue": self.queue.snapshot(),
            "batcher": self.batcher.snapshot(),
            "workers": self.workers,
            "inflight": self._inflight,
            "requests_done": self.requests_done,
            "requests_failed": self.requests_failed,
            "strategies": {
                "autotune_decisions": _tune.decisions(),
                "schedule_store": _xg.store_stats(),
            },
            # Write-combining facts (docs/UPDATE.md "Group commit"):
            # config (harvest window, per-group edit cap) next to the
            # live group-size / fsync tallies.
            "group_commit": {
                "window_ms": self.batcher.batch_ms,
                **_group_stats(),
            },
            # Object-store façade health (docs/STORE.md): per-tenant
            # bucket accounting — objects, live/dead bytes, pending
            # compactions.  Open buckets report their live in-memory
            # view (O(archives), no log replay per scrape); buckets
            # this daemon never opened get the read-only disk probe.
            "store": self._store_block(),
            # Hot-object read cache (serve/objcache.py): the zipf A/B's
            # scrape target — hit-rate, resident bytes, evictions.
            "objcache": self.objcache.stats(),
            # Lifecycle plane config (docs/SERVE.md "Request lifecycle").
            "slo": {
                "configured": bool(self.slo.objectives),
                "objectives": [o.describe() for o in self.slo.objectives],
                "windows_s": list(self.slo.windows),
            },
            "reqtrace": {
                "enabled": _reqtrace.enabled(),
                "ring": _reqtrace.ring_capacity(),
            },
            # Background-maintenance plane (docs/MAINT.md): controller
            # counters only — the ledger-replaying queue snapshot lives
            # on GET /maint, not in every /stats scrape.
            "maint": ({"enabled": True, **self.maint.stats()}
                      if self.maint is not None
                      else {"enabled": False}),
        }

    def _store_block(self) -> dict:
        from .. import store as _store

        tenants = {}
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            names = []
        for t in names:
            tdir = os.path.join(self.root, t)
            if not os.path.isdir(tdir):
                continue
            buckets = {}
            cold = []  # buckets this daemon never opened
            for name in _store.list_buckets(tdir):
                b = _store.cached_bucket(tdir, name)
                if b is None:
                    cold.append(name)
                    continue
                # Live in-memory view — no on-disk log replay per
                # scrape (a monitoring poller must stay O(archives)).
                s = b.stats()
                buckets[name] = {
                    "objects": s["objects"],
                    "archives": len(s["archives"]),
                    "live_bytes": s["live_bytes"],
                    "dead_bytes": s["dead_bytes"],
                    "index_records": s["index_records"],
                    "index_active_records": s["index_active_records"],
                    "open": s["open"],
                    "pending_drops": 0,  # resolved at load by contract
                    "pending_journals": 0,
                    "pending_compactions": s["pending_compactions"],
                    "config": s["config"],
                }
            if cold:
                probed = _store.probe(tdir)["buckets"]
                for name in cold:
                    if name in probed:
                        buckets[name] = probed[name]
            if buckets:
                tenants[t] = buckets
        return {
            "tenants": tenants,
            "knobs": {
                "RS_STORE_STRIPE_BYTES": _store.stripe_bytes_env(),
                "RS_STORE_COMPACT_DEAD_FRAC": _store.compact_dead_frac(),
                "RS_STORE_SNAPSHOT_RECORDS":
                    _store.snapshot_records_env(),
                "RS_STORE_LIST_LIMIT": list_limit_env(),
            },
        }

    # -- scheduling / execution ----------------------------------------------

    def _schedule(self) -> None:
        while True:
            batches = self.batcher.next_batches(timeout=0.25)
            if batches:
                for group in batches:
                    with self._inflight_cond:
                        self._inflight += len(group)
                    self._slots.acquire()  # blocks until a worker frees
                    self._pool.submit(self._run_group, group)
                continue
            if self.queue.draining and not self.queue.depth():
                return  # drained dry — scheduler done

    def finish_request(self, req: Request, status: int | None) -> None:
        """The ack boundary, called by the HANDLER after the response
        bytes are written (admission rejections included): stamp ``ack``,
        fold the wide lifecycle event (ring + ledger + stage quantiles +
        trace spans — obs/reqtrace.py), and feed the SLO engine with the
        user-visible wall (admission to response)."""
        now = time.monotonic()
        _reqtrace.mark(req, "ack", now)
        _reqtrace.emit(req, status=status)
        if status is not None:
            # status None = the CLIENT went away mid-response (broken
            # pipe): no user-visible outcome exists, and an impatient
            # load generator must not burn the daemon's availability
            # budget — the wide event above still records the abort
            # (outcome with status null).
            self.slo.observe(req.tenant, req.op, now - req.arrival,
                             ok=(status == 200), t=now)

    @staticmethod
    def _mark_device_done(req: Request, timer: PhaseTimer) -> None:
        """Derived device/drain boundary for the pipelined file ops:
        their writes OVERLAP compute (write-behind, docs/IO.md), so no
        single instant separates the two — the stamp is now minus the
        op's accumulated write-phase wall, clamped to the dispatch stamp
        so the timeline stays monotonic.  The write-group path stamps
        the true boundary instead (update/group.py stage hook)."""
        if req.stages is None or not timer.enabled:
            return
        now = time.monotonic()
        write_s = sum(v for name, v in timer.acc.items()
                      if name.startswith("write") and name.endswith("(io)"))
        _reqtrace.mark(req, "device_done",
                       min(now, max(req.t_dispatch, now - write_s)))

    def _finish(self, req: Request, outcome: str, result=None,
                error: BaseException | None = None) -> None:
        now = time.monotonic()
        # Service time stamped directly at the execution boundary, not
        # derived by subtraction: dispatch -> completion, EXCLUDING the
        # batch-form/slot waits and the response write (the old
        # arrival-minus-queue-wait formula folded both in, overstating
        # device time for every batched request).
        if req.t_dispatch:
            req.service_s = now - req.t_dispatch
            _reqtrace.mark(req, "drain_done", now)
        else:  # never dispatched (expired in the batch window)
            req.service_s = 0.0
        _metrics.counter(
            "rs_serve_requests_total", "serve requests by outcome",
        ).labels(op=req.op, tenant=req.tenant, outcome=outcome).inc()
        _metrics.quantile(
            "rs_serve_request_wall_seconds",
            "request wall (admission to completion), streaming quantiles",
        ).labels(op=req.op).observe(time.monotonic() - req.arrival)
        _metrics.quantile(
            "rs_serve_queue_wait_seconds",
            "time spent waiting for admission-queue dispatch",
        ).labels(op=req.op).observe(req.queue_wait_s)
        with self._inflight_cond:  # executor threads race these counters
            if outcome == "ok":
                self.requests_done += 1
            else:
                self.requests_failed += 1
        req.finish(outcome, result=result, error=error)

    def _run_group(self, group: list[Request]) -> None:
        try:
            _metrics.histogram(
                "rs_serve_batch_size",
                "requests coalesced per shape-bucketed batch",
                buckets=(1, 2, 4, 8, 16, 32, 64),
            ).observe(len(group))
            now = time.monotonic()
            live: list[Request] = []
            for req in group:
                if req.expired(now):
                    self.discard_upload(req)
                    self._finish(req, "expired", error=TimeoutError(
                        "deadline exceeded before execution"))
                else:
                    req.batch_size = len(group)
                    live.append(req)
            if not live:
                return
            t_disp = time.monotonic()
            for req in live:
                # Execution starts here — the service_s anchor (always
                # stamped; the stage dict only when the plane is on).
                req.t_dispatch = t_disp
                _reqtrace.mark(req, "dispatch", t_disp)
            if len(live) > 1 and live[0].op == "object_put":
                # Object write combining (docs/STORE.md): the shape key
                # pins these to one (tenant, bucket), so the window's
                # harvest commits as ONE grouped stripe append + ONE
                # index fsync — a PUT burst costs one journal fsync
                # chain and one stacked E·Δ GEMM.
                if self._run_object_put_group(live):
                    return
                _metrics.counter(
                    "rs_serve_batch_fallbacks_total",
                    "batches degraded to per-request execution",
                ).inc()
            if len(live) > 1 and live[0].op in ("update", "append"):
                # Write combining (docs/UPDATE.md "Group commit"): the
                # shape key pins these to one (tenant, archive), so the
                # window's harvest executes as ONE group-committed batch
                # under the per-name lock — one journal fsync chain, one
                # metadata rewrite, one generation bump — and every
                # request acks only after that commit point.
                if self._run_write_group(live):
                    return
                _metrics.counter(
                    "rs_serve_batch_fallbacks_total",
                    "batches degraded to per-request execution",
                ).inc()
            distinct = len({(r.tenant, r.name) for r in live})
            if (len(live) > 1 and distinct == len(live)
                    and live[0].op in ("encode", "decode")):
                # Duplicate (tenant, name) members force the solo path:
                # a fleet would encode one spool twice (or collapse two
                # decodes onto one output); solo runs serialize them
                # under the per-name lock with per-seq outputs.
                if self._run_fleet(live):
                    return
                # Fleet is fail-fast: one poisoned request aborts the
                # batch.  Isolation fallback — rerun each request solo so
                # only the truly failing one reports an error.
                _metrics.counter(
                    "rs_serve_batch_fallbacks_total",
                    "batches degraded to per-request execution",
                ).inc()
            for req in live:
                self._run_solo(req)
        except BaseException as e:  # scheduler must survive anything
            for req in group:
                if not req.done.is_set():
                    self.discard_upload(req)
                    self._finish(req, "error", error=e)
        finally:
            self._slots.release()
            with self._inflight_cond:
                self._inflight -= len(group)
                self._inflight_cond.notify_all()

    def _run_write_group(self, live: list[Request]) -> bool:
        """One group-committed update/append batch for same-archive write
        requests, forced into a SINGLE all-or-nothing group
        (``group_edits=len(edits)`` overrides ``RS_UPDATE_GROUP_WINDOW``)
        so a failed batch provably committed nothing.  Returns True when
        every request finished here; False when the caller should fall
        back to per-request isolation (a single bad edit — e.g. an
        out-of-range offset — must only fail its own request).  Fallback
        is only safe when the archive's generation did not move under the
        failed call — otherwise a solo re-run would apply already-
        committed edits twice (e.g. the journal unlink failing AFTER the
        commit point), so those requests fail with the truth instead."""
        from .. import api
        from ..utils.fileformat import metadata_file_name, read_archive_meta

        ordered = sorted(live, key=lambda r: r.seq)  # submission order
        edits = [
            {"op": r.op, "at": r.at, "src": r.upload} if r.op == "update"
            else {"op": "append", "src": r.upload}
            for r in ordered
        ]
        lead = ordered[0]
        # Group <-> request-id join (docs/SERVE.md "Request lifecycle"):
        # ONE group id covers the whole combined commit; every member
        # still acks under its own request id, and the group engine tags
        # its dispatch span + summary with the group id so the commit is
        # attributable from either side.
        group_id = f"wg-{_reqtrace.new_request_id()}"
        for r in ordered:
            r.group_id = group_id

        def _stage(stage: str) -> None:
            now = time.monotonic()
            for r in ordered:
                _reqtrace.mark(r, stage, now)

        def _generation():
            try:
                return read_archive_meta(
                    metadata_file_name(lead.spool)).generation
            except Exception:
                return None

        try:
            with self._name_lock((lead.tenant, lead.name)):
                gen0 = _generation()
                try:
                    summary = api.update_file_many(
                        lead.spool, edits, strategy=lead.strategy,
                        group_edits=len(edits), group_tag=group_id,
                        stage_hook=_stage,
                    )
                except Exception as e:
                    # Fall back ONLY on proof nothing committed: both
                    # generation reads succeeded and match.  gen0
                    # unreadable proves the DAEMON'S read failed, not
                    # that update_file_many's did — a transient error
                    # there plus a post-commit failure would make a solo
                    # re-run double-apply.
                    if gen0 is not None and _generation() == gen0:
                        for r in ordered:  # rerun solo — not this group
                            r.group_id = None
                        return False
                    for r in ordered:
                        self.discard_upload(r)
                        self._finish(r, "error", error=e)
                    return True
        except Exception:
            for r in ordered:
                r.group_id = None
            return False
        for r in ordered:
            self.discard_upload(r)
            self._finish(r, "ok",
                         result={**summary, "grouped": len(ordered),
                                 "group_id": group_id})
        return True

    def _object_bucket(self, req: Request):
        from .. import store as _store

        return _store.open_bucket(
            os.path.join(self.root, req.tenant), req.name,
            create=req.op == "object_put",
            k=req.k or None, p=req.p or None, w=req.w or None,
            stripe_bytes=req.stripe_bytes,
        )

    @staticmethod
    def _object_payload(req: Request) -> bytes:
        with open(req.upload, "rb") as fp:
            return fp.read()

    def _object_get(self, req: Request) -> bytes:
        """GET /o/ read plane: consult the hot-object cache BEFORE the
        windowed read lane (caller holds the per-name lock, so the
        verdict cannot race a same-name write).  A hit is as checked as
        a miss — the cached location must equal the CURRENT index entry
        and the bytes re-verify their CRC32 (serve/objcache.py); a miss
        reads through store/readpath.py and fills the cache with the
        exact entry it served."""
        cache = self.objcache
        bucket = self._object_bucket(req)
        if not cache.enabled:
            req.cache = "bypass"
            info: dict = {}
            data = bucket.get(req.key, info=info)
            req.path = info.get("path")
            return data
        entry = bucket.entry_for(req.key)  # ObjectNotFound -> clean 404
        data = cache.get(req.tenant, req.name, req.key, entry)
        if data is not None:
            req.cache, req.path = "hit", "cached"
            _metrics.counter(
                "rs_serve_device_dispatches_avoided_total",
                "requests served without touching the device read lane",
            ).labels(op="object_get").inc()
            return data
        info = {}
        data = bucket.get(req.key, info=info)
        req.cache, req.path = "miss", info.get("path")
        served = info.get("entry")
        if served is not None:
            cache.put(req.tenant, req.name, req.key, served, data)
        return data

    def _run_object_put_group(self, live: list[Request]) -> bool:
        """One put_many batch for a same-bucket PUT harvest (submission
        order; later duplicate keys win, like sequential PUTs).
        All-or-nothing by construction — put_many commits nothing on
        failure — so the isolation fallback (return False) can always
        rerun members solo without double-applies."""
        from ..update.engine import SimulatedCrash

        ordered = sorted(live, key=lambda r: r.seq)
        group_id = f"og-{_reqtrace.new_request_id()}"
        for r in ordered:
            r.group_id = group_id
        try:
            with self._name_lock((ordered[0].tenant, ordered[0].name)):
                bucket = self._object_bucket(ordered[0])
                items = [(r.key, self._object_payload(r))
                         for r in ordered]
                locations = bucket.put_many(items)
        except SimulatedCrash:
            raise  # chaos-only: not a fallback case, the disk is torn
        except Exception:
            for r in ordered:
                r.group_id = None
            return False
        for r, loc in zip(ordered, locations):
            self.objcache.invalidate(r.tenant, r.name, r.key)
            self.discard_upload(r)
            self._finish(r, "ok", result={
                **loc, "grouped": len(ordered), "group_id": group_id})
        return True

    def _run_fleet(self, live: list[Request]) -> bool:
        """One warm-executable fleet for a same-shape batch; False when it
        failed and the caller should fall back to solo isolation."""
        from .. import api

        lead = live[0]
        try:
            with self._locked_names(live):
                if lead.op == "encode":
                    for r in live:
                        self._promote_upload(r)
                    results = api.encode_fleet(
                        [r.spool for r in live], lead.k, lead.p,
                        generator=lead.generator, strategy=lead.strategy,
                        checksums=lead.checksums, w=lead.w,
                        layout=lead.layout,
                    )
                    for r in live:
                        self._finish_encode(r, results[r.spool])
                else:
                    outputs = {r.spool: self._decode_out(r)
                               for r in live}
                    results = api.decode_fleet(
                        [r.spool for r in live], outputs,
                        strategy=lead.strategy,
                    )
                    for r in live:
                        self._finish(r, "ok", result=results[r.spool])
            return True
        except Exception:
            return False

    @staticmethod
    def _decode_out(req: Request) -> str:
        # Unique per request: concurrent decodes of one archive must not
        # race on the output path (seq is admission-unique).
        return f"{req.spool}.out.{req.seq}"

    def _finish_encode(self, req: Request, files: list[str]) -> None:
        if not req.keep:
            try:
                os.unlink(req.spool)
            except OSError:
                pass
        self._finish(req, "ok", result=files)

    def _run_solo(self, req: Request) -> None:
        from .. import api

        # Phase accounting feeds the derived device/drain stage boundary
        # (_mark_device_done); disabled with the lifecycle plane so the
        # hot path pays nothing extra when telemetry is off.
        timer = PhaseTimer(enabled=req.stages is not None)
        try:
            if req.op == "maint":
                # Maintenance job closure (docs/MAINT.md): runs under
                # the FOREGROUND (tenant, name) lock of its target so a
                # repair excludes that archive's own writes; errors land
                # in the generic handler like any other op (no-wedge).
                with self._name_lock(req.lock_key
                                     or (req.tenant, req.name)):
                    result = req.job()
                self._finish(req, "ok", result=result)
                return
            with self._name_lock((req.tenant, req.name)):
                if req.op == "encode":
                    self._promote_upload(req)
                    files = api.encode_file(
                        req.spool, req.k, req.p,
                        generator=req.generator,
                        strategy=req.strategy, checksums=req.checksums,
                        w=req.w, layout=req.layout, timer=timer,
                    )
                    self._mark_device_done(req, timer)
                    self._finish_encode(req, files)
                elif req.op == "decode":
                    out = api.auto_decode_file(
                        req.spool, self._decode_out(req),
                        strategy=req.strategy, timer=timer,
                    )
                    self._mark_device_done(req, timer)
                    self._finish(req, "ok", result=out)
                elif req.op == "object_put":
                    bucket = self._object_bucket(req)
                    loc = bucket.put(req.key, self._object_payload(req))
                    self.objcache.invalidate(req.tenant, req.name,
                                             req.key)
                    self._mark_device_done(req, timer)
                    self.discard_upload(req)
                    self._finish(req, "ok", result=loc)
                elif req.op == "object_get":
                    data = self._object_get(req)
                    self._mark_device_done(req, timer)
                    self._finish(req, "ok", result=data)
                elif req.op == "object_delete":
                    bucket = self._object_bucket(req)
                    out = bucket.delete(req.key)
                    self.objcache.invalidate(req.tenant, req.name,
                                             req.key)
                    self._mark_device_done(req, timer)
                    self._finish(req, "ok", result=out)
                elif req.op in ("update", "append"):
                    # The upload temp IS the payload (never promoted onto
                    # the spool — the archive's chunks are the target).
                    if req.op == "update":
                        summary = api.update_file(
                            req.spool, req.at, src=req.upload,
                            strategy=req.strategy, timer=timer,
                        )
                    else:
                        summary = api.append_file(
                            req.spool, src=req.upload,
                            strategy=req.strategy, timer=timer,
                        )
                    self._mark_device_done(req, timer)
                    self.discard_upload(req)
                    self._finish(req, "ok", result=summary)
                else:  # scrub
                    report = api.scan_file(req.spool,
                                           syndrome=req.syndrome)
                    self._finish(req, "ok", result=report)
        except Exception as e:
            # Bounded per-request failure (injected faults land here after
            # the retry plane gave up): 500 for THIS request, queue moves
            # on — the no-wedge contract.
            self.discard_upload(req)
            self._finish(req, "error", error=e)


def main(argv=None) -> int:
    """The ``rs serve`` subcommand."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="rs serve",
        description="Resident multi-tenant encode/decode daemon: "
        "POST /encode /decode /scrub, admission control, cross-request "
        "batching, graceful drain on SIGTERM (docs/SERVE.md).",
    )
    ap.add_argument("--root", default=None,
                    help="data root (default $RS_SERVE_ROOT or "
                    "./rs_serve_root); one namespace dir per tenant")
    ap.add_argument("--port", type=int, default=None,
                    help=f"bind port (default $RS_SERVE_PORT or "
                    f"{DEFAULT_PORT}; 0 = ephemeral)")
    ap.add_argument("--addr", default=None,
                    help="bind address (default $RS_SERVE_ADDR or "
                    "127.0.0.1 — no auth, keep it local)")
    ap.add_argument("--depth", type=int, default=None,
                    help="admission depth (default $RS_SERVE_DEPTH or 64)")
    ap.add_argument("--batch-ms", type=float, default=None,
                    help="coalescing window (default $RS_SERVE_BATCH_MS "
                    "or 5)")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="batch size cap (default $RS_SERVE_MAX_BATCH "
                    "or 16)")
    ap.add_argument("--workers", type=int, default=None,
                    help="executor threads (default $RS_SERVE_WORKERS "
                    "or 2)")
    ap.add_argument("--warm", metavar="K,N[,W[,BYTES]]", action="append",
                    default=[],
                    help="pre-compile the encode executable for shape "
                    "K,N[,W] before listening, bucket-sized for BYTES-"
                    "sized files when given (repeatable)")
    ap.add_argument("--faults", metavar="SPEC", default=None,
                    help="activate the deterministic fault plane for the "
                    "daemon's lifetime (same grammar as RS_FAULTS; "
                    "docs/RESILIENCE.md)")
    ap.add_argument("--slo", metavar="SPEC", default=None,
                    help="per-tenant SLO objectives (same grammar as "
                    "RS_SLO, e.g. 'default:encode:p99=250ms,avail=99.9'; "
                    "GET /slo reports attainment + burn rates)")
    ap.add_argument("--maint", action="store_true",
                    help="run the background-maintenance plane (repair/"
                    "scrub/compaction as a throttled tenant; also "
                    "RS_MAINT=1 — docs/MAINT.md)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)

    root = args.root or os.environ.get("RS_SERVE_ROOT") or "rs_serve_root"
    if args.port is None:
        try:
            args.port = int(os.environ.get("RS_SERVE_PORT", DEFAULT_PORT))
        except ValueError:
            print(f"rs serve: RS_SERVE_PORT="
                  f"{os.environ['RS_SERVE_PORT']!r} is not a port",
                  file=sys.stderr)
            return 2

    fault_ctx = None
    if args.faults:
        from ..resilience import faults as _faults

        try:
            plan = _faults.parse_plan(args.faults, seed=_faults.env_seed())
        except ValueError as e:
            print(f"rs serve: bad --faults spec: {e}", file=sys.stderr)
            return 2
        fault_ctx = _faults.activate(plan)
        fault_ctx.__enter__()

    try:
        daemon = ServeDaemon(
            root, port=args.port, addr=args.addr, depth=args.depth,
            batch_ms=args.batch_ms, max_batch=args.max_batch,
            workers=args.workers, slo_spec=args.slo,
            maint=True if args.maint else None,
        )
    except _slo.SLOSpecError as e:
        print(f"rs serve: bad --slo/RS_SLO spec: {e}", file=sys.stderr)
        if fault_ctx is not None:
            fault_ctx.__exit__(None, None, None)
        return 2
    except OSError as e:
        print(f"rs serve: cannot bind: {e}", file=sys.stderr)
        if fault_ctx is not None:
            fault_ctx.__exit__(None, None, None)
        return 1

    stop = threading.Event()

    def _on_signal(signum, frame):
        # Handler just flags; the drain (device flushes, ordered commits)
        # runs on the main thread below.
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    daemon.start()
    for spec in args.warm:
        try:
            parts = [int(x) for x in spec.split(",")]
        except ValueError:
            parts = []
        if len(parts) < 2 or parts[1] <= parts[0]:
            print(f"rs serve: bad --warm {spec!r} "
                  "(want K,N[,W[,BYTES]], n > k)", file=sys.stderr)
            daemon.close(drain=False)
            if fault_ctx is not None:
                fault_ctx.__exit__(None, None, None)
            return 2
        daemon.warm(parts[0], parts[1] - parts[0],
                    w=parts[2] if len(parts) > 2 else 8,
                    file_bytes=parts[3] if len(parts) > 3 else None)
    print(f"rs serve: listening on http://{daemon.addr}:{daemon.port} "
          f"(root {daemon.root}, depth {daemon.queue.max_depth}, "
          f"batch {daemon.batcher.batch_ms}ms x{daemon.batcher.max_batch}, "
          f"{daemon.workers} workers"
          f"{', maint on' if daemon.maint is not None else ''}) "
          f"— SIGTERM drains", file=sys.stderr)
    try:
        stop.wait()
    finally:
        print("rs serve: draining...", file=sys.stderr)
        daemon.close(drain=True)
        if fault_ctx is not None:
            fault_ctx.__exit__(None, None, None)
        print(f"rs serve: drained ({daemon.requests_done} ok, "
              f"{daemon.requests_failed} failed, "
              f"{daemon.queue.rejected} rejected)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
