"""Cross-request batching by plan-cache shape bucket.

The residency win (docs/SERVE.md): concurrent small requests that share a
``(k, n, w, strategy)`` shape dispatch through ONE warm AOT executable
from the plan cache (plan.py, PR 1) and stream their writes through one
shared write-behind lane (io_executor.py, PR 3) — the fleet entry points
(``api.encode_fleet`` / ``api.decode_fleet``) already implement exactly
that interleave for CLI batches; the batcher's job is to FORM those
batches out of an online arrival stream.

Discipline: when the scheduler pops the first waiting request it opens a
coalescing window of ``RS_SERVE_BATCH_MS`` (default 5 ms — a latency tax
any single request pays at most once) and keeps popping — still under the
admission queue's fairness order — until the window closes or
``RS_SERVE_MAX_BATCH`` requests are in hand.  The window's harvest is
then grouped by shape bucket; each group executes as one fleet.  A window
of one request degrades to the solo path with zero extra delay beyond the
window itself; ``RS_SERVE_BATCH_MS=0`` disables coalescing entirely.

Import cost: stdlib only.
"""

from __future__ import annotations

import threading
import time

from ..obs import reqtrace as _reqtrace
from ..utils.env import float_env as _float_env, int_env as _int_env
from .queue import AdmissionQueue, Request


DEFAULT_BATCH_MS = 5.0
DEFAULT_MAX_BATCH = 16


class Batcher:
    """Forms shape-bucketed batches from an :class:`AdmissionQueue`.

    One consumer (the daemon's scheduler thread) calls
    :meth:`next_batches`; stats are read by ``/stats`` under a lock.
    """

    def __init__(self, queue: AdmissionQueue,
                 batch_ms: float | None = None,
                 max_batch: int | None = None):
        self.queue = queue
        self.batch_ms = (
            _float_env("RS_SERVE_BATCH_MS", DEFAULT_BATCH_MS)
            if batch_ms is None else float(batch_ms)
        )
        self.max_batch = max(1, (
            _int_env("RS_SERVE_MAX_BATCH", DEFAULT_MAX_BATCH)
            if max_batch is None else int(max_batch)
        ))
        self._lock = threading.Lock()
        self.windows = 0
        self.batches = 0
        self.coalesced = 0  # requests that rode along in a batch of > 1
        self.max_batch_seen = 0
        self._batch_seq = 0  # batch-id source (unique per formed batch)

    def next_batches(self, timeout: float | None = None) \
            -> list[list[Request]] | None:
        """Block up to ``timeout`` for work; returns the next window's
        shape-bucketed batches (each a non-empty list of requests sharing
        one plan-cache key), or None on timeout / drained-empty."""
        first = self.queue.pop(timeout=timeout)
        if first is None:
            return None
        window = [first]
        if self.batch_ms > 0:
            close = time.monotonic() + self.batch_ms / 1000.0
            while len(window) < self.max_batch:
                remaining = close - time.monotonic()
                if remaining <= 0:
                    break
                nxt = self.queue.pop(timeout=remaining)
                if nxt is None:
                    break
                window.append(nxt)
        groups: dict[tuple, list[Request]] = {}
        for req in window:
            groups.setdefault(req.shape_key(), []).append(req)
        batches = list(groups.values())
        formed = time.monotonic()
        with self._lock:
            self.windows += 1
            self.batches += len(batches)
            for b in batches:
                self._batch_seq += 1
                for req in b:
                    # Batch identity + the batch_formed stage stamp: the
                    # window just closed, so every member shares one
                    # instant (the lifecycle plane's batch-wait boundary).
                    req.batch_id = self._batch_seq
                    _reqtrace.mark(req, "batch_formed", formed)
                if len(b) > 1:
                    self.coalesced += len(b)
                self.max_batch_seen = max(self.max_batch_seen, len(b))
        return batches

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "batch_ms": self.batch_ms,
                "max_batch": self.max_batch,
                "windows": self.windows,
                "batches": self.batches,
                "coalesced": self.coalesced,
                "max_batch_seen": self.max_batch_seen,
            }
