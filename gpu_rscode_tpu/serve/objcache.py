"""Zipf-aware hot-object read cache for the daemon's GET /o/ lane.

Under a realistic skewed key distribution (``rs loadgen
--object-zipf``) the same few hot objects are read over and over, and
each windowed read pays k preads + de-interleave + CRC even when the
bytes were served milliseconds ago.  This LRU short-circuits that:
consulted BEFORE the windowed read lane, capped by
``RS_OBJ_CACHE_BYTES`` (default 64 MiB; 0 disables), one entry per
(tenant, bucket, key).

Staleness is impossible by construction, not by invalidation
discipline: a hit serves only when the cached object's FULL recorded
location — (arc, at, len, crc, gen), captured from the index entry the
fill actually read — still equals the bucket's CURRENT index entry
(``Bucket.entry_for``), and the cached bytes re-verify against that
entry's CRC32 (a hit is as checked as a miss).  Archive ids are never
reused and stripe offsets are append-only, so an equal location tuple
names the same committed bytes forever; any overwrite/delete/
compaction re-point changes the tuple and the stale entry simply stops
matching.  The explicit ``invalidate()`` calls on PUT-overwrite/
DELETE (under the daemon's per-name lock ordering) are hygiene — they
free the bytes immediately instead of waiting for LRU pressure.

Exported as ``rs_obj_cache_*`` metrics and the daemon ``/stats``
``objcache`` block; each GET's verdict rides the per-request wide
event (``cache: hit|miss|bypass``) and the ``X-RS-Cache`` header.
"""

from __future__ import annotations

import threading
import zlib
from collections import OrderedDict

from ..obs import metrics as _metrics
from ..utils.env import int_env as _int_env

DEFAULT_CACHE_BYTES = 64 * 1024 * 1024


def cache_bytes_env() -> int:
    """``RS_OBJ_CACHE_BYTES`` — cache capacity in bytes (default
    64 MiB; <= 0 disables the cache entirely)."""
    return _int_env("RS_OBJ_CACHE_BYTES", DEFAULT_CACHE_BYTES)


def _loc_tuple(entry: dict) -> tuple:
    return (entry["arc"], int(entry["at"]), int(entry["len"]),
            int(entry["crc"]) & 0xFFFFFFFF, int(entry["gen"]))


def _counter(name: str, help: str):
    return _metrics.counter(name, help)


class ObjectCache:
    """Byte-capped LRU of hot object payloads, location-validated."""

    def __init__(self, cap_bytes: int | None = None):
        self.cap = (cache_bytes_env() if cap_bytes is None
                    else int(cap_bytes))
        self._lock = threading.Lock()
        # (tenant, bucket, key) -> (loc_tuple, bytes); OrderedDict end
        # is most-recently-used.
        self._items: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    @property
    def enabled(self) -> bool:
        return self.cap > 0

    def get(self, tenant: str, bucket: str, key: str,
            entry: dict) -> bytes | None:
        """The cached payload iff its recorded location equals the
        CURRENT index ``entry`` and the bytes re-verify against the
        entry's CRC32; None (a miss) otherwise."""
        if not self.enabled:
            return None
        ident = (tenant, bucket, key)
        want = _loc_tuple(entry)
        with self._lock:
            item = self._items.get(ident)
            if item is not None and item[0] == want \
                    and zlib.crc32(item[1]) == want[3]:
                self._items.move_to_end(ident)
                self.hits += 1
                _counter("rs_obj_cache_hits_total",
                         "daemon object-cache hits (read lane avoided)"
                         ).inc()
                return item[1]
            if item is not None:
                # Superseded (or, unthinkably, corrupt in memory):
                # drop — the caller refills from the read lane.
                self._drop(ident)
            self.misses += 1
            _counter("rs_obj_cache_misses_total",
                     "daemon object-cache misses").inc()
            return None

    def put(self, tenant: str, bucket: str, key: str, entry: dict,
            data: bytes) -> None:
        """Fill after a read-lane miss: ``entry`` must be the exact
        index entry the read served (``Bucket.get``'s ``info["entry"]``)
        — the validation identity of every future hit."""
        if not self.enabled or len(data) > self.cap:
            return
        ident = (tenant, bucket, key)
        with self._lock:
            self._drop(ident)
            self._items[ident] = (_loc_tuple(entry), bytes(data))
            self._bytes += len(data)
            while self._bytes > self.cap:
                old, (_, blob) = self._items.popitem(last=False)
                self._bytes -= len(blob)
                self.evictions += 1
                _counter("rs_obj_cache_evictions_total",
                         "daemon object-cache LRU evictions").inc()
            self._export()

    def invalidate(self, tenant: str, bucket: str,
                   key: str | None = None) -> None:
        """Hygiene drop on PUT-overwrite/DELETE (one key) or
        compaction/unknown churn (whole bucket, ``key=None``)."""
        if not self.enabled:
            return
        with self._lock:
            if key is not None:
                self.invalidations += self._drop((tenant, bucket, key))
            else:
                doomed = [i for i in self._items
                          if i[0] == tenant and i[1] == bucket]
                for ident in doomed:
                    self.invalidations += self._drop(ident)
            self._export()

    def _drop(self, ident: tuple) -> int:
        item = self._items.pop(ident, None)
        if item is None:
            return 0
        self._bytes -= len(item[1])
        return 1

    def _export(self) -> None:
        _metrics.gauge("rs_obj_cache_bytes",
                       "daemon object-cache resident payload bytes"
                       ).set(self._bytes)
        _metrics.gauge("rs_obj_cache_objects",
                       "daemon object-cache resident objects"
                       ).set(len(self._items))

    def stats(self) -> dict:
        """Schema-stable block for daemon ``/stats`` and rs doctor."""
        with self._lock:
            total = self.hits + self.misses
            return {
                "enabled": self.enabled,
                "cap_bytes": self.cap,
                "bytes": self._bytes,
                "objects": len(self._items),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }
