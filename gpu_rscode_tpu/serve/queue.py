"""Bounded admission queue with per-tenant fairness and deadline ordering.

The serving discipline (docs/SERVE.md): a resident daemon in front of one
accelerator must (1) bound its memory — past ``RS_SERVE_DEPTH`` queued
requests new arrivals are REJECTED (HTTP 429), never buffered without
limit; (2) keep one greedy tenant from starving the others — requests are
scheduled by *deficit round-robin* over per-tenant subqueues, the classic
O(1) byte-fair scheduler (each visit grants a tenant ``RS_SERVE_QUANTUM``
bytes of credit; a request is released only when the tenant's accumulated
deficit covers its cost, so many small requests from tenant B interleave
fairly with tenant A's large ones); and (3) respect deadlines — within a
tenant, requests order by their ``X-RS-Deadline-Ms`` deadline (earliest
first, arrival order breaking ties), and the dispatcher fails requests
whose deadline already passed instead of wasting device time on them.

Thread-safe: handler threads ``submit()``, the scheduler thread ``pop()``s,
and drain flips admission off under one condition variable.

Import cost: stdlib only.
"""

from __future__ import annotations

import bisect
import math
import threading
import time

from ..obs import metrics as _metrics, reqtrace as _reqtrace
from ..utils.env import int_env as _int_env

DEFAULT_DEPTH = 64
DEFAULT_QUANTUM = 256 * 1024  # bytes of credit per DRR visit


class QueueFull(RuntimeError):
    """Admission rejected: the queue is at ``RS_SERVE_DEPTH`` (HTTP 429)."""


class Draining(RuntimeError):
    """Admission rejected: the daemon is draining (HTTP 503)."""


class Request:
    """One admitted unit of work, carried from handler to executor.

    ``cost`` is the request's payload size in bytes (the DRR currency);
    ``deadline`` is an absolute ``time.monotonic()`` instant or None.
    The handler thread blocks on ``done``; the executor fills ``outcome``
    (ok | error | expired), ``result`` / ``error``, and the observability
    fields before setting it.
    """

    __slots__ = (
        "op", "tenant", "name", "spool", "upload", "k", "p", "w",
        "strategy", "generator", "checksums", "syndrome", "keep", "cost",
        "at", "layout", "key", "stripe_bytes", "seq", "arrival",
        "deadline", "batch_size",
        "queue_wait_s", "service_s", "outcome", "result", "error", "done",
        "req_id", "batch_id", "group_id", "t_dispatch", "stages",
        "cache", "path", "job", "lock_key",
    )

    def __init__(self, op: str, tenant: str, name: str, spool: str, *,
                 k: int = 0, p: int = 0, w: int = 8, strategy: str = "auto",
                 generator: str = "vandermonde", checksums: bool = True,
                 syndrome: bool = False, keep: bool = False,
                 at: int = 0, layout: str = "row",
                 key: str | None = None, stripe_bytes: int | None = None,
                 cost: int = 1, deadline: float | None = None,
                 req_id: str | None = None):
        self.op = op
        self.tenant = tenant
        self.name = name
        self.spool = spool
        # Encode uploads land in a per-request temp first; the executor
        # promotes it onto ``spool`` under the daemon's per-name lock
        # (concurrent same-name uploads must never interleave bytes).
        self.upload: str | None = None
        self.k, self.p, self.w = k, p, w
        self.strategy = strategy
        self.generator = generator
        self.checksums = checksums
        self.syndrome = syndrome
        self.keep = keep
        self.at = int(at)         # update: byte offset of the edit
        self.layout = layout      # encode: chunk layout (docs/UPDATE.md)
        self.key = key            # object ops: the object key (/o/ paths)
        self.stripe_bytes = stripe_bytes  # object_put bucket creation
        self.cost = max(1, int(cost))
        self.seq = 0  # assigned at submit (admission order)
        self.arrival = time.monotonic()
        self.deadline = deadline
        self.batch_size = 1
        self.queue_wait_s = 0.0
        self.service_s = 0.0
        self.outcome: str | None = None
        self.result = None
        self.error: BaseException | None = None
        self.done = threading.Event()
        # Lifecycle identity (docs/SERVE.md "Request lifecycle"): the
        # request id is ALWAYS minted (rejection traceability must not
        # depend on telemetry); the stage-stamp dict is allocated only
        # when the reqtrace plane is enabled (obs/reqtrace.py).
        self.req_id = req_id if req_id else _reqtrace.new_request_id()
        self.batch_id = 0         # assigned when the batcher forms a batch
        self.group_id: str | None = None  # write-combined group join
        self.t_dispatch = 0.0     # execution start (service_s anchor)
        self.stages: dict | None = None
        # object_get read-plane observability (serve/objcache.py):
        # cache verdict (hit|miss|bypass) and the lane that produced the
        # bytes (cached|fast|degraded) — wide-event + response-header
        # fields, None for every other op.
        self.cache: str | None = None
        self.path: str | None = None
        # Maintenance-plane requests (op="maint", docs/MAINT.md): the
        # zero-arg job closure the executor runs, and the foreground
        # (tenant, name) lock the job must serialize against (a repair
        # of tenant alpha's archive must exclude alpha's own writes to
        # it, not just other maint jobs).
        self.job = None
        self.lock_key: tuple | None = None

    def shape_key(self) -> tuple:
        """The plan-cache shape bucket this request dispatches under —
        requests sharing a key share one warm AOT executable, so the
        batcher coalesces exactly along it.  Update/append requests key
        by (tenant, archive) instead: writes against ONE archive harvested
        in the same window execute as one group-committed batch (one
        journal fsync chain + one metadata commit — docs/UPDATE.md
        "Group commit"), and mixing updates with appends in that group is
        exactly what the group engine's sequential semantics handle.
        Object PUTs key by (tenant, bucket) the same way: a same-bucket
        PUT burst harvested in one window commits as ONE grouped stripe
        append + ONE index fsync (store/bucket.py put_many)."""
        if self.op in ("update", "append"):
            return ("write", self.tenant, self.name, self.k, self.p,
                    self.w, self.strategy)
        if self.op == "object_put":
            return ("objput", self.tenant, self.name)
        if self.op in ("object_get", "object_delete"):
            # Reads/deletes serialize under the bucket lock anyway;
            # grouping buys nothing — keep them solo batches.
            return (self.op, self.tenant, self.name, self.seq)
        if self.op == "maint":
            # Maintenance jobs are opaque closures — nothing to coalesce.
            return (self.op, self.tenant, self.seq)
        return (self.op, self.k, self.p, self.w, self.strategy,
                self.generator, self.layout)

    def sort_key(self) -> tuple:
        # Earliest deadline first; deadline-less requests behind any
        # deadlined one; admission order breaks ties.
        return (self.deadline if self.deadline is not None else math.inf,
                self.seq)

    def __lt__(self, other: "Request") -> bool:
        return self.sort_key() < other.sort_key()

    def expired(self, now: float | None = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)

    def finish(self, outcome: str, result=None,
               error: BaseException | None = None) -> None:
        self.outcome = outcome
        self.result = result
        self.error = error
        self.done.set()


class AdmissionQueue:
    """Bounded multi-tenant queue: DRR across tenants, deadline order
    within one (module doc).  ``depth``/``quantum`` default from
    ``RS_SERVE_DEPTH`` / ``RS_SERVE_QUANTUM``."""

    def __init__(self, depth: int | None = None,
                 quantum: int | None = None):
        self.max_depth = max(1, depth if depth is not None
                             else _int_env("RS_SERVE_DEPTH", DEFAULT_DEPTH))
        self.quantum = max(1, quantum if quantum is not None
                           else _int_env("RS_SERVE_QUANTUM",
                                         DEFAULT_QUANTUM))
        self._cond = threading.Condition()
        self._queues: dict[str, list[Request]] = {}
        self._deficit: dict[str, int] = {}
        self._active: list[str] = []  # tenants with queued work, RR order
        self._rr = 0
        self._count = 0
        self._seq = 0
        self._draining = False
        self.admitted = 0
        self.rejected = 0

    # -- admission -----------------------------------------------------------

    def submit(self, req: Request) -> Request:
        """Admit ``req`` or raise :class:`QueueFull` / :class:`Draining`.
        Assigns the admission sequence number used for tie-breaking."""
        with self._cond:
            if self._draining:
                self.rejected += 1
                _metrics.counter(
                    "rs_serve_admission_rejects_total",
                    "serve requests rejected at admission",
                ).labels(tenant=req.tenant, reason="draining").inc()
                raise Draining("daemon is draining; not admitting")
            if self._count >= self.max_depth:
                self.rejected += 1
                _metrics.counter(
                    "rs_serve_admission_rejects_total",
                    "serve requests rejected at admission",
                ).labels(tenant=req.tenant, reason="depth").inc()
                raise QueueFull(
                    f"queue at RS_SERVE_DEPTH={self.max_depth}"
                )
            self._seq += 1
            req.seq = self._seq
            q = self._queues.get(req.tenant)
            if q is None:
                q = self._queues[req.tenant] = []
                self._active.append(req.tenant)
                self._deficit.setdefault(req.tenant, 0)
            bisect.insort(q, req)
            self._count += 1
            self.admitted += 1
            _metrics.gauge(
                "rs_serve_queue_depth", "admitted requests waiting"
            ).set(self._count)
            self._cond.notify()
        return req

    # -- scheduling ----------------------------------------------------------

    def _pop_locked(self) -> Request | None:
        if not self._count:
            return None
        if len(self._active) == 1:
            # Single active tenant: no one to be fair to — grant directly
            # instead of spinning quantum-increments up to a large cost.
            t = self._active[0]
            self._deficit[t] = 0
            req = self._queues[t].pop(0)
        else:
            while True:
                self._rr %= len(self._active)
                t = self._active[self._rr]
                head = self._queues[t][0]
                if self._deficit[t] < head.cost:
                    # One quantum per visit, then move to the next tenant
                    # (textbook DRR) — a huge head request accrues credit
                    # across rounds while small tenants keep flowing.
                    self._deficit[t] += self.quantum
                    self._rr += 1
                    continue
                self._deficit[t] -= head.cost
                req = self._queues[t].pop(0)
                break
        if not self._queues[req.tenant]:
            del self._queues[req.tenant]
            idx = self._active.index(req.tenant)
            self._active.pop(idx)
            if idx < self._rr:
                self._rr -= 1  # keep the pointer on the same next tenant
            self._deficit[req.tenant] = 0  # empty queue forfeits credit
        self._count -= 1
        _metrics.gauge(
            "rs_serve_queue_depth", "admitted requests waiting"
        ).set(self._count)
        return req

    def pop(self, timeout: float | None = None) -> Request | None:
        """Next request under the fairness discipline; blocks up to
        ``timeout``.  Returns None on timeout, or immediately when the
        queue is draining and empty (the scheduler's exit signal)."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            while True:
                req = self._pop_locked()
                if req is not None:
                    now = time.monotonic()
                    req.queue_wait_s = now - req.arrival
                    _reqtrace.mark(req, "dequeue", now)
                    return req
                if self._draining:
                    return None
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)

    # -- lifecycle / introspection ------------------------------------------

    def drain(self) -> None:
        """Stop admitting (new submits raise :class:`Draining`); queued
        work keeps draining through ``pop`` until empty."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        with self._cond:
            return self._count

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "depth": self._count,
                "max_depth": self.max_depth,
                "quantum": self.quantum,
                "draining": self._draining,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "tenants": {t: len(q) for t, q in self._queues.items()},
                "deficits": {t: d for t, d in self._deficit.items() if d},
            }
