"""Shape-bucketed execution plans — the dispatch layer of the hot loop.

The reference keeps its GPU busy by overlapping PCIe copies with kernels
(encode.cu:165-218); the JAX port's equivalent tax is *dispatch overhead*:
every distinct segment width (tail segments, small files, per-k sweeps)
costs a fresh XLA trace+compile, and every segment round-trips an unpinned
host buffer.  This module removes both from the segment loop:

* **Shape bucketing** — segment column counts are rounded up a small
  geometric ladder (powers of two of a 128-lane-aligned floor, capped at
  the full segment width), so any file's segment loop compiles at most
  O(log(seg_cols/128)) executables per (k, n, w, strategy) instead of one
  per distinct tail width.  The pad columns are zeros; GF linearity makes
  their output columns zeros too, and the caller-visible result is trimmed
  back to the true width.
* **A bounded, thread-safe plan cache** keyed on (bucket, matrix shape,
  dtypes, w, strategy, mesh fingerprint) holding AOT-lowered/compiled
  callables (``jax.jit(...).lower(...).compile()``), with hit/miss/eviction
  counters and an explicit :meth:`PlanCache.clear` that also invalidates
  the Pallas refold-autotune cache (the two caches go stale together: see
  ADVICE r5 finding 2 and docs/PLAN.md on ``jax.clear_caches()``).
* **Buffer donation** — plans compile a ``donate_argnums`` variant for the
  data operand, used for segments the pipeline itself staged
  (:class:`StagedSegment` marks ownership transfer) whose output can
  actually alias the donated buffer (XLA needs equal sizes: full-k
  decode/repair, not encode's p < k), so XLA reuses the segment's device
  buffer across the loop instead of allocating a fresh output every
  dispatch.  Caller-owned arrays (a bench timing the same device buffer
  repeatedly) are never donated.

Dispatch strategy per plan:

* ``bitplane`` / ``table`` — true AOT: the GEMM is lowered and compiled
  once per plan; later dispatches skip jit's signature machinery entirely.
* ``pallas`` — the FIRST dispatch of each codec runs eagerly through
  ``codec._gf_matmul_pallas_eager`` (preserving the documented contracts:
  failure injection for tests, and RS_PALLAS_REFOLD=autotune calibration
  on concrete arrays); subsequent dispatches run the AOT executable.
  Under autotune the plan times its OWN compiled refold candidates
  (``pallas_gemm.calibrate_aot_refold``) — the eager decision described a
  different compile, and dot speed at w=16 is per-compile bimodal.
* ``xor`` — the plan key additionally carries the COEFFICIENT MATRIX
  DIGEST (the XOR schedule is a function of the matrix values, not just
  its shape), and the cached callable is a composite of three stage
  executables (pack / xor-chain / unpack, ops/xor_gemm.py) — XLA fuses
  a monolithic emission ~2x slower than the staged one.  One schedule
  per digest, never one per dispatch; schedule term counts surface in
  ``describe()`` and ``rs doctor``.  Donation is skipped (the stage
  split owns its intermediates).
* mesh plans — counted and fingerprinted, but the callable is the
  existing jitted ``sharded_gf_matmul`` (XLA's jit cache pins the
  executable; donation is skipped — sharded inputs may be caller-held).
* update-op plans (``codec.update``, docs/UPDATE.md) — the delta-parity
  GEMM ``E·Δ`` dispatches with the SAME (p, k) coefficient shape as
  encode, so its plan key aliases the encode bucket class on purpose: a
  warm encode executable (or ``warm_plan``) serves update traffic with
  zero extra compiles, and the bucket ladder absorbs the small ragged
  widths partial-stripe edits produce.  The ``op`` split lives in the
  metrics (``segments_dispatched{op="update"}``, ``rs_codec_bytes_total``)
  rather than the cache key — compile classes stay shape-pure.

Env knobs (all read per call, so tests can monkeypatch):

* ``RS_PLAN=0`` — disable the whole layer (legacy per-shape jit dispatch).
* ``RS_PLAN_MIN_BUCKET`` — ladder floor, default 128 (the TPU lane width).
* ``RS_PLAN_CACHE_SIZE`` — LRU bound on cached plans, default 64.
* ``RS_PLAN_DONATE`` — ``1`` force donation on, ``0`` off; unset = donate
  on accelerator backends only (CPU XLA rejects donation with a warning).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

import numpy as np

from .obs import (attrib as _attrib, metrics as _metrics,
                  profiler as _prof, tracing as _tracing)


def enabled() -> bool:
    """Whether the plan layer is on (RS_PLAN=0/false/off/no disables)."""
    return os.environ.get("RS_PLAN", "1").lower() not in (
        "0", "false", "off", "no"
    )


def _min_bucket() -> int:
    try:
        v = int(os.environ.get("RS_PLAN_MIN_BUCKET", "128"))
        return v if v > 0 else 128
    except ValueError:
        return 128


def _donation_allowed() -> bool:
    env = os.environ.get("RS_PLAN_DONATE")
    if env is not None:
        return env.lower() not in ("0", "false", "off", "no")
    # CPU XLA refuses donation ("Some donated buffers were not usable")
    # with a UserWarning per compile; accelerators honour it.  Checked on
    # the REAL device platform (not the tpu_devices_present helper, which
    # tests fake to steer strategy selection): donation must follow what
    # the executing backend actually supports.
    import jax

    try:
        plat = jax.devices()[0].platform.lower()
    except Exception:
        return False
    return plat in ("tpu", "gpu", "cuda", "rocm")


def bucket_cols(m: int, cap: int | None = None) -> int:
    """Round a segment column count up the bucket ladder.

    ``cap`` is the plan's maximum width (the full segment width): the
    ladder is min_bucket * 2^j capped there, so a segment loop emits at
    most the full width plus O(log) tail buckets.  ``cap=None`` means "no
    ladder" — direct eager callers (benches, tests) keep their exact shape
    and never pay pad compute.  Widths at or above the cap (including
    chunks smaller than one bucket, where cap == chunk) pass through
    unchanged.
    """
    if cap is None or m >= cap or m <= 0:
        return m
    b = _min_bucket()
    while b < m:
        b <<= 1
    return min(b, cap)


def mesh_fingerprint(mesh) -> tuple:
    """Hashable identity of the dispatch target: which devices, in which
    mesh layout, on which platform.  Part of every plan key so a rebuilt
    mesh (new axis order, different device set) cannot alias a stale
    executable."""
    import jax

    if mesh is None:
        return ("local", jax.default_backend())
    devs = tuple(int(d.id) for d in mesh.devices.flat)
    plat = next(iter(mesh.devices.flat)).platform
    return (
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        devs,
        plat,
    )


class StagedSegment:
    """A segment the pipeline staged onto the device ahead of dispatch.

    Marks ownership transfer: the wrapped array was created by
    :func:`stage_segment` for exactly one GEMM dispatch, so the plan layer
    may DONATE its device buffer.  ``cols`` is the true (pre-pad) column
    count; ``cap`` the plan cap it was bucketed under.  ``host`` keeps the
    (padded) host copy alive until the dispatch succeeds: if a donating
    dispatch fails after invalidating the device buffer (pallas demote
    path), the codec re-stages from it instead of reading a deleted array.
    """

    __slots__ = ("array", "cols", "cap", "host")

    def __init__(self, array, cols: int, cap: int | None, host=None):
        self.array = array
        self.cols = cols
        self.cap = cap
        self.host = host

    @property
    def shape(self):
        return self.array.shape


def stage_segment(B, cap: int | None, retain_host: bool = True):
    """Pad a host segment to its bucket and issue its (async) H2D transfer.

    This is the H2D stage of the 3-stage pipeline (see
    ``parallel.pipeline.DeviceStagingRing``): ``jax.device_put`` returns
    immediately, so the transfer of segment i+1 overlaps segment i's
    compute.  The zero pad is written host-side (one bounded memcpy) so the
    staged buffer is exactly the plan's compiled shape.  The host copy is
    retained only where a dispatch could DONATE the device buffer (and so
    might need to re-stage after a donating failure): donation enabled AND
    the caller says the coming dispatch is aliasable (``retain_host`` —
    encode's p < k output can never alias, so its ring holds no extra host
    memory beyond the prefetcher's own window).
    """
    import jax

    padded = _pad_to(B, bucket_cols(B.shape[1], cap))
    host = padded if retain_host and _donation_allowed() else None
    prof_on = _prof.enabled()
    t0 = time.monotonic() if prof_on else 0.0
    with _tracing.span("h2d_stage", lane="stage", cols=int(B.shape[1]),
                       bucket=int(padded.shape[1])):
        staged = jax.device_put(padded)
    if prof_on:
        # Staging wall as the host observes it (device_put returns once
        # the transfer is SCHEDULED on async backends — no block here,
        # staging exists to overlap); folded into the next dispatch's
        # profile as its h2d field (obs/profiler.py note_staging).
        _prof.note_staging(time.monotonic() - t0, int(padded.nbytes))
    _metrics.counter(
        "rs_segments_staged_total",
        "segments bucket-padded and staged onto the device (H2D issued)",
    ).inc()
    if host is not None:
        # Donation watermark (obs/attrib.py): the extra host memory the
        # donation-recovery copies pin while their segment is in flight.
        _metrics.counter(
            "rs_donation_host_copy_bytes_total",
            "bytes of retained host copies backing donatable segments",
        ).inc(int(host.nbytes))
    return StagedSegment(staged, B.shape[1], cap, host=host)


class ExecutionPlan:
    """One cached executable class: a (bucket, shapes, strategy, target)
    combination, with its AOT-compiled donate/no-donate variants."""

    __slots__ = (
        "key", "strategy", "w", "bucket", "refold", "calls", "donated_calls",
        "compile_seconds", "cost_analysis", "xor_stats", "last_stages",
        "_compiled", "_lock",
    )

    def __init__(self, key, strategy, w, bucket):
        self.key = key
        self.strategy = strategy
        self.w = w
        self.bucket = bucket
        self.refold = None          # pallas plans: resolved at first compile
        self.calls = 0
        self.donated_calls = 0
        self.compile_seconds = 0.0  # lower+compile wall across all variants
        self.cost_analysis = None   # XLA cost model of one dispatch, or None
        self.xor_stats = None       # xor plans: schedule term counts
        self.last_stages = None     # newest RS_PROF stage breakdown, or None
        self._compiled: dict = {}   # donate(bool) -> jax Compiled
        self._lock = threading.Lock()   # serializes this plan's builds

    # -- builders ------------------------------------------------------------

    def _compile(self, A, B, fn, donate: bool):
        import jax

        jitted = jax.jit(fn, donate_argnums=(1,) if donate else ())
        t0 = time.perf_counter()
        with _tracing.span(
            "plan_compile", lane="compile", strategy=self.strategy,
            bucket=int(self.bucket), donate=donate,
        ):
            exe = jitted.lower(
                jax.ShapeDtypeStruct(A.shape, A.dtype),
                jax.ShapeDtypeStruct(B.shape, B.dtype),
            ).compile()
        dt = time.perf_counter() - t0
        self.compile_seconds += dt  # under the plan's own lock (see run())
        if self.cost_analysis is None:
            # Roofline accounting (obs/attrib.py): the XLA cost model of
            # one dispatch — FLOPs, bytes accessed, transcendentals.
            # Variants share compute (donate only changes aliasing), so
            # the first variant's analysis stands for the plan; backends
            # that return None/partial leave it None and `rs analyze`
            # falls back to the analytic model.
            self.cost_analysis = _attrib.extract_cost_analysis(exe)
        _metrics.histogram(
            "rs_plan_compile_seconds",
            "wall seconds spent in AOT lower+compile per plan variant",
        ).labels(strategy=self.strategy).observe(dt)
        return exe

    def _build(self, A, B, donate: bool):
        """Lower + compile this plan's executable for concrete operands.
        Runs under the plan's own lock (see :meth:`run`); compile errors
        propagate to the dispatch site, where the codec's pallas guard can
        demote exactly like an eager failure."""
        w, strategy = self.w, self.strategy
        if strategy == "ring":
            # Same contract as the xor branch below, with the ring
            # three-stage pipeline (ops/ring_gemm.py) as the composite.
            from .ops import ring_gemm as _rg

            t0 = time.perf_counter()
            pipe = _rg.get_ring_pipeline(np.asarray(A), B.shape, B.dtype, w)
            dt = time.perf_counter() - t0
            self.compile_seconds += dt
            if self.cost_analysis is None:
                self.cost_analysis = pipe.cost_analysis
            self.xor_stats = pipe.describe()
            _metrics.histogram(
                "rs_plan_compile_seconds",
                "wall seconds spent in AOT lower+compile per plan variant",
            ).labels(strategy=strategy).observe(dt)
            return pipe
        if strategy == "xor":
            # Digest-keyed composite pipeline (ops/xor_gemm.py): three
            # stage executables whose XOR schedule is baked from the
            # CONCRETE coefficients (the plan key carries the matrix
            # digest, so one schedule serves every dispatch of this
            # matrix — never one per dispatch).  The pipeline cache is
            # shared with the eager path and cleared with this cache.
            # Donation is not applicable: the stage split owns its
            # intermediates, and dispatch() never requests it for xor.
            from .ops import xor_gemm as _xg

            t0 = time.perf_counter()
            pipe = _xg.get_pipeline(np.asarray(A), B.shape, B.dtype, w)
            dt = time.perf_counter() - t0  # ~0 on a pipeline-cache hit
            self.compile_seconds += dt
            if self.cost_analysis is None:
                self.cost_analysis = pipe.cost_analysis
            self.xor_stats = pipe.describe()
            _metrics.histogram(
                "rs_plan_compile_seconds",
                "wall seconds spent in AOT lower+compile per plan variant",
            ).labels(strategy=strategy).observe(dt)
            return pipe
        if strategy == "pallas":
            from .ops import pallas_gemm as _pg

            if self.refold is None:
                self.refold = _pg.plan_refold_resolution(w)
            if self.refold == "autotune":
                # Calibrate against THIS plan's own executables: the eager
                # path's cached decision timed a DIFFERENT compile, and
                # dot speed at w=16 is per-compile bimodal.  Candidates
                # are timed non-donating (a donating warm-up would delete
                # the operand); the winner's plain executable is kept.
                def plain_variant(refold):
                    return self._compile(
                        A, B,
                        lambda a, b: _pg.gf_matmul_pallas(
                            a, b, w=w, refold=refold
                        ),
                        donate=False,
                    )

                self.refold, exe = _pg.calibrate_aot_refold(
                    A, B, w, plain_variant
                )
                self._compiled.setdefault(False, exe)
                if not donate:
                    return exe
            refold = self.refold

            def fn(a, b):
                return _pg.gf_matmul_pallas(a, b, w=w, refold=refold)

        else:
            from .ops.gemm import gf_matmul

            def fn(a, b):
                return gf_matmul(a, b, w=w, strategy=strategy)

        return self._compile(A, B, fn, donate)

    # -- dispatch ------------------------------------------------------------

    def run(self, A, B, donate: bool):
        # The plan's own lock covers check AND build: two threads racing
        # the same cold variant compile once, not twice (the compile is
        # seconds; the serialization is the point).  The dispatch itself
        # runs outside the lock so warm callers never serialize.
        with self._lock:
            exe = self._compiled.get(donate)
            if exe is None:
                t0 = time.perf_counter()
                exe = self._compiled[donate] = self._build(A, B, donate)
                # Cold-dispatch attribution (obs/profiler.py): the build
                # wall is part of THIS dispatch's wall, named `compile`.
                _prof.add_compile(time.perf_counter() - t0)
            self.calls += 1
            if donate:
                self.donated_calls += 1
        _metrics.counter(
            "rs_plan_dispatch_total",
            "GEMM dispatches through cached plan executables",
        ).labels(strategy=self.strategy, donated=donate).inc()
        if self.strategy not in ("xor", "ring") and \
                _prof.active() is not None:
            # Monolithic strategies have one device stage; the xor/ring
            # pipelines attribute their own pack/chain/unpack inside.
            return _prof.run_stage("chain", exe, A, B)
        return exe(A, B)

    def describe(self) -> dict:
        with self._lock:  # a concurrent _build may be inserting a variant
            variants = list(self._compiled)
        out = {
            "strategy": self.strategy,
            "w": self.w,
            "bucket": self.bucket,
            "a_shape": list(self.key[2]),
            "b_dtype": self.key[5],
            "mesh": self.key[6][0] != "local",
            "refold": self.refold,
            "variants": sorted(
                ("donate" if d else "plain") for d in variants
            ) or (["jit"] if self.key[6][0] != "local" else []),
            "calls": self.calls,
            "donated_calls": self.donated_calls,
            "compile_seconds": self.compile_seconds,
            "cost_analysis": self.cost_analysis,
        }
        if self.xor_stats is not None:
            # Schedule economy for `rs doctor`: terms before/after CSE
            # and the matrix digest this plan is keyed by (keyed by the
            # lowering that produced it — "xor" or "ring").
            out[self.strategy] = self.xor_stats
        if self.last_stages is not None:
            # Newest profiled dispatch's stage walls (obs/profiler.py):
            # where this plan's dispatch wall went, in the same stage
            # vocabulary as `rs perf` and the xor_ab captures.
            out["stages"] = self.last_stages
        return out


class PlanCache:
    """Bounded, thread-safe LRU of :class:`ExecutionPlan`.

    The cache lock covers lookup/eviction; each plan's OWN lock covers its
    builds (see :meth:`ExecutionPlan.run`), so a slow compile on one shape
    class never blocks dispatches of another.  ``clear()`` also drops the
    Pallas refold-autotune decisions — both caches pin choices to
    executables XLA may since have evicted, so they are invalidated
    together (pair with ``jax.clear_caches()``).
    """

    def __init__(self, max_size: int | None = None, name: str = "local"):
        self._lock = threading.RLock()
        self._plans: OrderedDict = OrderedDict()
        self._max_size = max_size
        self.name = name
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _count_event(self, event: str) -> None:
        # The plain int attributes stay authoritative (always counted —
        # they are plan-layer contract surface, metrics on or off); the
        # registry mirror makes them part of the unified snapshot's
        # metric families when RS_METRICS is on.
        _metrics.counter(
            "rs_plan_cache_events_total",
            "plan cache lookups by outcome",
        ).labels(cache=self.name, event=event).inc()

    def _bound(self) -> int:
        if self._max_size is not None:
            return self._max_size
        try:
            v = int(os.environ.get("RS_PLAN_CACHE_SIZE", "64"))
            return v if v > 0 else 64
        except ValueError:
            return 64

    def lookup(self, key, strategy, w, bucket) -> "ExecutionPlan":
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                self.hits += 1
                self._plans.move_to_end(key)
                self._count_event("hit")
                return plan
            self.misses += 1
            self._count_event("miss")
            plan = ExecutionPlan(key, strategy, w, bucket)
            self._plans[key] = plan
            while len(self._plans) > self._bound():
                # Eviction needs no autotune invalidation: AOT plans
                # calibrate against their OWN executables (never the
                # eager decision cache), so a rebuilt plan re-measures
                # rather than inheriting a decision about a dead compile.
                self._plans.popitem(last=False)
                self.evictions += 1
                self._count_event("eviction")
            return plan

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()
            self.hits = self.misses = self.evictions = 0
        from .ops.pallas_gemm import clear_autotune_cache
        from .ops.xor_gemm import clear_pipeline_cache

        clear_autotune_cache()
        clear_pipeline_cache()
        # The generation-keyed survivor-subset cache (api.py) memoizes
        # inverses whose xor schedules live in the caches just dropped —
        # clear it too so a post-clear decode re-derives rather than
        # assuming a warm schedule that no longer exists.  (Persistent
        # STORE entries survive by design: they are pure data, re-read
        # and re-validated on the next build — see clear_pipeline_cache.)
        from .api import clear_subset_cache

        clear_subset_cache()

    def stats(self) -> dict:
        # Snapshot under the cache lock, describe() OUTSIDE it: describe
        # takes each plan's own lock, which a multi-second _build may hold
        # — holding the cache lock across that would stall every lookup.
        with self._lock:
            plans = list(self._plans.values())
            out = {
                "enabled": enabled(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "executables": len(plans),
                "max_size": self._bound(),
            }
        out["plans"] = [p.describe() for p in plans]
        out["compile_seconds"] = sum(
            p["compile_seconds"] for p in out["plans"]
        )
        return out

PLAN_CACHE = PlanCache(name="local")
# Mesh dispatches are counter-only entries (the executable lives in the
# jitted collective's own cache, keyed by EXACT shapes — so they are
# counted by exact width, which reflects real mesh compiles).  They live
# in their own cache so unbounded mesh width churn can never evict local
# plans that hold real AOT executables.
MESH_PLAN_CACHE = PlanCache(name="mesh")


def _pad_to(B, bucket: int):
    m = B.shape[1]
    if m == bucket:
        return B
    if isinstance(B, np.ndarray):
        padded = np.zeros((B.shape[0], bucket), dtype=B.dtype)
        padded[:, :m] = B
        return padded
    import jax.numpy as jnp

    return jnp.pad(B, ((0, 0), (0, bucket - m)))


def dispatch(
    A,
    B,
    *,
    w: int,
    strategy: str,
    cap: int | None = None,
    cols: int | None = None,
    donate: bool = False,
    eager_fn=None,
):
    """Plan-cached single-device GEMM dispatch.

    ``A`` (p, k) coefficients, ``B`` (k, m) data — possibly already padded
    to its bucket by :func:`stage_segment`, in which case ``cols`` is the
    true width.  Pads to the bucket, runs the cached executable (or
    ``eager_fn(A, B)`` when given — the codec's first-pallas-dispatch
    contract), and trims the result back to the true width.  ``donate``
    requests the donating variant; it is honoured only for ownership-
    transferred buffers and when the backend supports donation.
    """
    m = cols if cols is not None else B.shape[1]
    bucket = max(bucket_cols(m, cap), B.shape[1])
    key = (
        strategy,
        w,
        tuple(A.shape),
        str(np.dtype(A.dtype)),
        bucket,
        str(np.dtype(B.dtype)),
        mesh_fingerprint(None),
    )
    if strategy in ("xor", "ring"):
        # The XOR/ring schedule is a function of the coefficient VALUES,
        # so the plan key carries the matrix digest (one compiled schedule
        # per matrix, shared by every dispatch — docs/XOR.md); the
        # bucket additionally rounds up to the pipeline's 32-symbol
        # pack alignment (ragged caps only — ladder buckets are already
        # 128-aligned).  ``B`` may be a PackedOperand — a bit-plane
        # handle an earlier chained dispatch packed (docs/XOR.md
        # "Packed-operand reuse"); the pipeline skips its pack stage.
        from .ops.xor_gemm import PackedOperand, matrix_digest, padded_cols

        bucket = max(bucket, padded_cols(bucket))
        key = key[:4] + (bucket,) + key[5:] + (matrix_digest(A, w),)
        if isinstance(B, PackedOperand) and B.shape[1] != bucket:
            raise ValueError(
                f"packed operand cols {B.shape[1]} does not match the "
                f"plan bucket {bucket} — pack after staging, with the "
                "same cap"
            )
    prof = None
    if _prof.enabled():
        nb = getattr(B, "nbytes", None)
        if nb is None and hasattr(B, "cols_true"):  # PackedOperand
            nb = B.rows * B.cols_true * np.dtype(B.dtype).itemsize
        prof = _prof.begin(strategy=strategy, w=w, bucket=int(bucket),
                           bytes_in=int(nb) if nb else None)
    if prof is not None:
        misses_before = PLAN_CACHE.misses
    plan = PLAN_CACHE.lookup(key, strategy, w, bucket)
    if prof is not None:
        _prof.attr(plan_bucket="miss" if PLAN_CACHE.misses > misses_before
                   else "hit")
    try:
        B = _pad_to(B, bucket)
        if eager_fn is not None:
            with plan._lock:
                plan.calls += 1
            out = eager_fn(A, B)
        else:
            # XLA input-output aliasing needs equal buffer sizes: the
            # (rows, m) output can only reuse B's (k, m) buffer when
            # rows == k (full-k decode/repair).  Encode's p < k dispatch
            # would just compile a donate variant that warns 'donated
            # buffers were not usable' and aliases nothing — drop the
            # request instead.  The xor pipeline never donates: its stage
            # split owns the intermediate planes (nor does ring, which
            # shares the split).
            can_alias = A.shape[0] == B.shape[0] and strategy not in (
                "xor", "ring"
            )
            out = plan.run(
                A, B, donate and can_alias and _donation_allowed()
            )
        out = out if bucket == m else out[:, :m]
    except BaseException:
        _prof.discard(prof)
        raise
    if prof is not None:
        event = _prof.finish(prof, out)
        if event is not None and event.get("stages"):
            plan.last_stages = event["stages"]
    return out


def dispatch_mesh(A, B, *, w: int, strategy: str, mesh, stripe_sharded, fn):
    """Mesh-path plan accounting: the executable is pinned by the jitted
    collective's own cache (``fn`` is a ``sharded_gf_matmul`` partial and
    is called directly — caching it here would only pin the caller's codec
    and mesh in a process-global), but the dispatch is registered in
    MESH_PLAN_CACHE so compile classes are counted and fingerprinted per
    mesh.  No donation: sharded inputs may be caller-held."""
    key = (
        strategy,
        w,
        tuple(np.asarray(A).shape),
        str(np.dtype(A.dtype)),
        B.shape[1],
        str(np.dtype(B.dtype)),
        mesh_fingerprint(mesh),
        bool(stripe_sharded),
    )
    plan = MESH_PLAN_CACHE.lookup(key, strategy, w, B.shape[1])
    with plan._lock:
        plan.calls += 1
    return fn(A, B)
