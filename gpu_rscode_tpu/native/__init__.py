"""ctypes bindings for the native host runtime (rs_native.cpp).

The shared library is built on first use with g++ (cached next to the
source; rebuilt when the source is newer).  Every entry point has a NumPy
fallback so the framework works on machines without a toolchain — the
native path is a performance feature, the Python path is the contract.

Maps the reference's native host layer: CPU codec oracle (cpu-rs.c), host
inverter (cpu-decode.c), staging copies (encode.cu:389-398).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time

import numpy as np

from ..obs import metrics as _obs_metrics

_SRC = os.path.join(os.path.dirname(__file__), "rs_native.cpp")
_SO = os.path.join(os.path.dirname(__file__), "librs_native.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_failed = False


class NativeUnavailable(RuntimeError):
    pass


def _count_io(direction: str, call: str, nbytes: int, seconds: float) -> None:
    """rs_io_* accounting for one staging call (no-op unless RS_METRICS).
    ``direction`` is "read" or "write"; ``call`` labels the staging
    primitive so read and write balances stay attributable per path."""
    _obs_metrics.counter(
        f"rs_io_{direction}_bytes_total",
        f"bytes {direction} by the staging-I/O layer",
    ).labels(call=call).inc(nbytes)
    _obs_metrics.counter(
        f"rs_io_{direction}_seconds_total",
        f"wall seconds in staging-I/O {direction} calls",
    ).labels(call=call).inc(seconds)


def _build() -> str:
    # Compile to a pid-suffixed temp and atomically rename so concurrent
    # processes never dlopen a half-written .so.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _SO


def get_lib() -> ctypes.CDLL:
    """Load (building if needed) the native library; raises
    NativeUnavailable if no toolchain."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            raise NativeUnavailable("native build failed earlier this session")
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError) as e:
            _build_failed = True
            raise NativeUnavailable(f"cannot build/load rs_native: {e}") from e

        u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
        lib.rs_gf_init.restype = ctypes.c_int
        lib.rs_gemm.argtypes = [u8p, u8p, u8p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_longlong, ctypes.c_int]
        lib.rs_gemm.restype = None
        lib.rs_invert.argtypes = [u8p, u8p, ctypes.c_int]
        lib.rs_invert.restype = ctypes.c_int
        lib.rs_stripe_read.argtypes = [
            ctypes.c_char_p, u8p, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
        ]
        lib.rs_stripe_read.restype = ctypes.c_longlong
        lib.rs_scatter_write.argtypes = [
            ctypes.POINTER(ctypes.c_int), u8p, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong,
        ]
        lib.rs_scatter_write.restype = ctypes.c_int
        lib.rs_gather_rows.argtypes = [
            ctypes.POINTER(ctypes.c_int), u8p, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong,
        ]
        lib.rs_gather_rows.restype = ctypes.c_int
        lib.rs_gf_init()
        _lib = lib
        return lib


def available() -> bool:
    try:
        get_lib()
        return True
    except NativeUnavailable:
        return False


def gemm(A: np.ndarray, B: np.ndarray, nthreads: int = 0) -> np.ndarray:
    """Native GF(256) GEMM; NumPy-oracle fallback when no toolchain."""
    A = np.ascontiguousarray(A, dtype=np.uint8)
    B = np.ascontiguousarray(B, dtype=np.uint8)
    p, k = A.shape
    k2, m = B.shape
    assert k == k2, (A.shape, B.shape)
    try:
        lib = get_lib()
    except NativeUnavailable:
        from ..ops.gf import get_field

        return get_field(8).matmul(A, B)
    C = np.empty((p, m), dtype=np.uint8)
    lib.rs_gemm(A, B, C, p, k, m, nthreads or os.cpu_count() or 1)
    return C


def invert(M: np.ndarray) -> np.ndarray:
    """Native Gauss-Jordan inverse; raises SingularMatrixError if singular."""
    from ..ops.inverse import SingularMatrixError, invert_matrix

    M = np.ascontiguousarray(M, dtype=np.uint8)
    k = M.shape[0]
    try:
        lib = get_lib()
    except NativeUnavailable:
        return invert_matrix(M)
    out = np.empty((k, k), dtype=np.uint8)
    if lib.rs_invert(M, out, k) != 0:
        raise SingularMatrixError("matrix not invertible (native)")
    return out


def stripe_read(
    path: str,
    chunk: int,
    k: int,
    off: int,
    cols: int,
    total_size: int,
    fallback_src: np.ndarray | None = None,
) -> np.ndarray:
    """(k, cols) stripe segment of a file via native pread.

    ``fallback_src``: an already-open memmap of ``path`` used when the
    native library is unavailable (avoids re-mapping the file per segment).
    """
    dst = np.empty((k, cols), dtype=np.uint8)
    t0 = time.perf_counter()
    try:
        lib = get_lib()
    except NativeUnavailable:
        src = (
            fallback_src
            if fallback_src is not None
            else np.memmap(path, dtype=np.uint8, mode="r")
        )
        dst[:] = 0

        def read_row(i: int) -> None:
            lo = i * chunk + off
            hi = min(lo + cols, (i + 1) * chunk, total_size)
            if lo < hi:
                dst[i, : hi - lo] = src[lo:hi]

        # Fan the per-chunk range copies across the shared reader pool
        # (RS_IO_READERS) — each row touches a distinct slice of dst and a
        # distinct source range, so the rows are independent.
        from ..parallel.io_executor import run_rows

        run_rows(k, read_row)
        _count_io("read", "stripe_read", dst.nbytes, time.perf_counter() - t0)
        return dst
    got = lib.rs_stripe_read(path.encode(), dst, chunk, k, off, cols, total_size)
    if got < 0:
        raise OSError(f"rs_stripe_read failed for {path!r} (I/O error or truncated file)")
    _count_io("read", "stripe_read", dst.nbytes, time.perf_counter() - t0)
    return dst


def scatter_write(files, arr: np.ndarray, off: int) -> None:
    """Write each row of (p, cols) ``arr`` to the matching open binary file
    at byte offset ``off`` (native pwrite; Python seek/write fallback)."""
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    p, cols = arr.shape
    assert len(files) == p
    t0 = time.perf_counter()
    try:
        lib = get_lib()
    except NativeUnavailable:
        for fp, row in zip(files, arr):
            fp.seek(off)
            fp.write(row.tobytes())
        _count_io(
            "write", "scatter_write", arr.nbytes, time.perf_counter() - t0
        )
        return
    for fp in files:
        fp.flush()  # nothing buffered may straddle the raw pwrite below
    fds = (ctypes.c_int * p)(*[fp.fileno() for fp in files])
    if lib.rs_scatter_write(fds, arr, p, cols, off) != 0:
        raise OSError("rs_scatter_write failed (short write)")
    _count_io("write", "scatter_write", arr.nbytes, time.perf_counter() - t0)


def gather_rows(files, off: int, cols: int, fallback_maps=None) -> np.ndarray:
    """(k, cols) segment at byte offset ``off`` of k open chunk files —
    the decode-side staging twin of :func:`stripe_read` (native pread per
    row; memmap slice-copy fallback).

    ``files``: open binary file objects (one per surviving chunk).
    ``fallback_maps``: memmaps used when the native library is
    unavailable.  Callers invoking this in a per-segment loop should pass
    them (built once per file set) — omitting them re-mmaps every file on
    every fallback call and requires ``f.name`` to be a real path.
    """
    k = len(files)
    dst = np.empty((k, cols), dtype=np.uint8)
    t0 = time.perf_counter()
    try:
        lib = get_lib()
    except NativeUnavailable:
        maps = fallback_maps
        if maps is None:
            maps = [np.memmap(f.name, dtype=np.uint8, mode="r") for f in files]

        def read_row(i: int) -> None:
            dst[i] = maps[i][off : off + cols]

        # Distinct memmaps and distinct dst rows: fan across the shared
        # reader pool (RS_IO_READERS), mirroring rs_native.cpp's run_rows.
        from ..parallel.io_executor import run_rows

        run_rows(k, read_row)
        _count_io("read", "gather_rows", dst.nbytes, time.perf_counter() - t0)
        return dst
    fds = (ctypes.c_int * k)(*[f.fileno() for f in files])
    if lib.rs_gather_rows(fds, dst, k, off, cols) != 0:
        raise OSError("rs_gather_rows failed (short read)")
    _count_io("read", "gather_rows", dst.nbytes, time.perf_counter() - t0)
    return dst
