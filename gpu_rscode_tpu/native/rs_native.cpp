// rs_native — host-side native runtime: CPU GF(2^8) codec + striped file IO.
//
// Role parity with the reference's C host components, re-designed (not
// translated): the CPU oracle encoder/decoder (cpu-rs.c — full single-thread
// codec used as correctness baseline), the host Gauss-Jordan inverter
// (cpu-decode.c:251-298, the production decode-matrix path), and the
// pinned-buffer staging copies (encode.cu:389-398) whose TPU-era analog is
// fast striped pread/pwrite between the filesystem and NumPy buffers.
//
// Differences by design:
//  * the GEMM hot loop is PSHUFB nibble-table SIMD when the build target
//    has AVX2 (split-nibble linearity — the vectorised form of the
//    reference's cpu-rs-double.c strategy; ~6x the scalar path), with
//    parity rows grouped 4-wide so the data streams from DRAM once per
//    group; scalar fallback uses the full 64 KiB product table (the
//    fastest scalar strategy in the reference's own cpu-rs-* study).
//    All tables are built at init from the primitive polynomial 0x11D —
//    generated here, not copied from anywhere;
//  * GEMM is cache-blocked over columns and fans out across std::thread
//    workers (host-core analog of the reference's pthread-per-GPU split);
//  * Gauss-Jordan uses row pivoting (correct under zero pivots; the
//    reference's column-swap variant corrupts its accumulator there);
//  * everything is exposed extern "C" for ctypes.

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr int kPoly = 0x11D;
uint8_t g_mul[256][256];
uint8_t g_inv[256];
bool g_ready = false;

uint8_t slow_mul(uint32_t a, uint32_t b) {
  uint32_t r = 0;
  while (b) {
    if (b & 1) r ^= a;
    b >>= 1;
    a <<= 1;
    if (a & 0x100) a ^= kPoly;
  }
  return static_cast<uint8_t>(r);
}

// Row-parallel IO fan-out (the 2026-07-30 tmpfs phase split pinned these
// single-core staging copies — not compute, not disk — as the end-to-end
// stream bound; stripe rows are independent fds/offsets, so they thread
// the same way the GEMM's column ranges do).  Threading pays only when
// the per-call volume dwarfs thread spawn (~50 us each); below 1 MiB the
// serial loop wins.  RS_NATIVE_IO_THREADS caps the pool (0/1 = serial).
int io_threads(int rows, long long total_bytes) {
  if (rows < 2 || total_bytes < (1 << 20)) return 1;
  int cap = 8;  // page-cache/tmpfs memcpy saturates well before all cores
  if (const char* env = std::getenv("RS_NATIVE_IO_THREADS")) {
    cap = std::atoi(env);
    if (cap < 1) cap = 1;
  }
  unsigned hw = std::thread::hardware_concurrency();
  int nt = hw ? static_cast<int>(hw) : 1;
  if (nt > cap) nt = cap;
  return nt < rows ? nt : rows;
}

// Run fn(row) over rows 0..k-1 on nt threads (round-robin assignment —
// rows are similar-sized, so striding balances without a work queue).
// fn returns false on failure; any failure makes the whole call fail,
// and workers finish their current row then stop.
template <typename Fn>
bool run_rows(int k, int nt, Fn fn) {
  if (nt <= 1) {
    for (int i = 0; i < k; ++i)
      if (!fn(i)) return false;
    return true;
  }
  std::atomic<bool> ok{true};
  std::vector<std::thread> workers;
  workers.reserve(nt - 1);
  for (int w = 1; w < nt; ++w) {
    workers.emplace_back([&, w]() {
      for (int i = w; i < k && ok.load(std::memory_order_relaxed); i += nt)
        if (!fn(i)) ok.store(false, std::memory_order_relaxed);
    });
  }
  // Stride 0 runs on the calling thread — one fewer spawn per staging
  // call, which matters near the 1 MiB threshold where spawn cost and
  // copy time are comparable.
  for (int i = 0; i < k && ok.load(std::memory_order_relaxed); i += nt)
    if (!fn(i)) ok.store(false, std::memory_order_relaxed);
  for (auto& th : workers) th.join();
  return ok.load();
}

void gemm_range_scalar(const uint8_t* A, const uint8_t* B, uint8_t* C, int p,
                       int k, long long m, long long lo, long long hi) {
  constexpr long long kBlock = 4096;  // keep working set in L1/L2
  for (long long c0 = lo; c0 < hi; c0 += kBlock) {
    const long long c1 = c0 + kBlock < hi ? c0 + kBlock : hi;
    for (int i = 0; i < p; ++i) {
      uint8_t* crow = C + static_cast<long long>(i) * m;
      std::memset(crow + c0, 0, static_cast<size_t>(c1 - c0));
      for (int t = 0; t < k; ++t) {
        const uint8_t a = A[i * k + t];
        if (a == 0) continue;
        const uint8_t* mrow = g_mul[a];
        const uint8_t* brow = B + static_cast<long long>(t) * m;
        if (a == 1) {
          for (long long c = c0; c < c1; ++c) crow[c] ^= brow[c];
        } else {
          for (long long c = c0; c < c1; ++c) crow[c] ^= mrow[brow[c]];
        }
      }
    }
  }
}

#if defined(__AVX2__)
// SIMD GF(2^8) constant-multiply via two 16-entry nibble tables + PSHUFB
// (the split-nibble linearity a*x = a*(hi<<4) ^ a*lo — the same
// decomposition the reference's cpu-rs-double.c strategy and its GF(16)
// nibble tables exploit, here vectorised 32 bytes per shuffle pair).
// ~10x the 64 KiB-table scalar loop per core: the scalar path is one
// dependent L1 gather per byte; this is 2 shuffles + 3 xors per 32 bytes.
// Parity rows are processed in groups of 4 sharing each loaded data block,
// so B streams from DRAM once per group instead of once per parity row,
// and the column loop runs 2x32-byte blocks per iteration so each pair of
// nibble tables is loaded from L1 once per 64 output bytes (the table
// loads, not the shuffles, bound the 1-block form).
void gemm_range_avx2(const uint8_t* A, const uint8_t* B, uint8_t* C, int p,
                     int k, long long m, long long lo, long long hi) {
  const __m256i nib = _mm256_set1_epi8(0x0f);
  constexpr int kGroup = 4;
  // (group-row, t) nibble tables; a == 0 rows keep all-zero tables (a
  // shuffle of zeros XORs as a no-op) so the inner loop stays branch-free.
  std::vector<__m256i> tlo(static_cast<size_t>(kGroup) * k);
  std::vector<__m256i> thi(static_cast<size_t>(kGroup) * k);
  for (int i0 = 0; i0 < p; i0 += kGroup) {
    const int pg = p - i0 < kGroup ? p - i0 : kGroup;
    for (int g = 0; g < pg; ++g) {
      for (int t = 0; t < k; ++t) {
        const uint8_t a = A[(i0 + g) * k + t];
        alignas(16) uint8_t lo_t[16], hi_t[16];
        for (int x = 0; x < 16; ++x) {
          lo_t[x] = g_mul[a][x];
          hi_t[x] = g_mul[a][x << 4];
        }
        tlo[g * k + t] = _mm256_broadcastsi128_si256(
            _mm_load_si128(reinterpret_cast<const __m128i*>(lo_t)));
        thi[g * k + t] = _mm256_broadcastsi128_si256(
            _mm_load_si128(reinterpret_cast<const __m128i*>(hi_t)));
      }
    }
    long long c = lo;
    for (; c + 64 <= hi; c += 64) {
      __m256i acc[kGroup], acc2[kGroup];
      for (int g = 0; g < kGroup; ++g) {
        acc[g] = _mm256_setzero_si256();
        acc2[g] = _mm256_setzero_si256();
      }
      for (int t = 0; t < k; ++t) {
        const uint8_t* brow = B + static_cast<long long>(t) * m;
        const __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(brow + c));
        const __m256i w2 = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(brow + c + 32));
        const __m256i vl = _mm256_and_si256(v, nib);
        const __m256i vh = _mm256_and_si256(_mm256_srli_epi16(v, 4), nib);
        const __m256i wl = _mm256_and_si256(w2, nib);
        const __m256i wh = _mm256_and_si256(_mm256_srli_epi16(w2, 4), nib);
        for (int g = 0; g < pg; ++g) {
          const __m256i lo_tab = tlo[g * k + t];
          const __m256i hi_tab = thi[g * k + t];
          acc[g] = _mm256_xor_si256(
              acc[g],
              _mm256_xor_si256(_mm256_shuffle_epi8(lo_tab, vl),
                               _mm256_shuffle_epi8(hi_tab, vh)));
          acc2[g] = _mm256_xor_si256(
              acc2[g],
              _mm256_xor_si256(_mm256_shuffle_epi8(lo_tab, wl),
                               _mm256_shuffle_epi8(hi_tab, wh)));
        }
      }
      for (int g = 0; g < pg; ++g) {
        uint8_t* crow = C + static_cast<long long>(i0 + g) * m;
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(crow + c), acc[g]);
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(crow + c + 32),
                            acc2[g]);
      }
    }
    for (; c + 32 <= hi; c += 32) {
      __m256i acc[kGroup] = {_mm256_setzero_si256(), _mm256_setzero_si256(),
                             _mm256_setzero_si256(), _mm256_setzero_si256()};
      for (int t = 0; t < k; ++t) {
        const uint8_t* brow = B + static_cast<long long>(t) * m;
        const __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(brow + c));
        const __m256i vl = _mm256_and_si256(v, nib);
        const __m256i vh = _mm256_and_si256(_mm256_srli_epi16(v, 4), nib);
        for (int g = 0; g < pg; ++g) {
          acc[g] = _mm256_xor_si256(
              acc[g],
              _mm256_xor_si256(_mm256_shuffle_epi8(tlo[g * k + t], vl),
                               _mm256_shuffle_epi8(thi[g * k + t], vh)));
        }
      }
      for (int g = 0; g < pg; ++g) {
        _mm256_storeu_si256(
            reinterpret_cast<__m256i*>(C + static_cast<long long>(i0 + g) * m
                                       + c),
            acc[g]);
      }
    }
    if (c < hi) {  // ragged tail: scalar
      for (int g = 0; g < pg; ++g) {
        uint8_t* crow = C + static_cast<long long>(i0 + g) * m;
        std::memset(crow + c, 0, static_cast<size_t>(hi - c));
        for (int t = 0; t < k; ++t) {
          const uint8_t a = A[(i0 + g) * k + t];
          if (a == 0) continue;
          const uint8_t* mrow = g_mul[a];
          const uint8_t* brow = B + static_cast<long long>(t) * m;
          for (long long cc = c; cc < hi; ++cc) crow[cc] ^= mrow[brow[cc]];
        }
      }
    }
  }
}
#endif  // __AVX2__

void gemm_range(const uint8_t* A, const uint8_t* B, uint8_t* C, int p, int k,
                long long m, long long lo, long long hi) {
#if defined(__AVX2__)
  gemm_range_avx2(A, B, C, p, k, m, lo, hi);
#else
  gemm_range_scalar(A, B, C, p, k, m, lo, hi);
#endif
}

}  // namespace

extern "C" {

int rs_gf_init(void) {
  if (g_ready) return 0;
  for (int a = 0; a < 256; ++a)
    for (int b = 0; b <= a; ++b) g_mul[a][b] = g_mul[b][a] = slow_mul(a, b);
  for (int a = 1; a < 256; ++a)
    for (int b = 1; b < 256; ++b)
      if (g_mul[a][b] == 1) {
        g_inv[a] = static_cast<uint8_t>(b);
        break;
      }
  g_ready = true;
  return 0;
}

// C[p x m] = A[p x k] . B[k x m] over GF(256), XOR-accumulated.
void rs_gemm(const uint8_t* A, const uint8_t* B, uint8_t* C, int p, int k,
             long long m, int nthreads) {
  rs_gf_init();
  if (nthreads <= 1 || m < (1 << 16)) {
    gemm_range(A, B, C, p, k, m, 0, m);
    return;
  }
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 1;
  const int nt =
      nthreads < static_cast<int>(hw) ? nthreads : static_cast<int>(hw);
  std::vector<std::thread> workers;
  const long long step = (m + nt - 1) / nt;
  for (int w = 0; w < nt; ++w) {
    const long long lo = w * step;
    const long long hi = lo + step < m ? lo + step : m;
    if (lo >= hi) break;
    workers.emplace_back(gemm_range, A, B, C, p, k, m, lo, hi);
  }
  for (auto& th : workers) th.join();
}

// Gauss-Jordan inverse with row pivoting.  0 on success, -1 if singular.
int rs_invert(const uint8_t* M, uint8_t* out, int k) {
  rs_gf_init();
  std::vector<uint8_t> a(M, M + static_cast<size_t>(k) * k);
  std::vector<uint8_t> r(static_cast<size_t>(k) * k, 0);
  for (int i = 0; i < k; ++i) r[i * k + i] = 1;
  for (int col = 0; col < k; ++col) {
    int piv = -1;
    for (int row = col; row < k; ++row)
      if (a[row * k + col]) {
        piv = row;
        break;
      }
    if (piv < 0) return -1;
    if (piv != col) {
      for (int j = 0; j < k; ++j) {
        std::swap(a[col * k + j], a[piv * k + j]);
        std::swap(r[col * k + j], r[piv * k + j]);
      }
    }
    const uint8_t inv_p = g_inv[a[col * k + col]];
    for (int j = 0; j < k; ++j) {
      a[col * k + j] = g_mul[a[col * k + j]][inv_p];
      r[col * k + j] = g_mul[r[col * k + j]][inv_p];
    }
    for (int row = 0; row < k; ++row) {
      if (row == col) continue;
      const uint8_t f = a[row * k + col];
      if (!f) continue;
      const uint8_t* fr = g_mul[f];
      for (int j = 0; j < k; ++j) {
        a[row * k + j] ^= fr[a[col * k + j]];
        r[row * k + j] ^= fr[r[col * k + j]];
      }
    }
  }
  std::memcpy(out, r.data(), static_cast<size_t>(k) * k);
  return 0;
}

// Gather the k stripe rows of a file segment into dst[k x cols] with pread
// (one syscall per row), zero-padding past EOF / chunk end.  Returns bytes
// read, or -1 on open failure.  This is the host staging hot path for
// encode: it replaces k Python slice-copies per segment.
long long rs_stripe_read(const char* path, uint8_t* dst, long long chunk,
                         int k, long long off, long long cols,
                         long long total_size) {
  const int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  // pread carries its own offset, so concurrent row reads share one fd.
  std::atomic<long long> got_total{0};
  const bool ok = run_rows(k, io_threads(k, static_cast<long long>(k) * cols),
                           [&](int i) {
    uint8_t* row = dst + static_cast<long long>(i) * cols;
    const long long lo = static_cast<long long>(i) * chunk + off;
    long long hi = lo + cols;
    const long long chunk_end = static_cast<long long>(i + 1) * chunk;
    if (hi > chunk_end) hi = chunk_end;
    if (hi > total_size) hi = total_size;
    long long want = hi - lo;
    if (want < 0) want = 0;
    long long done = 0;
    while (done < want) {
      const ssize_t n = pread(fd, row + done, static_cast<size_t>(want - done),
                              lo + done);
      if (n <= 0) return false;  // error or unexpected EOF: fail loudly,
      done += n;                 // never zero-fill silently (zeroed data
    }                            // would encode corrupt parity)
    got_total.fetch_add(done, std::memory_order_relaxed);
    if (done < cols) std::memset(row + done, 0, static_cast<size_t>(cols - done));
    return true;
  });
  close(fd);
  return ok ? got_total.load() : -1;
}

// Gather one cols-byte segment at offset off from each of k open chunk
// files into dst[k x cols] (pread).  The decode-side twin of
// rs_stripe_read: chunk files are exactly chunk-sized, so a short read is
// an error (never zero-filled — decoding zeroed data would fabricate
// output).  Returns 0, or -1 on any read failure.
int rs_gather_rows(const int* fds, uint8_t* dst, int k, long long off,
                   long long cols) {
  const bool ok = run_rows(k, io_threads(k, static_cast<long long>(k) * cols),
                           [&](int i) {
    uint8_t* row = dst + static_cast<long long>(i) * cols;
    long long done = 0;
    while (done < cols) {
      const ssize_t n = pread(fds[i], row + done,
                              static_cast<size_t>(cols - done), off + done);
      if (n <= 0) return false;
      done += n;
    }
    return true;
  });
  return ok ? 0 : -1;
}

// Scatter p parity row segments to p files at offset off (pwrite).
// fds: open file descriptors.  Returns 0, or -1 on short write.
int rs_scatter_write(const int* fds, const uint8_t* src, int p,
                     long long cols, long long off) {
  const bool ok = run_rows(p, io_threads(p, static_cast<long long>(p) * cols),
                           [&](int i) {
    const uint8_t* row = src + static_cast<long long>(i) * cols;
    long long done = 0;
    while (done < cols) {
      const ssize_t n = pwrite(fds[i], row + done,
                               static_cast<size_t>(cols - done), off + done);
      if (n <= 0) return false;
      done += n;
    }
    return true;
  });
  return ok ? 0 : -1;
}

}  // extern "C"
