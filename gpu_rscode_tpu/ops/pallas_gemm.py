"""Fused Pallas TPU kernel for the GF(2^w) GEMM — the production hot loop.

Role parity: the reference's tiled shared-memory GF-GEMM kernel
(``matrix_mul``, matrix.cu:232-407) — the single kernel both encode and
decode dispatch.  TPU-first design, not a translation:

The XLA bitplane path (:mod:`.gemm`) materialises the (k*w, m) bit-plane
expansion of the data in HBM — 8x (int8) / 16x (bf16) the input bytes of
HBM traffic.  This kernel fuses the whole chain per column tile in VMEM:

    HBM uint8 (k, TILE) --DMA--> VMEM
      -> bit-expand on the VPU          (k, TILE)   -> (k*w, TILE)
      -> one MXU matmul with the (p*w, k*w) bit operator
      -> parity + refold on the VPU     (p*w, TILE) -> (p, TILE)
    VMEM uint8 (p, TILE) --DMA--> HBM

so HBM sees exactly 1x the input + 1x the output bytes — the kernel is
bandwidth-optimal.  The coefficient operator (a few KB) stays resident in
VMEM across the whole grid (the analog of the reference staging its GF
tables into __shared__, matrix.cu:36-39, except here it's the *matrix* that
is staged and the tables have been compiled away entirely).

Grid: 1-D over column tiles, the embarrassingly-parallel axis (the
reference's grid-stride column sweep, matrix.cu:265-322).  Out-of-range
columns in the last tile compute garbage on garbage and are dropped by the
masked output write Pallas performs automatically.

Three bit-expansion formulations (``expand``), all bit-verified in
interpret mode.  The kernel is compute-bound in every measured era: the
2026-07-30 captures had it at ~99 % of a 64.9 GB/s compute-only ceiling,
and after the round-4/5 algebraic reductions the post-flip floors
(kernel_floors_postflip_tpu_20260801T*) put it at ~97 % of a ~110 GB/s
ceiling, with the DMA floor far above (>= 170; readings scatter 125-333
across tunnel sessions, dma_floor_recheck_*):

* ``"shift"`` — plane s = (b >> s) & 1 in int32 lanes (proven default).
* ``"sign"``  — plane s = (int_w)(b << (w-1-s)) >> (w-1), i.e. {0, -1},
  staying in w-bit lanes (4x VPU packing for w=8).  -1 === 1 (mod 2), so
  the parity of the integer accumulator — all the refold reads — is
  unchanged.
* ``"nibble"`` (w=8) — one-hot of the high/low nibbles (32 rows per data
  byte) against the (p*w, k*32) one-hot-nibble operator (gf.nibble_mats):
  compare-based VPU expansion, 4x the MXU contraction depth.  The MXU
  analog of the reference's fastest kernel — the GF(16) nibble-table
  branch (design.tex:485 9.12 ms vs 160.5 ms; gf16.h:1-22).

Hardware verdict (2026-07-31, real v5e, committed captures
bench_captures/expand_r4b_* / expand_r4c_*): the production default is
``expand="shift_raw"`` plus, at w=8, ``refold="dot"`` — the mask-free
expansion beat ``shift`` at every probed shape, and moving the parity
refold onto the MXU beat the VPU shift-sum at every probed w=8 shape.
Headline (k=10, p=4): 105.5 GB/s end-to-end encode / 105.6 decode with
3.18 ms 4-erasure recovery (bench_tpu_20260801T000810Z — was 64.7/64.7
under shift+sum); raw GEMM 109.8 @ k=10, 152.5 @ k=32, 159.8 @ k=64,
167.4 @ k=128 (post-flip k-sweep).  w=16 measured 101.9 under
shift_raw (was 90.3 under shift) with the "sum" refold.  The r4c
w16+dot timeout was the TUNNEL, not a hang (resolved 2026-08-01: both
small-shape re-probes returned rc=0, w16_small_*_tpu_20260801T*) —
but the r5c crossover sweep showed w16+dot is BIMODAL at fixed shape
(mb=128: 84.8 / 82.3 / 147.6 across three runs; mb=64: 142.3; mb=320:
147.0; mb=32: 8.2) where sum is stable (101.7-102.6 at every probed
size, w16_cross_*_tpu_20260801T*).  The r5e tile map pinned the cause
as NOT tile-dependent: at mb=128, tile 8192 read 136.9 then 52.4 and
tile 16384 read 144.4 / 132.0 in-session against 84.8 / 82.3 / 147.6
for the same shape in the prior session (w16_bimodal_t*_tpu_20260801T*)
— every slow reading was a best-of-trials WITHIN one process, so the
mode is fixed at (re)compile time, i.e. remote-toolchain compile
nondeterminism, not a per-dispatch or per-tile effect.  A default that
regresses below sum on a coin-flip compile is not shippable, so w=16
keeps "sum"; RS_PALLAS_REFOLD=dot opts into the 132-147 GB/s fast mode
for callers who can tolerate the variance, and RS_PALLAS_REFOLD=autotune
times both variants once per compiled shape class and ships whichever
mode THIS process's compile produced (fast-dot when the coin lands
right, sum otherwise — the operational answer to nondeterminism a
static default cannot give).  ``"sign"`` and ``"nibble"``
do NOT
lower on the current Mosaic toolchain (sign: ``arith.subi`` on int8
vectors fails to legalize; nibble: 8-bit iota unsupported; reworked
int32-iota formulations crash the compile helper) — see
bench_captures/tile_pick_tpu_*.jsonl and expand_probe_tpu_*.jsonl.  They
remain available for interpret mode (bit-verified in CI) and future
toolchains.  Probed and rejected on measurement: a packed uint8
mask-compare variant (40.7 vs 64.4 shift), and ``pack2`` — correct only
under ``Precision.HIGHEST`` (packed lanes reach 257, which the default
bf16 MXU pass rounds to 256) whose multi-pass cost sinks it to 2.4 GB/s
(expand_r4b_decode capture).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs import metrics as _obs_metrics
from .gf import get_field

DEFAULT_TILE = 2048      # interpret / CPU-mesh default
# Measured best on real v5e, production path, 320 MB per timed call
# (bench_captures/tile_pick_tpu_20260730T050344Z.jsonl: 64.33 @ 16384, 64.63 @
# 32768 — a tie within tunnel jitter; 47.11 @ 8192, 56.91 @ 65536).
TPU_TILE = 16384
# Depth-split history: the 2026-07-31 PRE-flip k-sweep
# (k_sweep_tpu_20260731T010808Z.jsonl) had bf16@32768 winning at
# contraction depth k*w >= 256, so rounds 4-5 shipped a deep-config
# split.  The POST-flip re-sweep under the production shift_raw+dot
# kernel (k_sweep_postflip_tpu_20260801T002730Z.jsonl) RETIRED it:
# int8 wins at every k (k=32: 152.5 vs bf16's 119.0; k=64: 159.8 vs
# 136.7; k=128: 167.4 vs 140.2), tile 16384 is within ~5 % of the best
# tile at every depth, and int8@32768 at depth 1024 fails to compile
# (remote helper HTTP 500) — so int8@TPU_TILE is the one hardware
# default at w=8.  Unlike the reference, which degrades for k >= 32
# (design.tex:462-466), throughput GROWS with k: the p*w-row output
# refold amortizes over more input rows.


def _expand_shift(b, w, k, tile):
    b = b.astype(jnp.int32)
    in_shifts = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1)
    return ((b[:, None, :] >> in_shifts) & 1).reshape(k * w, tile)


def _expand_shift_raw(b, w, k, tile):
    # ``shift`` without the ``& 1`` — the round-4 algebraic shortcut.  The
    # matmul's accumulator is only ever read modulo 2 (XOR == parity), and
    # (b >> s) === bit_s (mod 2): every higher bit of the unmasked plane
    # contributes an even term (2^(t-s) for t > s), invisible to parity.
    # The int8 MXU cast wraps plane values mod 256 (even — parity-safe, and
    # two's-complement v-256 === v mod 2), products are exact in int32
    # (|sum| <= k*w*128 << 2^31), and the f32 path is exact below 2^24.
    # Net effect: w fewer VPU mask ops per input byte on the kernel's
    # bottleneck (the r3 floors capture pinned the kernel compute-bound on
    # expansion at ~65 of 286 GB/s DMA floor).
    b = b.astype(jnp.int32)
    in_shifts = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1)
    return (b[:, None, :] >> in_shifts).reshape(k * w, tile)


def _expand_sign(b, w, k, tile):
    sdt = jnp.int8 if w == 8 else jnp.int16
    bts = jax.lax.bitcast_convert_type(b, sdt)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1).astype(sdt)
    lsh = sdt(w - 1) - shifts
    return ((bts[:, None, :] << lsh) >> sdt(w - 1)).reshape(k * w, tile)


def _expand_nibble(b, w, k, tile):
    # One-hot of the high and low nibbles, int8 lanes throughout: 32 rows
    # per data byte, selecting columns of the (p*w, k*32) nibble operator
    # (gf.nibble_mats).  Trades 4x MXU work (affordable: the kernel runs at
    # a small fraction of int8 peak) for compare-based expansion on the VPU.
    v = jax.lax.broadcasted_iota(jnp.uint8, (1, 16, 1), 1)
    hi = (b >> 4)[:, None, :]
    lo = (b & 0xF)[:, None, :]
    planes = jnp.concatenate([hi == v, lo == v], axis=1)  # (k, 32, tile)
    return planes.reshape(k * 32, tile)


# ---- round-4 probe formulations (VERDICT r3 item 2 / 8) -------------------
# The 2026-07 Mosaic verdicts pinned the sign/nibble failures to 8-bit iota
# and int8 arith.subi; every formulation below avoids BOTH (shift amounts
# are numpy CONSTANTS, no iota op; no int8 subtraction).  All bit-verified
# in interpret mode; hardware verdicts land in
# bench_captures/expand_probe_* via tools/expand_probe.py.
# Hardware verdicts 2026-07-31 (expand_probe_tpu_20260731T010620Z.jsonl):
# packed32 hits an unimplemented Mosaic bitcast; sign16/shift_u8/
# nibble_const crash the remote compile helper — no narrow-lane VPU
# formulation lowers on this toolchain.  The follow-ups that stay in the
# lowerable int32-lane family are ``shift_raw`` (above) and ``pack2``
# (``_kernel_pack2``): two bytes per int32 lane via an XLA-level uint16
# bitcast OUTSIDE the kernel, f32 MXU contraction with 8-bit parity
# fields (exact below depth 256), and a packed refold whose lane value is
# already the two output bytes — half the VPU work per byte at both ends.


def _expand_packed32(b, w, k, tile):
    # VERDICT r3 candidate (b): int32 lane packing.  Bitcast 4 data bytes
    # into one int32 lane, extract a bit-plane of all 4 with ONE shift-mask
    # pass (mask 0x01010101), bitcast back to int8 — 4x fewer VPU lane-ops
    # per plane than per-byte int32 shifts; the MXU sees ordinary int8
    # planes.  w=8 only (byte-granular packing).  Shift amounts are
    # python-unrolled scalar immediates (Pallas kernels may not capture
    # array constants, and vector shift-amounts would need the iota Mosaic
    # refuses in narrow types).
    p32 = jax.lax.bitcast_convert_type(
        b.reshape(k, tile // 4, 4), jnp.int32
    )  # (k, tile/4)
    planes32 = jnp.stack(
        [(p32 >> jnp.int32(s)) & jnp.int32(0x01010101) for s in range(w)],
        axis=1,
    )  # (k, w, tile/4)
    planes8 = jax.lax.bitcast_convert_type(planes32, jnp.int8)
    return planes8.reshape(k * w, tile)  # (k, w, tile/4, 4) -> rows of bits


def _expand_sign16(b, w, k, tile):
    # VERDICT r3 candidate (d): sign-replication in int16-only lanes (2x
    # VPU packing vs int32) — bit s to the sign position, arithmetic shift
    # back to {0, -1}; -1 === 1 (mod 2) so the parity refold is unchanged.
    # Scalar-immediate shifts, unrolled: no int8 ops, no iota.
    bts = b.astype(jnp.int16)
    planes = jnp.stack(
        [(bts << jnp.int16(15 - s)) >> jnp.int16(15) for s in range(w)],
        axis=1,
    )
    return planes.reshape(k * w, tile)


def _expand_shift_u8(b, w, k, tile):
    # Python-unrolled CONSTANT shifts in uint8 lanes: no iota, no subi,
    # 4x lane packing vs int32, w compiled-in copies of one shift-mask op.
    planes = [(b >> np.uint8(s)) & np.uint8(1) for s in range(w)]
    return jnp.stack(planes, axis=1).reshape(k * w, tile)


def _expand_nibble32(b, w, k, tile):
    # The nibble one-hot (the reference's fastest-kernel idea, gf16.h:1-22)
    # carried entirely in int32 lanes — the only lane width the Mosaic
    # toolchain has lowered for this kernel (r3/r4 verdicts: every 8/16-bit
    # formulation fails legalization or crashes the compile helper).
    # 32 compares per input byte on the VPU buy a 4x-deeper MXU
    # contraction against the (p*w, k*32) one-hot operator; the k-sweep
    # capture shows deep contractions RAISE throughput, so the trade is
    # plausible where compares are cheaper than shifts.  Compare constants
    # are python-unrolled scalar immediates (no iota).
    v = b.astype(jnp.int32)
    hi = v[:, None, :] >> np.int32(4)
    lo = v[:, None, :] & np.int32(15)
    planes = jnp.concatenate(
        [hi == np.int32(c) for c in range(16)]
        + [lo == np.int32(c) for c in range(16)],
        axis=1,
    )  # (k, 32, tile) bool
    return planes.reshape(k * 32, tile)


def _expand_nibble_const(b, w, k, tile):
    # The nibble one-hot (reference's fastest-kernel idea, gf16.h:1-22)
    # with the 16 compare values python-unrolled as scalar immediates
    # instead of the 8-bit iota Mosaic refuses.
    hi = b >> np.uint8(4)
    lo = b & np.uint8(0xF)
    planes = jnp.stack(
        [hi == np.uint8(v) for v in range(16)]
        + [lo == np.uint8(v) for v in range(16)],
        axis=1,
    )  # (k, 32, tile)
    return planes.reshape(k * 32, tile)


def _kernel_pack2(a_ref, b_ref, o_ref, *, w: int, k: int, p: int):
    # Two data bytes per int32 lane (VERDICT r3 candidate (b), realized
    # without the in-kernel bitcast Mosaic refuses: the uint16 view is an
    # XLA-level bitcast OUTSIDE the kernel).  Each plane row holds bit s of
    # BOTH bytes at int32 bit positions 0 and 8 (mask 0x0101); the f32
    # matmul accumulates the two parity fields independently — field sums
    # are bounded by the contraction depth k*w < 256, so no cross-field
    # carry, and every value is far below 2^24 (f32-exact on the MXU).
    # The packed shift-sum refold then produces, per lane, exactly
    # lo_out + 256*hi_out — i.e. the uint16 of the two output bytes in the
    # same byte order the input bitcast used (the algebra is symmetric
    # under endianness, so the pair of bitcasts cancels either way).
    # Net: HALF the VPU lane-ops per byte in BOTH expansion and refold.
    tile2 = b_ref.shape[-1]
    v = b_ref[:].astype(jnp.int32)
    planes = jnp.stack(
        [(v >> np.int32(s)) & np.int32(0x0101) for s in range(w)], axis=1
    ).reshape(k * w, tile2)
    # Precision.HIGHEST is load-bearing on hardware: packed lanes take the
    # value 257 (both fields set), which needs 9 significand bits — the
    # MXU's default bf16 pass rounds it to 256, corrupting the low field
    # (observed OracleMismatch, expand_r4b_k10_tpu_20260731T031556Z.jsonl).
    # HIGHEST runs the multi-pass bf16 decomposition, exact for f32 inputs.
    acc = jnp.dot(
        a_ref[:], planes.astype(jnp.float32),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    bits = acc.astype(jnp.int32) & 0x0101
    out_shifts = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1)
    o_ref[:] = (
        jnp.sum(bits.reshape(p, w, tile2) << out_shifts, axis=1)
        .astype(jnp.uint16)
    )


def _kernel(
    a_ref, b_ref, o_ref, *, w: int, k: int, p: int, acc_dtype, expand, fold
):
    _kernel_body(a_ref, b_ref, None, o_ref, w=w, k=k, p=p,
                 acc_dtype=acc_dtype, expand=expand, fold=fold)


def _kernel_dotfold(
    a_ref, b_ref, f_ref, o_ref, *, w: int, k: int, p: int, acc_dtype, expand,
    fold,
):
    _kernel_body(a_ref, b_ref, f_ref, o_ref, w=w, k=k, p=p,
                 acc_dtype=acc_dtype, expand=expand, fold=fold)


def _kernel_body(
    a_ref, b_ref, f_ref, o_ref, *, w, k, p, acc_dtype, expand, fold
):
    tile = b_ref.shape[-1]
    expander = {
        "sign": _expand_sign,
        "nibble": _expand_nibble,
        "shift": _expand_shift,
        "shift_raw": _expand_shift_raw,
        "packed32": _expand_packed32,
        "sign16": _expand_sign16,
        "shift_u8": _expand_shift_u8,
        "nibble_const": _expand_nibble_const,
        "nibble32": _expand_nibble32,
    }[expand]
    planes = expander(b_ref[:], w, k, tile)
    acc = jnp.dot(
        a_ref[:].astype(acc_dtype),
        planes.astype(acc_dtype),
        preferred_element_type=jnp.float32 if acc_dtype != jnp.int8 else jnp.int32,
    )
    if not fold:
        # Pre-parity mode: emit the raw (p*w, tile) integer bit-plane
        # accumulators so a cross-device psum can extend the XOR-as-sum
        # before parity is taken (stripe-sharded GEMM, parallel/sharded.py).
        o_ref[:] = acc.astype(jnp.int32)
        return
    # Parity: XOR == sum mod 2.  Holds for the sign formulation too:
    # two's-complement (-n) & 1 == n & 1, and f32->int32 truncation is exact
    # for these small integers.
    bits = acc.astype(jnp.int32) & 1
    if f_ref is not None:
        # MXU refold: out = F . bits with F (p, p*w) the constant
        # bit-weight operator (2^s on the diagonal blocks, passed as an
        # operand — Pallas kernels may not capture array constants).  The
        # VPU's per-output shift + w-way sum becomes one tiny bf16 matmul;
        # exact in f32 (values <= 2^w - 1 < 2^24).
        # f32 -> int32 -> uint8/16: Mosaic refuses a direct f32 -> uint8
        # cast (expand_r4b_k10_dot_tpu_20260731T031850Z.log); the int32 hop
        # is the same cast chain the sum refold lowers with.
        o_ref[:] = jnp.dot(
            f_ref[:], bits.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32).astype(o_ref.dtype)
        return
    out_shifts = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1)
    o_ref[:] = (
        jnp.sum(bits.reshape(p, w, tile) << out_shifts, axis=1)
        .astype(o_ref.dtype)
    )


@functools.partial(jax.jit, static_argnames=("w", "tile", "interpret"))
def _pallas_matmul_pack2(A, B, w, tile, interpret):
    from .gemm import expand_bitmatrix_jnp

    p, k = A.shape
    _, m = B.shape
    a_op = expand_bitmatrix_jnp(A, w).astype(jnp.float32)
    pad = m % 2
    if pad:
        B = jnp.pad(B, ((0, 0), (0, 1)))
    m2 = (m + pad) // 2
    B16 = jax.lax.bitcast_convert_type(B.reshape(k, m2, 2), jnp.uint16)
    # Same alignment rule as _pallas_matmul: the halved tile must stay
    # lane-aligned (tile//2 of an odd-128-multiple tile is not).
    tile2 = min(tile // 2, ((m2 + 127) // 128) * 128)
    tile2 = ((tile2 + 127) // 128) * 128
    grid = (pl.cdiv(m2, tile2),)
    out16 = pl.pallas_call(
        functools.partial(_kernel_pack2, w=w, k=k, p=p),
        out_shape=jax.ShapeDtypeStruct((p, m2), jnp.uint16),
        grid=grid,
        in_specs=[
            pl.BlockSpec((p * w, k * w), lambda i: (0, 0)),
            pl.BlockSpec((k, tile2), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((p, tile2), lambda i: (0, i)),
        interpret=interpret,
    )(a_op, B16)
    out = jax.lax.bitcast_convert_type(out16, jnp.uint8).reshape(p, 2 * m2)
    return out[:, :m] if pad else out


@functools.partial(
    jax.jit,
    static_argnames=(
        "w", "tile", "acc_dtype", "interpret", "expand", "fold", "refold",
    ),
)
def _pallas_matmul(
    A, B, w, tile, acc_dtype, interpret, expand, fold=True, refold="sum"
):
    gf = get_field(w)
    p, k = A.shape
    _, m = B.shape
    # Expand the coefficient matrix to its GF(2) operator on the host side of
    # the graph (tiny; XLA folds it when A is a constant).  The bit-plane
    # expansions pair with the (p*w, k*w) bit operator; the nibble expansion
    # pairs with the (p*w, k*32) one-hot-nibble operator (the MXU analog of
    # the reference's GF(16) nibble-table strategy, gf16.h:1-22,
    # cpu-rs-double.c:52-55).
    from .gemm import expand_bitmatrix_jnp, expand_nibblematrix_jnp

    if expand in ("nibble", "nibble_const", "nibble32"):
        a_op = expand_nibblematrix_jnp(A, w)
        a_cols = k * 32
    else:
        a_op = expand_bitmatrix_jnp(A, w)
        a_cols = k * w
    a_bits = a_op.astype(jnp.int8 if acc_dtype == jnp.int8 else acc_dtype)
    out_dtype = jnp.uint8 if gf.dtype == np.uint8 else jnp.uint16
    # Clamp to m rounded up to the lane width, then round the tile itself
    # up to the lane width, so the block shape stays 128-aligned for ANY
    # tile origin (defaults, RS_PALLAS_TILE, explicit arguments, pack2's
    # halving); the last tile's overhang is masked by Pallas.  A
    # misaligned block would fail Mosaic lowering on hardware and
    # silently demote every dispatch to the bitplane path.
    tile = min(tile, ((m + 127) // 128) * 128)
    tile = ((tile + 127) // 128) * 128
    grid = (pl.cdiv(m, tile),)
    out_rows = p if fold else p * w
    in_specs = [
        pl.BlockSpec((p * w, a_cols), lambda i: (0, 0)),
        pl.BlockSpec((k, tile), lambda i: (0, i)),
    ]
    operands = [a_bits, B]
    if fold and refold == "dot":
        # (p, p*w) bit-weight fold operator: F[i, i*w + s] = 2^s.
        F = jnp.asarray(
            np.kron(np.eye(p), (1 << np.arange(w))[None, :]), jnp.bfloat16
        )
        kernel = functools.partial(
            _kernel_dotfold, w=w, k=k, p=p, acc_dtype=acc_dtype,
            expand=expand, fold=fold,
        )
        in_specs.append(pl.BlockSpec((p, p * w), lambda i: (0, 0)))
        operands.append(F)
    else:
        kernel = functools.partial(
            _kernel, w=w, k=k, p=p, acc_dtype=acc_dtype, expand=expand,
            fold=fold,
        )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (out_rows, m), out_dtype if fold else jnp.int32
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((out_rows, tile), lambda i: (0, i)),
        interpret=interpret,
    )(*operands)


# refold="autotune" decisions, keyed by the full dispatch configuration
# (shapes + dtypes + kernel config).  The w16 bimodality evidence
# (w16_bimodal_t*_tpu_20260801T*) shows the dot refold's fast/slow mode is
# fixed at (re)compile time — every slow reading was a best-of-trials
# WITHIN one process — so one timed calibration per compiled shape class
# is sound for the process lifetime: XLA's jit cache keeps that exact
# compilation alive, and a new shape class gets its own calibration.
# Writes go through _AUTOTUNE_LOCK (concurrent codec threads can race the
# same cold key; the worst pre-lock case was a benign duplicate
# calibration — the lock also makes the read accessors consistent).
# External readers use autotune_decisions(), never the dict itself.
_AUTOTUNE_CACHE: dict = {}
_AUTOTUNE_LOCK = threading.Lock()


def autotune_decisions() -> dict:
    """Snapshot of the refold='autotune' calibration results, keyed by
    dispatch configuration (shapes, dtypes, w, tile, acc_dtype, expand,
    interpret) with values "sum"/"dot".  The supported read surface for
    tools and benches (tools/w16_bench.py) — the backing dict is private
    and lock-guarded."""
    with _AUTOTUNE_LOCK:
        return dict(_AUTOTUNE_CACHE)


def clear_autotune_cache() -> None:
    """Drop every calibration decision.  Pair with ``jax.clear_caches()``:
    a decision is only sound while the executable it timed stays alive in
    XLA's jit cache — after an eviction the next compile re-flips the w16
    fast/slow coin while a stale pinned "dot" would silently re-expose the
    slow mode (ADVICE r5 finding 2).  Also invoked by the execution-plan
    cache's clear() (plan.PLAN_CACHE), which pins refold choices into AOT
    executables the same way."""
    with _AUTOTUNE_LOCK:
        _AUTOTUNE_CACHE.clear()

# Require a real win before preferring the variable mode: ties and noise
# go to the stable "sum".  The measured gap is wide on both sides (dot
# fast 132-147 vs sum ~102 vs dot slow 52-85 GB/s at w=16), so any
# margin in (0.7, 1.0) separates the modes; 0.9 leaves room for tunnel
# dispatch jitter.
_AUTOTUNE_MARGIN = 0.9


def _time_refold(run) -> float:
    """Best-of-2 wall time of ``run()`` after a compile/warm-up call.

    Separated out so tests can monkeypatch deterministic timings; the
    warm-up call also surfaces Mosaic lowering failures before anything
    is timed.
    """
    import time

    jax.block_until_ready(run())
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        best = min(best, time.perf_counter() - t0)
    return best


def _autotune_refold(A, B, w, tile, acc_dtype, interpret, expand) -> str:
    """Resolve ``refold="autotune"`` to "sum" or "dot" by timing both
    compiled kernels once on the actual operands.

    Motivated by w=16, where the dot refold is bimodal ACROSS compiles
    (remote-toolchain compile nondeterminism, not tile- or dispatch-
    dependent — see the module docstring) so no static default can ship
    its 132-147 GB/s fast mode safely; a per-process calibration can:
    whichever mode this process compiled is the mode every subsequent
    same-shape dispatch reuses.  Worst case (slow-mode compile or a dot
    lowering failure) the choice falls back to the stable "sum", so the
    floor is the static default's throughput minus one calibration.
    """
    key = (
        tuple(A.shape), str(A.dtype), tuple(B.shape), str(B.dtype), w, tile,
        str(acc_dtype), expand, interpret,
    )
    with _AUTOTUNE_LOCK:
        hit = _AUTOTUNE_CACHE.get(key)
    if hit is not None:
        return hit
    times = {}
    for cand in ("sum", "dot"):
        try:
            times[cand] = _time_refold(
                lambda: _pallas_matmul(
                    A, B, w, tile, acc_dtype, interpret, expand,
                    fold=True, refold=cand,
                )
            )
        except Exception as e:
            # Narrow handling (the codec's stated philosophy, codec.py:31):
            # only a backend/Mosaic failure means "this variant can't run
            # here" and loses the race; a ValueError/TypeError is a
            # programming bug and must propagate — silently caching 'sum'
            # over it would mask a dot-specific code bug with no signal
            # (ADVICE r5 finding 1).  If BOTH variants fail with backend
            # errors the caller's normal dispatch raises through the
            # existing Mosaic-failure fallback.
            from .. import codec as _codec

            if not isinstance(e, _codec._pallas_failure_types()):
                raise
            times[cand] = float("inf")
    choice = (
        "dot"
        if times["dot"] < _AUTOTUNE_MARGIN * times["sum"]
        else "sum"
    )
    _obs_metrics.counter(
        "rs_pallas_autotune_total",
        "refold autotune calibrations by winning candidate",
    ).labels(choice=choice, mode="eager", w=w).inc()
    with _AUTOTUNE_LOCK:
        # First writer wins: a thread that raced the same cold key already
        # proved its (identical) choice; keep the cache write-once per key.
        return _AUTOTUNE_CACHE.setdefault(key, choice)


def _default_refold(w: int) -> str:
    """The static per-width refold default: "dot" at w=8 (wins every
    probed shape — expand_r4b/r4c captures), "sum" elsewhere (at w=16
    dot is a compile-time coin flip; see the module docstring).  One
    definition shared by the env-fallback, pre-parity and tracer-guard
    resolution paths."""
    return "dot" if w == 8 else "sum"


def static_refold(w: int) -> str | None:
    """RS_PALLAS_REFOLD resolved to a static "sum"/"dot" with NO warning:
    "autotune" (and unknown values) map to the per-width default.  For
    dispatch sites that always run under a jit/shard_map trace — the mesh
    cols-sharded path — where calibration is impossible by construction
    and the tracer-guard's 'cannot calibrate' warning would fire on every
    trace, false-alarming the verify skill's warning check on perfectly
    healthy mesh runs (ADVICE r5 finding 3).  Returns ``None`` when the
    expand env resolves to pack2 (its fixed packed-refold pipeline REJECTS
    an explicit refold; the pack2 path returns before any refold env read,
    so ``None`` is both required and warning-safe there).  An UNKNOWN env
    value keeps the module's warn-and-fall-back hygiene — only the
    documented "autotune"→default mapping is silent."""
    import os

    if os.environ.get("RS_PALLAS_EXPAND") == "pack2" and w == 8:
        return None
    env = os.environ.get("RS_PALLAS_REFOLD")
    if env in ("sum", "dot"):
        return env
    if env and env != "autotune":
        return _env_fallback(
            f"RS_PALLAS_REFOLD={env!r} is unknown", _default_refold(w)
        )
    return _default_refold(w)


def plan_refold_resolution(w: int) -> str | None:
    """The refold an AOT execution plan (plan.ExecutionPlan) should bake:
    every non-calibrating case delegates to :func:`static_refold` (env
    pass-through, typo fallback, pack2's ``None`` — the mesh path and AOT
    plans must never bake DIFFERENT resolutions of the same env), while
    ``"autotune"`` is returned AS the string ``"autotune"`` so the plan
    calibrates against its OWN executables via
    :func:`calibrate_aot_refold` — the eager path's cached decision
    described a different compile, and dot speed at w=16 is per-compile
    bimodal (see the module docstring), so inheriting it would silently
    re-expose the slow mode the calibration exists to avoid."""
    import os

    # Derive, don't duplicate, static_refold's pack2 gate: a None static
    # resolution means pack2 applies and refold must stay unset — only a
    # refold-bearing pipeline may escalate to per-plan calibration.
    s = static_refold(w)
    if s is not None and os.environ.get("RS_PALLAS_REFOLD") == "autotune":
        return "autotune"
    return s


def calibrate_aot_refold(A, B, w, compile_variant):
    """Resolve ``refold="autotune"`` for one AOT plan build by timing the
    two candidates AS COMPILED BY THE CALLER on the actual operands —
    ``compile_variant(refold)`` must return the plan's own compiled
    executable for that refold.  Returns ``(choice, executable)`` so the
    winner's compile is not repeated.  The eager decision cache is
    deliberately NOT consulted or written: each decision is only sound
    for the executable it timed."""
    from .. import codec as _codec

    times, exes = {}, {}
    for cand in ("sum", "dot"):
        try:
            exe = compile_variant(cand)
            times[cand] = _time_refold(lambda: exe(A, B))
            exes[cand] = exe
        except Exception as e:
            # Same narrow handling as _autotune_refold: backend/Mosaic
            # failures lose the race, programming bugs propagate.
            if not isinstance(e, _codec._pallas_failure_types()):
                raise
            times[cand] = float("inf")
    choice = (
        "dot"
        if times["dot"] < _AUTOTUNE_MARGIN * times["sum"]
        else "sum"
    )
    _obs_metrics.counter(
        "rs_pallas_autotune_total",
        "refold autotune calibrations by winning candidate",
    ).labels(choice=choice, mode="aot", w=w).inc()
    if choice not in exes:
        # Both candidates failed to compile: surface the failure through
        # the caller's normal dispatch guard by compiling the default.
        return choice, compile_variant(choice)
    return choice, exes[choice]


def _default_expand(w: int, acc_dtype) -> str:
    """The production default that APPLIES at this (w, acc_dtype):
    shift_raw (faster at every probed shape — expand_r4b_*/expand_r4c_*
    captures), except w=16 with an explicitly non-int8 accumulator, where
    shift_raw's unmasked 16-bit planes would exceed bf16's exact-integer
    range and the masked shift formulation is the production choice."""
    if w == 16 and acc_dtype is not None and acc_dtype != jnp.int8:
        return "shift"
    return "shift_raw"


def _env_fallback(reason: str, to, label: str | None = None):
    """Warn-and-fall-back hygiene shared by every RS_PALLAS_* env knob
    (EXPAND / REFOLD / TILE): an env value that is unknown or inapplicable
    must neither crash production nor silently record a capture under a
    non-default configuration — the fallback target is the production
    default that applies, named in one uniformly-worded warning."""
    import warnings

    warnings.warn(f"{reason}; using {label or repr(to)}", stacklevel=3)
    return to


def gf_matmul_pallas(
    A,
    B,
    w: int = 8,
    tile: int | None = None,
    acc_dtype=None,
    interpret: bool | None = None,
    expand: str | None = None,
    fold_parity: bool = True,
    refold: str | None = None,
):
    """``C = A . B`` over GF(2^w) via the fused Pallas kernel.

    ``fold_parity=False`` returns the raw (p*w, m) int32 bit-plane
    accumulators instead of folded GF elements — the pre-parity form a
    stripe-sharded caller psums across devices before folding with
    :func:`..gemm.from_bitplanes` (XOR == total sum mod 2 must be taken
    AFTER the cross-device reduction).

    ``acc_dtype``: matmul input dtype — ``int8`` (int32 accumulation, exact
    for contraction depth < 2^31; 2x MXU rate on v5e) or ``bfloat16`` (f32
    accumulation, exact for depth < 2^24).  Both bit-verified; the TPU
    default is int8 @ tile 16384 at EVERY depth — the post-flip k-sweep
    (k_sweep_postflip_tpu_20260801T002730Z.jsonl) retired the old
    bf16-at-depth>=256 split: under shift_raw+dot, int8 wins at every k
    (152.5-167.4 GB/s at k=32-128 vs bf16's 119-140) and int8@32768
    fails to compile at depth 1024.
    ``expand``: data-expansion formulation — "shift_raw" (default; any
    width, but w=16 needs acc_dtype=int8 — unmasked planes exceed bf16's
    exact-integer range, so a w=16 call with an explicit non-int8
    acc_dtype defaults to "shift" instead), "shift" (any width), "sign"
    (w=8/16), or the
    byte-granular set "nibble"/"nibble_const"/"nibble32"/"packed32"/
    "sign16"/"shift_u8"/"pack2" (w=8 only; the nibble family one-hots
    against the (p*w, k*32) operator; see module docstring).  "pack2" additionally
    requires fold_parity=True and runs a fixed f32/packed-refold pipeline
    (passing acc_dtype or refold with it raises); contractions deeper than
    k*w < 256 split into carry-free depth slices XORed together.  On the
    current TPU toolchain only "shift"/"shift_raw"/"pack2" lower to
    hardware — pack2 correctly only under Precision.HIGHEST, whose cost
    sinks it to 2.4 GB/s (rejected; see module docstring).  "nibble32"
    (the nibble one-hot in int32 lanes, the lowerable lane width) is
    hardware-REFUSED too: it crashes the remote tpu_compile_helper
    (HTTP 500, nibble32_k10_tpu_20260801T002533Z.jsonl), the same wall
    as every r4 narrow-lane candidate; it and the remaining modes fail
    on hardware (bench_captures/expand_probe_*) and serve interpret
    mode only.
    ``refold``: how the kernel folds accumulator parities back into GF
    elements — "dot" (MXU: one tiny bf16 matmul against the (p, p*w)
    bit-weight operator; exact in f32 for any supported w) or "sum"
    (VPU: bits << s summed over w), or "autotune" — time both compiled
    variants once on the actual operands and cache the winner per shape
    class (ties/noise go to "sum"; intended for w=16, where the dot
    refold's speed is a compile-time coin flip — see _autotune_refold).
    Default: "dot" at w=8 (the width the captures validate), "sum"
    elsewhere until a width-specific capture lands.  Env-overridable via
    RS_PALLAS_REFOLD.
    ``interpret`` defaults to True off-TPU so the same code path runs under
    the CPU test mesh.
    """
    _BYTE_ONLY = (
        "nibble", "nibble_const", "nibble32", "packed32", "sign16",
        "shift_u8", "pack2",
    )
    _ANY_W = ("shift", "shift_raw")
    from_env = False
    if expand is None:
        # Production default, overridable for whole-pipeline hardware
        # experiments (e.g. RS_PALLAS_EXPAND=packed32 python bench.py)
        # without touching call sites; the literal default only changes
        # with a committed capture justifying it.  An env value that is
        # unknown or inapplicable at this width falls back WITH a warning
        # to the production default that applies (_default_expand) — an
        # env typo must neither crash production nor silently record a
        # capture under a non-default formulation.
        import os

        env = os.environ.get("RS_PALLAS_EXPAND")
        from_env = bool(env)
        if from_env:
            expand = env
            applies = expand in _ANY_W + ("sign",) + _BYTE_ONLY and (
                expand in _ANY_W or w == 8 or (w == 16 and expand == "sign")
            )
            if not applies:
                expand = _env_fallback(
                    f"RS_PALLAS_EXPAND={expand!r} is unknown or does not "
                    f"apply at w={w}",
                    _default_expand(w, acc_dtype),
                )
        else:
            # The measured production default (shift_raw beat shift at
            # every probed shape — expand_r4b_*/expand_r4c_* captures,
            # 2026-07-31: k10 60.0 vs 44.1, k64 119.4 vs 100.5, p=k=10
            # 48.4 vs 45.6, +dot k10 102.5 vs 82.8); at w=16 with an
            # explicit non-int8 acc_dtype this silently selects "shift"
            # rather than raise over a parameter the caller never passed.
            expand = _default_expand(w, acc_dtype)
    if expand not in _ANY_W + ("sign",) + _BYTE_ONLY:
        raise ValueError(f"unknown expand {expand!r}")
    if expand == "sign" and w not in (8, 16):
        raise ValueError(
            f"expand='sign' needs a lane-width field (w=8 or 16), got w={w}; "
            "use expand='shift' for other widths"
        )
    if expand in _BYTE_ONLY and w != 8:
        raise ValueError(
            f"expand={expand!r} is a GF(2^8) (byte-granular) strategy, "
            f"got w={w}"
        )
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    # Python-level entries: eager dispatches plus one per jit/AOT trace
    # (inside a trace this runs once per compile, so the counter reads as
    # "kernel builds + eager dispatches", labeled by call context).
    _obs_metrics.counter(
        "rs_pallas_gemm_calls_total",
        "gf_matmul_pallas entries (eager dispatches + compile traces)",
    ).labels(
        w=w,
        traced=isinstance(A, jax.core.Tracer) or isinstance(B, jax.core.Tracer),
    ).inc()
    if expand == "pack2" and not fold_parity:
        # The pre-parity (stripe-psum) form cannot be emitted: the
        # accumulator lanes hold two packed 8-bit parity fields, not the
        # per-column bit-plane accumulators from_bitplanes expects.
        why = "pack2 cannot emit pre-parity accumulators"
        if from_env:
            expand = _env_fallback(
                f"RS_PALLAS_EXPAND=pack2 does not apply here ({why})",
                _default_expand(w, acc_dtype),
            )
        else:
            raise ValueError(why)
    if interpret is None:
        # Device-platform check, not backend name: a tunnel backend serving
        # real TPU chips must compile, not interpret (utils/backend.py).
        from ..utils.backend import tpu_devices_present

        interpret = not tpu_devices_present()
    if tile is None:
        # RS_PALLAS_TILE: whole-pipeline tile experiments without touching
        # call sites (the CLI's -p cannot reach the kernel tile — it sizes
        # segments; this knob is the actual gridDim.x-cap analog of the
        # reference's -p, encode.cu:348-355).  Same warn-and-fall-back
        # hygiene as RS_PALLAS_EXPAND/REFOLD; an explicit argument wins.
        import os

        env = os.environ.get("RS_PALLAS_TILE")
        if env:
            try:
                tile = int(env)
                if tile <= 0:
                    raise ValueError(env)
            except ValueError:
                tile = _env_fallback(
                    f"RS_PALLAS_TILE={env!r} is not a positive integer",
                    None, label="the measured default",
                )
            if tile is not None and tile % 128:
                # TPU blocks must be lane-aligned; a misaligned tile
                # would fail Mosaic lowering and silently demote every
                # dispatch to the bitplane path.  Round up, warn — the
                # same warn-and-continue hygiene as the other env knobs.
                aligned = ((tile + 127) // 128) * 128
                import warnings

                warnings.warn(
                    f"RS_PALLAS_TILE={tile} is not a multiple of the "
                    f"128-lane width; rounding up to {aligned}",
                    stacklevel=2,
                )
                tile = aligned
    if tile is None:
        tile = DEFAULT_TILE if interpret else TPU_TILE
    acc_explicit = acc_dtype is not None
    if acc_dtype is None:
        if expand == "shift_raw" and w == 16:
            acc_dtype = jnp.int8
        else:
            acc_dtype = jnp.bfloat16 if interpret else jnp.int8
    if expand == "shift_raw" and w == 16 and acc_dtype != jnp.int8:
        # Unmasked 16-bit planes reach 65535; bf16 represents integers
        # exactly only up to 2^8, so rounding would corrupt the parity.
        # (int8 wraps mod 256 — even, parity-safe; w<=8 planes are <=255
        # and exact in bf16.)  Env-selected modes keep the warn-and-fall-
        # back guarantee instead of crashing production.
        if from_env:
            expand = _env_fallback(
                "RS_PALLAS_EXPAND=shift_raw needs acc_dtype=int8 at w=16",
                _default_expand(w, acc_dtype),
            )
        else:
            raise ValueError(
                "expand='shift_raw' at w=16 requires acc_dtype=int8"
            )
    if expand == "pack2":
        # Self-contained path: f32 accumulation (exact; fields < 256) and
        # the packed shift-sum refold.  Explicit acc_dtype/refold must not
        # be silently ignored — a probe capture would be labeled with a
        # configuration that never ran.
        if acc_explicit or refold is not None:
            raise ValueError(
                "pack2 has a fixed f32/packed-refold pipeline; "
                "acc_dtype and refold do not apply"
            )
        k_all = A.shape[1]
        k_c = (256 // w) - 1  # per-slice depth k_c*w <= 248 < 256
        if k_all <= k_c:
            return _pallas_matmul_pack2(A, B, w, tile, interpret)
        # Split-k: the packed parity fields are only carry-free below
        # depth 256, so deeper contractions run as ceil(k/k_c) carry-free
        # slices XORed together (XOR is the field addition, so slicing the
        # contraction is exact).  Each slice reads only its own k rows —
        # total input traffic is unchanged; the extra cost is the (p, m)
        # slice outputs and their XORs, cheap while p << k and affordable
        # even at p = k (HBM has 4x headroom over the measured kernel).
        out = None
        for c0 in range(0, k_all, k_c):
            part = _pallas_matmul_pack2(
                A[:, c0:c0 + k_c], B[c0:c0 + k_c], w, tile, interpret
            )
            out = part if out is None else out ^ part
        return out
    if refold is None:
        # Env override for whole-pipeline hardware experiments, mirroring
        # RS_PALLAS_EXPAND; an explicit refold argument always wins.
        import os

        # "dot" (MXU parity refold) is the measured production default at
        # w=8: it lowers after the int32 cast-chain fix and wins at every
        # probed w=8 shape — k64 132.0 vs 119.4, decode p=k=10 80.5 vs
        # 48.4, headline k10 102.5 vs 60.0 (expand_r4b_*dot/
        # expand_r4c_*dot captures, 2026-07-31).  w=16 stays on "sum":
        # dot there is BIMODAL at fixed shape (82-148 GB/s across runs
        # at mb=128) where sum is stable at ~102
        # (w16_cross_*_tpu_20260801T* — and the r4c "hang" was the
        # tunnel, both re-probes rc=0); a default that can regress
        # below the stable alternative on half its dispatches does not
        # ship.  RS_PALLAS_REFOLD=dot opts in.
        default_refold = _default_refold(w)
        refold = os.environ.get("RS_PALLAS_REFOLD") or default_refold
        if refold not in ("sum", "dot", "autotune"):
            refold = _env_fallback(
                f"RS_PALLAS_REFOLD={refold!r} is unknown", default_refold
            )
    if refold not in ("sum", "dot", "autotune"):
        raise ValueError(f"unknown refold {refold!r}")
    if refold == "autotune":
        if not fold_parity:
            # The pre-parity (stripe-psum) form has no refold stage to
            # tune — the fold happens host-side after the collective.
            refold = _default_refold(w)
        elif isinstance(A, jax.core.Tracer) or isinstance(B, jax.core.Tracer):
            # Inside a caller's jit trace the operands are tracers:
            # block_until_ready is a no-op there, so "timing" would
            # measure per-variant TRACE overhead and cache that garbage
            # decision for every later eager call of the same shape.
            # Calibration needs concrete arrays — fall back to the
            # static per-width default with the module's usual warning.
            refold = _env_fallback(
                "refold='autotune' cannot calibrate under a jit trace "
                "(operands are tracers); call the pallas path eagerly "
                "to calibrate",
                _default_refold(w),
            )
        else:
            refold = _autotune_refold(
                A, B, w, tile, acc_dtype, interpret, expand
            )
    _obs_metrics.counter(
        "rs_pallas_refold_total",
        "resolved refold choices at kernel dispatch/trace time",
    ).labels(refold=refold, expand=expand, w=w).inc()
    return _pallas_matmul(
        A, B, w, tile, acc_dtype, interpret, expand, fold=fold_parity,
        refold=refold,
    )
